"""Neural-network layer ops (the reference's `src/operator/*-inl.h` corpus).

Each op is a pure JAX function over jnp/lax; layout is NCHW to match the
reference default.  Convs and matmuls are expressed with
``lax.conv_general_dilated`` / ``jnp.dot`` so XLA tiles them onto the MXU;
elementwise pieces are left for XLA to fuse.

Reference citations per op are in each docstring.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .registry import register


def _pair(v, n=2):
    if isinstance(v, (tuple, list)):
        t = tuple(int(x) for x in v)
        return t if len(t) == n else t * n if len(t) == 1 else t
    return (int(v),) * n


# ----------------------------------------------------------------- layout
# Global image-layout mode for the conv/pool/batchnorm family.  The symbol
# graphs are written against the reference's NCHW convention; on TPU the
# MXU/vector units want the channel dim minor (NHWC), so the performant
# path (ShardedTrainer(layout="NHWC")) activates this flag *at trace time*
# and feeds NHWC activations end-to-end instead of paying per-op
# transposes.  Weights keep the reference OIHW layout (cheap per-step
# transpose, preserves checkpoint compatibility).
_IMAGE_LAYOUT = "NCHW"


class image_layout:
    """Context manager selecting the activation layout ('NCHW'/'NHWC')
    seen by Convolution/Pooling/BatchNorm during tracing."""

    def __init__(self, layout):
        if layout not in ("NCHW", "NHWC"):
            raise MXNetError("unsupported image layout %r" % (layout,))
        self.layout = layout

    def __enter__(self):
        global _IMAGE_LAYOUT
        self._prev = _IMAGE_LAYOUT
        _IMAGE_LAYOUT = self.layout
        return self

    def __exit__(self, *exc):
        global _IMAGE_LAYOUT
        _IMAGE_LAYOUT = self._prev
        return False


def current_image_layout():
    return _IMAGE_LAYOUT


def _is_nhwc(data):
    """True when a 4-d activation flows channel-minor (trainer NHWC mode)."""
    return data.ndim == 4 and _IMAGE_LAYOUT == "NHWC"


def _ch_axis(data):
    return 3 if _is_nhwc(data) else 1


# Ops that index the channel axis but have no NHWC adaptation; a trainer in
# NHWC mode refuses graphs containing them rather than silently computing on
# the wrong axis.  Extend this list when adding channel-sensitive ops.
NHWC_UNAWARE_OPS = frozenset({
    "SwapAxis", "SpatialTransformer", "BilinearSampler", "GridGenerator",
    "ROIPooling", "Correlation", "Proposal", "MultiBoxPrior",
    "MultiBoxTarget", "MultiBoxDetection",
})


def _mxu_out(y):
    """Name MXU-op outputs for the remat policy: under
    MXNET_BACKWARD_DO_MIRROR the backward pass saves exactly these and
    recomputes everything else (BN/activation), the reference's mirroring
    split (graph_executor.cc:218-231).  Identity outside jax.checkpoint."""
    from jax.ad_checkpoint import checkpoint_name
    return checkpoint_name(y, "mxu_out")


def maybe_mirror(f):
    """MXNET_BACKWARD_DO_MIRROR=1 -> rematerialized backward (reference
    graph_executor.cc:218-231 mirroring): wrap a traced forward in
    jax.checkpoint saving only the MXU-op outputs tagged by
    :func:`_mxu_out`, so BN statistics, activations and other elementwise
    intermediates are recomputed in the backward pass instead of living
    in HBM across it — the 30-50% activation-memory trade the reference
    documents (docs/how_to/env_var.md:64-66; measurements: docs/perf.md).
    Used by the executor backward/fused paths and ShardedTrainer."""
    from .. import config
    if not config.get_bool("MXNET_BACKWARD_DO_MIRROR"):
        return f
    import jax
    policy = jax.checkpoint_policies.save_only_these_names("mxu_out")
    return jax.checkpoint(f, policy=policy)


# --------------------------------------------------------------------- dense
@register("FullyConnected", arg_names=lambda a: ("data", "weight") if a["no_bias"]
          else ("data", "weight", "bias"),
          params={"num_hidden": 0, "no_bias": False, "flatten": True},
          aliases=("fully_connected",))
def fully_connected(attrs, ctx, data, weight, bias=None):
    """Y = X W^T + b.  Reference: src/operator/fully_connected-inl.h:48-145."""
    if attrs["flatten"]:
        x = data.reshape((data.shape[0], -1))
    else:
        x = data
    # the TPU MXU accumulates bf16 dots in f32 natively; no upcast
    # annotation (preferred_element_type breaks the conv/dot transpose rule)
    y = jnp.dot(x, weight.T)
    if bias is not None:
        y = y + bias
    return _mxu_out(y.astype(data.dtype))


# ---------------------------------------------------------------------- conv
@register("Convolution", arg_names=lambda a: ("data", "weight") if a["no_bias"]
          else ("data", "weight", "bias"),
          params={"kernel": (1, 1), "stride": (), "dilate": (), "pad": (),
                  "num_filter": 0, "num_group": 1, "no_bias": False,
                  "workspace": 1024, "cudnn_tune": None, "cudnn_off": False,
                  "layout": None},
          aliases=("convolution", "Convolution_v1"))
def convolution(attrs, ctx, data, weight, bias=None):
    """N-d convolution, NCHW/NCW/NCDHW.  Reference: src/operator/convolution-inl.h:103-325.

    Weight layout (num_filter, C/group, *kernel) as in the reference; lowered
    to one lax.conv_general_dilated so XLA maps it onto the MXU.
    """
    kernel = tuple(attrs["kernel"])
    nd = len(kernel)
    stride = tuple(attrs["stride"]) or (1,) * nd
    dilate = tuple(attrs["dilate"]) or (1,) * nd
    pad = tuple(attrs["pad"]) or (0,) * nd
    layout = attrs.get("layout") or _IMAGE_LAYOUT
    if attrs.get("layout") and _IMAGE_LAYOUT == "NHWC" \
            and attrs["layout"] != "NHWC":
        raise MXNetError(
            "Convolution node pins layout=%r but the trainer runs "
            "image_layout('NHWC'); drop the explicit layout attr or train "
            "in NCHW" % (attrs["layout"],))
    if nd == 2 and layout == "NHWC":
        # activations NHWC, weight kept reference-OIHW -> HWIO view
        dn = lax.conv_dimension_numbers(
            data.shape, weight.shape[2:] + weight.shape[1:2] + weight.shape[:1],
            ("NHWC", "HWIO", "NHWC"))
        w = jnp.transpose(weight, (2, 3, 1, 0))
        from .fused import (phase_bwd_enabled, phase_bwd_eligible,
                            phase_bwd_conv_nhwc, conv1x1_dot_enabled,
                            conv1x1_as_dot)
        if conv1x1_dot_enabled() and kernel == (1, 1) \
                and stride == (1, 1) and tuple(pad) == (0, 0) \
                and dilate == (1, 1) and int(attrs["num_group"]) == 1:
            # pointwise conv lowered as a fusible dot (ops/fused.py)
            y = conv1x1_as_dot(data, w)
        elif phase_bwd_enabled() and phase_bwd_eligible(
                data.shape, kernel, stride, pad, dilate,
                attrs["num_group"]):
            # stride-2 conv with phase-decomposed backward-data
            # (ops/fused.py — removes the 4x lhs-dilation MAC waste)
            y = phase_bwd_conv_nhwc(data, w,
                                    tuple((p, p) for p in pad))
        else:
            y = lax.conv_general_dilated(
                data, w, window_strides=stride,
                padding=[(p, p) for p in pad], rhs_dilation=dilate,
                dimension_numbers=dn,
                feature_group_count=int(attrs["num_group"]))
        if bias is not None:
            y = y + bias
        return _mxu_out(y.astype(data.dtype))
    dn = lax.conv_dimension_numbers(
        data.shape, weight.shape,
        ("NCHW", "OIHW", "NCHW") if nd == 2 else
        ("NCW", "OIW", "NCW") if nd == 1 else ("NCDHW", "OIDHW", "NCDHW"))
    y = lax.conv_general_dilated(
        data, weight, window_strides=stride,
        padding=[(p, p) for p in pad], rhs_dilation=dilate,
        dimension_numbers=dn, feature_group_count=int(attrs["num_group"]))
    if bias is not None:
        y = y + bias.reshape((1, -1) + (1,) * nd)
    return _mxu_out(y.astype(data.dtype))


@register("Deconvolution", arg_names=lambda a: ("data", "weight") if a["no_bias"]
          else ("data", "weight", "bias"),
          params={"kernel": (1, 1), "stride": (), "dilate": (), "pad": (),
                  "adj": (), "target_shape": (), "num_filter": 0,
                  "num_group": 1, "no_bias": True, "workspace": 512,
                  "cudnn_tune": None, "cudnn_off": False, "layout": None})
def deconvolution(attrs, ctx, data, weight, bias=None):
    """Transposed convolution.  Reference: src/operator/deconvolution-inl.h.

    Implemented as conv_general_dilated with lhs_dilation (the XLA-native
    formulation of conv-transpose).  Weight layout (C_in, C_out/group, *k).
    """
    kernel = tuple(attrs["kernel"])
    nd = len(kernel)
    stride = tuple(attrs["stride"]) or (1,) * nd
    pad = tuple(attrs["pad"]) or (0,) * nd
    adj = tuple(attrs["adj"]) or (0,) * nd
    groups = int(attrs["num_group"])
    # flip spatial dims and swap in/out channels -> direct conv on dilated input
    w = jnp.flip(weight, axis=tuple(range(2, 2 + nd)))
    if groups == 1:
        w = jnp.swapaxes(w, 0, 1)
    else:
        ci, co = weight.shape[0], weight.shape[1]
        w = w.reshape((groups, ci // groups, co) + kernel)
        w = jnp.swapaxes(w, 1, 2).reshape((groups * co, ci // groups) + kernel)
    padding = [(kernel[i] - 1 - pad[i], kernel[i] - 1 - pad[i] + adj[i])
               for i in range(nd)]
    if nd == 2 and _is_nhwc(data):
        dn = lax.conv_dimension_numbers(
            data.shape, w.shape[2:] + w.shape[1:2] + w.shape[:1],
            ("NHWC", "HWIO", "NHWC"))
        y = lax.conv_general_dilated(
            data, jnp.transpose(w, (2, 3, 1, 0)),
            window_strides=(1, 1), padding=padding,
            lhs_dilation=stride, dimension_numbers=dn,
            feature_group_count=groups)
        if bias is not None:
            y = y + bias
        return y.astype(data.dtype)
    dn = lax.conv_dimension_numbers(
        data.shape, w.shape,
        ("NCHW", "OIHW", "NCHW") if nd == 2 else
        ("NCW", "OIW", "NCW") if nd == 1 else ("NCDHW", "OIDHW", "NCDHW"))
    y = lax.conv_general_dilated(
        data, w, window_strides=(1,) * nd, padding=padding,
        lhs_dilation=stride, dimension_numbers=dn,
        feature_group_count=groups)
    if bias is not None:
        y = y + bias.reshape((1, -1) + (1,) * nd)
    return y.astype(data.dtype)


# ------------------------------------------------------------------- pooling
@register("Pooling",
          params={"kernel": (1, 1), "pool_type": "max", "global_pool": False,
                  "stride": (), "pad": (), "pooling_convention": "valid",
                  "cudnn_off": False},
          aliases=("pooling", "Pooling_v1"))
def pooling(attrs, ctx, data):
    """Max/avg/sum pooling via lax.reduce_window.

    Reference: src/operator/pooling-inl.h (+pooling.cc registration).
    """
    nd = data.ndim - 2
    nhwc = nd == 2 and _IMAGE_LAYOUT == "NHWC"
    sp0 = 1 if nhwc else 2  # first spatial axis
    if attrs["global_pool"]:
        kernel = data.shape[sp0:sp0 + nd]
        stride = (1,) * nd
        pad = (0,) * nd
    else:
        kernel = _pair(attrs["kernel"], nd)
        # reference defaults stride to 1 (pooling-inl.h), NOT to the kernel
        stride = tuple(attrs["stride"]) or (1,) * nd
        pad = tuple(attrs["pad"]) or (0,) * nd
    conv = attrs.get("pooling_convention", "valid")
    spatial_pad = []
    for i in range(nd):
        lo = hi = pad[i]
        if conv == "full":
            # ceil division convention: pad extra on the high side as needed
            in_sz = data.shape[sp0 + i] + 2 * pad[i]
            rem = (in_sz - kernel[i]) % stride[i]
            if rem:
                hi += stride[i] - rem
        spatial_pad.append((lo, hi))
    if nhwc:
        window = (1,) + tuple(kernel) + (1,)
        strides = (1,) + tuple(stride) + (1,)
        padding = [(0, 0)] + spatial_pad + [(0, 0)]
    else:
        window = (1, 1) + tuple(kernel)
        strides = (1, 1) + tuple(stride)
        padding = [(0, 0), (0, 0)] + spatial_pad
    ptype = attrs["pool_type"]
    # init values must be python literals (the identity element) so JAX's
    # reduce_window autodiff monoid pattern-match fires
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) \
            else int(jnp.iinfo(data.dtype).min)
        return lax.reduce_window(data, init, lax.max,
                                 window, strides, padding)
    zero = 0.0 if jnp.issubdtype(data.dtype, jnp.floating) else 0
    summed = lax.reduce_window(data, zero, lax.add,
                               window, strides, padding)
    if ptype == "sum":
        return summed
    if ptype == "avg":
        # reference divides by full window size (count_include_pad)
        wsize = 1
        for k in kernel:
            wsize *= k
        return (summed / wsize).astype(data.dtype)
    raise MXNetError(f"unknown pool_type {ptype}")


# ---------------------------------------------------------------- batch norm
@functools.lru_cache(maxsize=None)
def _bn_core(eps, momentum, train_stats, bshape_key):
    """Hand-scheduled BatchNorm fwd/bwd (custom_vjp).

    BN statistics are the #1 non-MXU cost in conv nets (they tie the convs
    in the ResNet-50 step profile), so the pass structure is explicit:
      fwd: ONE fused stats pass (sum, sum of squares -> mean, biased var),
           then one normalize pass as a single multiply-add per element.
      bwd: ONE fused reduce pass (sum dy, sum dy*x), then one dx pass
           (dx = a*dy + c*x + d with per-channel scalars).
    The jax-autodiff formulation of mean/var costs roughly twice these
    memory passes.  Reference kernel: src/operator/batch_norm-inl.h.
    """
    import jax as _jax

    bshape = tuple(bshape_key)
    red = tuple(i for i, s in enumerate(bshape) if s == 1)

    # mxlint: allow-dtype-widening(normalization/softmax statistics accumulate in f32 by contract)
    def fwd_math(x, gamma, beta, mm, mv):
        xf = x.astype(jnp.float32)
        if train_stats:
            n = 1
            for i in red:
                n *= x.shape[i]
            # single-pass sum/sum² stats, SHIFTED by the moving mean: for
            # any constant c, var = E[(x-c)²] - E[x-c]².  With c ≈ the true
            # mean (which the moving mean approaches) this avoids the
            # catastrophic f32 cancellation of the raw E[x²]-E[x]² form on
            # large-mean channels, while keeping one fused read of x.
            c = lax.stop_gradient(mm.astype(jnp.float32))
            xs = xf - c.reshape(bshape)
            s1 = jnp.sum(xs, axis=red)
            s2 = jnp.sum(jnp.square(xs), axis=red)
            meanc = s1 / n
            var = jnp.maximum(s2 / n - jnp.square(meanc), 0.0)
            mean = meanc + c
            new_mm = mm * momentum + mean * (1 - momentum)
            new_mv = mv * momentum + var * (1 - momentum)
        else:
            mean, var = mm.astype(jnp.float32), mv.astype(jnp.float32)
            new_mm, new_mv = mm, mv
        inv = lax.rsqrt(var + eps)
        scale = gamma.astype(jnp.float32) * inv
        shift = beta.astype(jnp.float32) - mean * scale
        out = (xf * scale.reshape(bshape) + shift.reshape(bshape))
        return (out.astype(x.dtype), mean, var, new_mm, new_mv), \
            (mean, inv, mm)

    @_jax.custom_vjp
    def bn(x, gamma, beta, mm, mv):
        return fwd_math(x, gamma, beta, mm, mv)[0]

    def bn_fwd(x, gamma, beta, mm, mv):
        outs, (mean, inv, mm_res) = fwd_math(x, gamma, beta, mm, mv)
        return outs, (x, gamma, mean, inv, mm_res)

    def bn_bwd(res, cots):
        x, gamma, mean, inv, mm = res
        dy, dmean_o, dvar_o, dmm_o, dmv_o = cots
        n = 1
        for i in red:
            n *= x.shape[i]
        dyf = dy.astype(jnp.float32)
        # same shifted formulation as forward (avoids cancellation in the
        # sum(dy*x) - mean*sum(dy) difference on large-mean channels)
        c = lax.stop_gradient(mm.astype(jnp.float32))
        xs = x.astype(jnp.float32) - c.reshape(bshape)
        meanc = mean - c
        dbeta = jnp.sum(dyf, axis=red)
        sdyxs = jnp.sum(dyf * xs, axis=red)
        dgamma = (sdyxs - meanc * dbeta) * inv  # = sum(dy * xhat)
        a = gamma.astype(jnp.float32) * inv
        if train_stats:
            # dx = (a/n)(n*dy - sum(dy) - xhat*sum(dy*xhat)), written as
            # a*dy + K*(x - mean) + const, plus the cotangent paths of the
            # explicit mean/var/moving outputs
            dmean = dmean_o + (1 - momentum) * dmm_o
            dvar = dvar_o + (1 - momentum) * dmv_o
            k = (-a * inv * dgamma + 2.0 * dvar) * (1.0 / n)
            d = -k * meanc - a * dbeta * (1.0 / n) + dmean * (1.0 / n)
            dx = (dyf * a.reshape(bshape) + xs * k.reshape(bshape)
                  + d.reshape(bshape))
            dmm = momentum * dmm_o
            dmv = momentum * dmv_o
        else:
            # eval/global-stats: moving stats are aux constants; the
            # normalize path into them is not differentiated (the
            # reference never backprops into moving stats)
            dx = dyf * a.reshape(bshape)
            dmm = dmm_o + dmean_o
            dmv = dmv_o + dvar_o
        return (dx.astype(x.dtype), dgamma.astype(gamma.dtype),
                dbeta.astype(gamma.dtype), dmm, dmv)

    bn.defvjp(bn_fwd, bn_bwd)
    return bn


@register("BatchNorm",
          arg_names=("data", "gamma", "beta"),
          aux_names=("moving_mean", "moving_var"),
          num_outputs=lambda a: 3 if a.get("output_mean_var") else 1,
          params={"eps": 1e-3, "momentum": 0.9, "fix_gamma": True,
                  "use_global_stats": False, "output_mean_var": False,
                  "axis": 1, "cudnn_off": False},
          aliases=("batch_norm", "BatchNorm_v1"))
# mxlint: allow-dtype-widening(normalization/softmax statistics accumulate in f32 by contract)
def batch_norm(attrs, ctx, data, gamma, beta, moving_mean, moving_var):
    """Batch normalization with functional aux-state threading.

    Reference: src/operator/batch_norm-inl.h / batch_norm.cc.  The reference
    mutates moving_{mean,var} aux states in forward during training; here the
    updated stats are returned as trailing outputs and threaded by the
    executor (SURVEY §7 'hard parts': aux state).
    Returns (out[, mean, var], new_moving_mean, new_moving_var).
    """
    axis = int(attrs["axis"])
    if axis == 1 and data.ndim == 4 and _IMAGE_LAYOUT == "NHWC":
        axis = 3  # NHWC mode: symbols declare the reference NCHW channel axis
    eps = float(attrs["eps"])
    momentum = float(attrs["momentum"])
    bshape = tuple(1 if i != axis else data.shape[axis]
                   for i in range(data.ndim))
    if attrs["fix_gamma"]:
        gamma = lax.stop_gradient(jnp.ones_like(gamma))
    train_stats = bool(ctx.is_train and not attrs["use_global_stats"])
    bn = _bn_core(eps, momentum, train_stats, bshape)
    out, mean, var, new_mm, new_mv = bn(data, gamma, beta,
                                        moving_mean.astype(jnp.float32),
                                        moving_var.astype(jnp.float32))
    new_mm = new_mm.astype(moving_mean.dtype)
    new_mv = new_mv.astype(moving_var.dtype)
    if attrs.get("output_mean_var"):
        return out, mean, var, new_mm, new_mv
    return out, new_mm, new_mv


@register("LayerNorm", arg_names=("data", "gamma", "beta"),
          num_outputs=lambda a: 3 if a.get("output_mean_var") else 1,
          params={"axis": -1, "eps": 1e-5, "output_mean_var": False})
# mxlint: allow-dtype-widening(normalization/softmax statistics accumulate in f32 by contract)
def layer_norm(attrs, ctx, data, gamma, beta):
    """Layer normalization over ``axis`` (the transformer workhorse;
    post-reference-era op — the 0.10.1 reference predates attention —
    kept API-compatible with mxnet's later LayerNorm)."""
    axis = int(attrs["axis"])
    eps = float(attrs["eps"])
    xf = data.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axis, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=axis, keepdims=True)
    inv = lax.rsqrt(var + eps)
    bshape = tuple(data.shape[axis] if i == (axis % data.ndim) else 1
                   for i in range(data.ndim))
    out = ((xf - mean) * inv * gamma.astype(jnp.float32).reshape(bshape)
           + beta.astype(jnp.float32).reshape(bshape)).astype(data.dtype)
    if attrs.get("output_mean_var"):
        # mxnet's LayerNorm(output_mean_var=True) returns (out, mean, std)
        return (out, jnp.squeeze(mean, axis),
                jnp.squeeze(jnp.sqrt(var + eps), axis))
    return out


@register("InstanceNorm", arg_names=("data", "gamma", "beta"),
          params={"eps": 1e-3})
def instance_norm(attrs, ctx, data, gamma, beta):
    """Reference: src/operator/instance_norm-inl.h."""
    ch = _ch_axis(data)
    red = tuple(i for i in range(1, data.ndim) if i != ch)
    mean = jnp.mean(data, axis=red, keepdims=True)
    var = jnp.var(data, axis=red, keepdims=True)
    bshape = tuple(-1 if i == ch else 1 for i in range(data.ndim))
    out = (data - mean) * lax.rsqrt(var + attrs["eps"])
    return out * gamma.reshape(bshape) + beta.reshape(bshape)


@register("L2Normalization", params={"eps": 1e-10, "mode": "instance"})
def l2_normalization(attrs, ctx, data):
    """Reference: src/operator/l2_normalization-inl.h."""
    mode = attrs["mode"]
    if mode == "instance":
        red = tuple(range(1, data.ndim))
        keep = True
    elif mode == "channel":
        red, keep = (1,), True
    elif mode == "spatial":
        red, keep = tuple(range(2, data.ndim)), True
    else:
        raise MXNetError(f"unknown mode {mode}")
    norm = jnp.sqrt(jnp.sum(jnp.square(data), axis=red, keepdims=keep)
                    + attrs["eps"])
    return data / norm


@register("LRN", params={"alpha": 1e-4, "beta": 0.75, "knorm": 2.0, "nsize": 5})
# mxlint: allow-dtype-widening(normalization/softmax statistics accumulate in f32 by contract)
def lrn(attrs, ctx, data):
    """Local response norm across channels.  Reference: src/operator/lrn-inl.h."""
    nsize = int(attrs["nsize"])
    ch = _ch_axis(data)
    sq = jnp.square(data.astype(jnp.float32))
    pre = nsize // 2
    post = nsize - pre - 1
    pads = [(0, 0)] * data.ndim
    pads[ch] = (pre, post)
    padded = jnp.pad(sq, pads)
    acc = sum(lax.slice_in_dim(padded, i, i + data.shape[ch], axis=ch)
              for i in range(nsize))
    scale = attrs["knorm"] + (attrs["alpha"] / nsize) * acc
    return (data * scale ** (-attrs["beta"])).astype(data.dtype)


# ------------------------------------------------------------- activations
@register("Activation", params={"act_type": "relu"}, aliases=("activation",))
def activation(attrs, ctx, data):
    """Reference: src/operator/activation-inl.h; functors mshadow_op.h."""
    t = attrs["act_type"]
    if t == "relu":
        return jax.nn.relu(data)
    if t == "sigmoid":
        return jax.nn.sigmoid(data)
    if t == "tanh":
        return jnp.tanh(data)
    if t == "softrelu":
        return jax.nn.softplus(data)
    if t == "softsign":
        return data / (1 + jnp.abs(data))
    raise MXNetError(f"unknown act_type {t}")


@register("LeakyReLU", arg_names=lambda a: ("data", "gamma")
          if a["act_type"] == "prelu" else ("data",),
          params={"act_type": "leaky", "slope": 0.25,
                  "lower_bound": 0.125, "upper_bound": 0.334},
          stochastic=lambda a: a["act_type"] == "rrelu")
def leaky_relu(attrs, ctx, data, gamma=None):
    """Reference: src/operator/leaky_relu-inl.h."""
    t = attrs["act_type"]
    if t == "leaky":
        return jnp.where(data > 0, data, data * attrs["slope"])
    if t == "prelu":
        ch = _ch_axis(data)
        g = gamma.reshape(tuple(-1 if i == ch else 1
                                for i in range(data.ndim)))
        return jnp.where(data > 0, data, data * g)
    if t == "elu":
        return jnp.where(data > 0, data, attrs["slope"] * (jnp.exp(data) - 1))
    if t == "rrelu":
        if ctx.is_train:
            lo, hi = attrs["lower_bound"], attrs["upper_bound"]
            slope = jax.random.uniform(ctx.require_key(),
                                       data.shape, data.dtype, lo, hi)
        else:
            slope = (attrs["lower_bound"] + attrs["upper_bound"]) / 2.0
        return jnp.where(data > 0, data, data * slope)
    raise MXNetError(f"unknown act_type {t}")


@register("Dropout", params={"p": 0.5, "mode": "training"}, stochastic=True,
          aliases=("dropout",))
def dropout(attrs, ctx, data):
    """Inverted dropout.  Reference: src/operator/dropout-inl.h."""
    p = float(attrs["p"])
    if not ctx.is_train or p <= 0.0:
        return data
    keep = 1.0 - p
    mask = jax.random.bernoulli(ctx.require_key(), keep, data.shape)
    return jnp.where(mask, data / keep, 0).astype(data.dtype)


# ------------------------------------------------------------------ softmax
# mxlint: allow-dtype-widening(normalization/softmax statistics accumulate in f32 by contract)
def _softmax(x, axis):
    return jax.nn.softmax(x.astype(jnp.float32), axis=axis).astype(x.dtype)


@register("softmax", params={"axis": -1, "temperature": None})
def softmax_op(attrs, ctx, data):
    """Reference: softmax in src/operator/nn-era tensor ops (softmax_output.cc kin)."""
    x = data
    if attrs.get("temperature"):
        x = x / attrs["temperature"]
    return _softmax(x, int(attrs["axis"]))


@register("log_softmax", params={"axis": -1})
# mxlint: allow-dtype-widening(normalization/softmax statistics accumulate in f32 by contract)
def log_softmax_op(attrs, ctx, data):
    return jax.nn.log_softmax(data.astype(jnp.float32),
                              axis=int(attrs["axis"])).astype(data.dtype)


@register("SoftmaxActivation", params={"mode": "instance"})
def softmax_activation(attrs, ctx, data):
    """Reference: src/operator/softmax_activation-inl.h."""
    if attrs["mode"] == "channel":
        return _softmax(data, _ch_axis(data))
    return _softmax(data.reshape((data.shape[0], -1)), -1).reshape(data.shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6))
def _softmax_output(data, label, grad_scale, multi_output, use_ignore,
                    ignore_label, normalization):
    axis = 1 if multi_output else -1
    return _softmax(data, axis)


def _softmax_output_fwd(data, label, grad_scale, multi_output, use_ignore,
                        ignore_label, normalization):
    out = _softmax_output(data, label, grad_scale, multi_output, use_ignore,
                          ignore_label, normalization)
    return out, (out, label)


def _softmax_output_bwd(grad_scale, multi_output, use_ignore, ignore_label,
                        normalization, res, g):
    # Reference backward (src/operator/softmax_output-inl.h): grad = p - onehot,
    # ignoring the incoming head gradient (it is a terminal loss op).
    out, label = res
    axis = 1 if multi_output else -1
    nclass = out.shape[axis]
    lab = label.astype(jnp.int32)
    onehot = jax.nn.one_hot(lab, nclass, dtype=jnp.float32, axis=axis)
    grad = out.astype(jnp.float32) - onehot
    valid = None
    if use_ignore:
        keep = (lab != int(ignore_label))
        keepb = jnp.expand_dims(keep, axis=axis)
        grad = grad * keepb
        valid = jnp.maximum(jnp.sum(keep), 1)
    scale = grad_scale
    if normalization == "batch":
        scale = scale / out.shape[0]
    elif normalization == "valid" and valid is not None:
        scale = scale / valid
    elif normalization == "valid":
        scale = scale / lab.size
    return (grad * scale).astype(out.dtype), jnp.zeros_like(label)


_softmax_output.defvjp(_softmax_output_fwd, _softmax_output_bwd)


@register("SoftmaxOutput", arg_names=("data", "label"),
          params={"grad_scale": 1.0, "ignore_label": -1.0, "multi_output": False,
                  "use_ignore": False, "preserve_shape": False,
                  "normalization": "null", "out_grad": False,
                  "smooth_alpha": 0.0},
          is_loss=True, aliases=("Softmax",))
def softmax_output(attrs, ctx, data, label):
    """Softmax forward + cross-entropy-style custom backward.

    Reference: src/operator/softmax_output.cc:32,114 (+`Softmax` deprecated
    alias) — forward is softmax; backward is (p - onehot(label)) * grad_scale
    regardless of head grad.
    """
    return _softmax_output(data, label, float(attrs["grad_scale"]),
                           bool(attrs["multi_output"]), bool(attrs["use_ignore"]),
                           float(attrs["ignore_label"]), attrs["normalization"])


def _head_grad_op(fwd_fn, bwd_fn):
    """Build a custom_vjp op whose backward ignores the head gradient."""
    f = jax.custom_vjp(fwd_fn)
    f.defvjp(lambda *args: (fwd_fn(*args), args), bwd_fn)
    return f


_linreg = _head_grad_op(
    lambda data, label: data,
    lambda res, g: ((res[0] - res[1].reshape(res[0].shape)).astype(res[0].dtype),
                    jnp.zeros_like(res[1])))
_maereg = _head_grad_op(
    lambda data, label: data,
    lambda res, g: (jnp.sign(res[0] - res[1].reshape(res[0].shape)).astype(res[0].dtype),
                    jnp.zeros_like(res[1])))
_logreg = _head_grad_op(
    lambda data, label: jax.nn.sigmoid(data),
    lambda res, g: ((jax.nn.sigmoid(res[0]) - res[1].reshape(res[0].shape)).astype(res[0].dtype),
                    jnp.zeros_like(res[1])))


@register("LinearRegressionOutput", arg_names=("data", "label"),
          params={"grad_scale": 1.0}, is_loss=True)
def linear_regression_output(attrs, ctx, data, label):
    """Reference: src/operator/regression_output-inl.h (grad = pred - label)."""
    return _linreg(data, label)


@register("MAERegressionOutput", arg_names=("data", "label"),
          params={"grad_scale": 1.0}, is_loss=True)
def mae_regression_output(attrs, ctx, data, label):
    return _maereg(data, label)


@register("LogisticRegressionOutput", arg_names=("data", "label"),
          params={"grad_scale": 1.0}, is_loss=True)
def logistic_regression_output(attrs, ctx, data, label):
    return _logreg(data, label)


@register("SVMOutput", arg_names=("data", "label"),
          params={"margin": 1.0, "regularization_coefficient": 1.0,
                  "use_linear": False}, is_loss=True)
def svm_output(attrs, ctx, data, label):
    """Reference: src/operator/svm_output-inl.h."""
    margin = float(attrs["margin"])
    reg = float(attrs["regularization_coefficient"])
    use_linear = bool(attrs["use_linear"])

    def bwd(res, g):
        x, lab = res
        n = x.shape[-1]
        onehot = jax.nn.one_hot(lab.astype(jnp.int32), n, dtype=x.dtype)
        score_correct = jnp.sum(x * onehot, axis=-1, keepdims=True)
        if use_linear:
            viol = ((margin - (2 * onehot - 1) * x) > 0).astype(x.dtype)
            grad = -(2 * onehot - 1) * viol * reg
        else:
            viol = ((x - score_correct + margin) > 0).astype(x.dtype) * (1 - onehot)
            grad = viol - onehot * jnp.sum(viol, axis=-1, keepdims=True)
            grad = grad * reg
        return grad, jnp.zeros_like(lab)

    f = _head_grad_op(lambda d, l: d, bwd)
    return f(data, label)


@register("MakeLoss", params={"grad_scale": 1.0, "valid_thresh": 0.0,
                              "normalization": "null"}, is_loss=True)
def make_loss(attrs, ctx, data):
    """Forward identity; backward = grad_scale (loss source).

    Reference: src/operator/make_loss-inl.h.
    """
    scale = float(attrs["grad_scale"])
    norm = attrs["normalization"]
    thresh = float(attrs["valid_thresh"])

    def bwd(res, g):
        (x,) = res
        if norm == "batch":
            s = jnp.asarray(scale / x.shape[0], x.dtype)
        elif norm == "valid":
            # divide by the count of entries above valid_thresh
            # (make_loss-inl.h:98-113) — SSD's per-positive-anchor scaling
            valid = jnp.maximum(jnp.sum(x > thresh), 1).astype(x.dtype)
            s = jnp.asarray(scale, x.dtype) / valid
        else:
            s = jnp.asarray(scale, x.dtype)
        return (jnp.broadcast_to(s, x.shape).astype(x.dtype),)

    f = _head_grad_op(lambda d: d, bwd)
    return f(data)


@register("BlockGrad", aliases=("stop_gradient",))
def block_grad(attrs, ctx, data):
    """Reference: src/operator/slice_channel / blockgrad op — stops gradients."""
    return lax.stop_gradient(data)


# ----------------------------------------------------------------- shape ops
@register("Flatten", aliases=("flatten",))
def flatten_op(attrs, ctx, data):
    """Reference: reshape family in src/operator/tensor/matrix_op.cc."""
    return data.reshape((data.shape[0], -1))


@register("Concat", arg_names=lambda a: tuple(f"arg{i}" for i in range(int(a["num_args"]))),
          params={"num_args": 1, "dim": 1}, key_var_num_args="num_args",
          aliases=("concat",))
def concat(attrs, ctx, *args):
    """Reference: src/operator/concat-inl.h."""
    dim = int(attrs["dim"])
    if dim == 1 and all(_is_nhwc(a) for a in args):
        dim = 3  # channel concat under the trainer's NHWC activation mode
    return jnp.concatenate(args, axis=dim)


@register("SliceChannel",
          num_outputs=lambda a: int(a["num_outputs"]),
          params={"num_outputs": 1, "axis": 1, "squeeze_axis": False},
          aliases=("split",))
def slice_channel(attrs, ctx, data):
    """Reference: src/operator/slice_channel-inl.h."""
    axis = int(attrs["axis"])
    if axis == 1 and _is_nhwc(data):
        axis = 3
    parts = jnp.split(data, int(attrs["num_outputs"]), axis=axis)
    if attrs["squeeze_axis"]:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


@register("Embedding", arg_names=("data", "weight"),
          params={"input_dim": 0, "output_dim": 0, "dtype": "float32"})
def embedding(attrs, ctx, data, weight):
    """Reference: src/operator/tensor/indexing_op.cc Embedding."""
    return jnp.take(weight, data.astype(jnp.int32), axis=0)


@register("Pad", params={"mode": "constant", "pad_width": (), "constant_value": 0.0})
def pad_op(attrs, ctx, data):
    """Reference: src/operator/pad-inl.h (pad_width is declared in the
    reference NCHW axis order; permuted here when activations are NHWC)."""
    pw = tuple(attrs["pad_width"])
    pairs = [(int(pw[2 * i]), int(pw[2 * i + 1])) for i in range(len(pw) // 2)]
    if len(pairs) == 4 and _is_nhwc(data):
        pairs = [pairs[0], pairs[2], pairs[3], pairs[1]]
    mode = attrs["mode"]
    if mode == "constant":
        return jnp.pad(data, pairs, constant_values=attrs["constant_value"])
    if mode == "edge":
        return jnp.pad(data, pairs, mode="edge")
    if mode == "reflect":
        return jnp.pad(data, pairs, mode="reflect")
    raise MXNetError(f"unknown pad mode {mode}")


@register("UpSampling",
          arg_names=lambda a: tuple(f"arg{i}" for i in range(int(a["num_args"]))),
          params={"scale": 1, "num_filter": 0, "sample_type": "nearest",
                  "multi_input_mode": "concat", "num_args": 1, "workspace": 512},
          key_var_num_args="num_args")
def upsampling(attrs, ctx, *args):
    """Nearest-neighbour upsampling.  Reference: src/operator/upsampling-inl.h."""
    scale = int(attrs["scale"])
    h_ax = 1 if _is_nhwc(args[0]) else 2
    outs = []
    target = args[0].shape[h_ax] * scale
    for a in args:
        s = target // a.shape[h_ax]
        out = jnp.repeat(jnp.repeat(a, s, axis=h_ax), s, axis=h_ax + 1)
        outs.append(out)
    if len(outs) == 1:
        return outs[0]
    if attrs["multi_input_mode"] == "sum":
        return sum(outs)
    return jnp.concatenate(outs, axis=3 if _is_nhwc(args[0]) else 1)


@register("Crop", arg_names=lambda a: tuple(f"arg{i}" for i in range(int(a["num_args"]))),
          params={"num_args": 1, "offset": (0, 0), "h_w": (0, 0),
                  "center_crop": False},
          key_var_num_args="num_args")
def crop(attrs, ctx, *args):
    """Reference: src/operator/crop-inl.h."""
    data = args[0]
    nhwc = _is_nhwc(data)
    h_ax = 1 if nhwc else 2
    if len(args) == 2:
        h, w = args[1].shape[h_ax], args[1].shape[h_ax + 1]
    else:
        h, w = attrs["h_w"]
    if attrs["center_crop"]:
        oh = (data.shape[h_ax] - h) // 2
        ow = (data.shape[h_ax + 1] - w) // 2
    else:
        oh, ow = attrs["offset"]
    if nhwc:
        return lax.dynamic_slice(data, (0, oh, ow, 0),
                                 (data.shape[0], h, w, data.shape[3]))
    return lax.dynamic_slice(data, (0, 0, oh, ow),
                             (data.shape[0], data.shape[1], h, w))


@register("SwapAxis", params={"dim1": 0, "dim2": 0}, aliases=("swapaxes",))
def swapaxis(attrs, ctx, data):
    """Reference: src/operator/swapaxis-inl.h."""
    return jnp.swapaxes(data, int(attrs["dim1"]), int(attrs["dim2"]))


# -------------------------------------------------------------- sequence ops
def _seq_mask(data, length, batch_axis, time_axis):
    steps = jnp.arange(data.shape[time_axis])
    mshape = [1] * data.ndim
    mshape[time_axis] = data.shape[time_axis]
    mask = steps.reshape(mshape) < jnp.reshape(
        length, [data.shape[batch_axis] if i == batch_axis else 1
                 for i in range(data.ndim)])
    return mask


@register("SequenceMask", arg_names=lambda a: ("data", "sequence_length")
          if a["use_sequence_length"] else ("data",),
          params={"use_sequence_length": False, "value": 0.0, "axis": 0})
def sequence_mask(attrs, ctx, data, sequence_length=None):
    """Reference: src/operator/sequence_mask-inl.h (time-major [T,B,...])."""
    if sequence_length is None:
        return data
    mask = _seq_mask(data, sequence_length, batch_axis=1, time_axis=0)
    return jnp.where(mask, data, jnp.asarray(attrs["value"], data.dtype))


@register("SequenceLast", arg_names=lambda a: ("data", "sequence_length")
          if a["use_sequence_length"] else ("data",),
          params={"use_sequence_length": False, "axis": 0})
def sequence_last(attrs, ctx, data, sequence_length=None):
    """Reference: src/operator/sequence_last-inl.h."""
    if sequence_length is None:
        return data[-1]
    idx = (sequence_length.astype(jnp.int32) - 1)
    return jnp.take_along_axis(
        data, idx.reshape((1, -1) + (1,) * (data.ndim - 2)), axis=0)[0]


@register("SequenceReverse", arg_names=lambda a: ("data", "sequence_length")
          if a["use_sequence_length"] else ("data",),
          params={"use_sequence_length": False, "axis": 0})
def sequence_reverse(attrs, ctx, data, sequence_length=None):
    """Reference: src/operator/sequence_reverse-inl.h."""
    if sequence_length is None:
        return jnp.flip(data, axis=0)
    T = data.shape[0]
    steps = jnp.arange(T).reshape((T, 1))
    lens = sequence_length.astype(jnp.int32).reshape((1, -1))
    src = jnp.where(steps < lens, lens - 1 - steps, steps)  # [T, B]
    src = src.reshape((T, -1) + (1,) * (data.ndim - 2))
    return jnp.take_along_axis(data, src, axis=0)


@register("softmax_cross_entropy", arg_names=("data", "label"))
# mxlint: allow-dtype-widening(normalization/softmax statistics accumulate in f32 by contract)
def softmax_cross_entropy(attrs, ctx, data, label):
    """Scalar cross entropy of softmax(data) against integer labels
    (reference loss_binary_op.cc:11-60)."""
    logp = jax.nn.log_softmax(data.astype(jnp.float32), axis=-1)
    idx = jnp.clip(label.astype(jnp.int32), 0, data.shape[-1] - 1)
    picked = jnp.take_along_axis(logp, idx[:, None], axis=-1)
    return -jnp.sum(picked).reshape((1,)).astype(data.dtype)


@register("IdentityAttachKLSparseReg", arg_names=("data",),
          aux_names=("moving_avg",),
          params={"sparseness_target": 0.1, "penalty": 0.001,
                  "momentum": 0.9})
# mxlint: allow-dtype-widening(normalization/softmax statistics accumulate in f32 by contract)
def identity_attach_kl_sparse_reg(attrs, ctx, data, moving_avg):
    """Identity forward; backward adds a KL sparseness penalty against a
    running mean activation (identity_attach_KL_sparse_reg-inl.h:60-110;
    pair with sigmoid activations).  The reference updates the running
    mean during backward; here it updates on the training forward — the
    same once-per-step cadence in functional form."""
    s = float(attrs["sparseness_target"])
    penalty = float(attrs["penalty"])
    momentum = float(attrs["momentum"])
    x2 = data.reshape((data.shape[0], -1)).astype(jnp.float32)
    if ctx.is_train:
        avg = jnp.mean(x2, axis=0)
        new_ma = momentum * moving_avg.astype(jnp.float32) \
            + (1 - momentum) * avg
    else:
        new_ma = moving_avg.astype(jnp.float32)
    ma = lax.stop_gradient(new_ma)

    @jax.custom_vjp
    def f(x):
        return x

    def fwd(x):
        return x, ()

    def bwd(res, g):
        reg = penalty * (-s / ma + (1 - s) / (1 - ma))
        return ((g.reshape(x2.shape) + reg).reshape(g.shape).astype(g.dtype),)

    f.defvjp(fwd, bwd)
    return f(data), new_ma.astype(moving_avg.dtype)


@register("LSoftmax", arg_names=("data", "weight", "label"),
          params={"num_hidden": 0, "margin": 2, "beta": 1.0,
                  "beta_min": 0.0, "scale": 1.0, "verbose": False})
# mxlint: allow-dtype-widening(normalization/softmax statistics accumulate in f32 by contract)
def lsoftmax(attrs, ctx, data, weight, label):
    """Large-margin softmax inner product (reference lsoftmax.cc /
    lsoftmax.cu — GPU-only there; this jnp formulation runs on every
    backend).  For the label class: f = |x||w| psi(theta) with
    psi(theta) = (-1)^k cos(m*theta) - 2k on the monotone extension of
    cos, blended with the plain product by beta/(1+beta).
    """
    m = int(attrs["margin"])
    beta = float(attrs["beta"])
    x = data.astype(jnp.float32)
    w = weight.astype(jnp.float32)
    out = x @ w.T                                     # (N, C)
    if m == 1 or not ctx.is_train:
        return out.astype(data.dtype)
    n = x.shape[0]
    y = jnp.clip(label.astype(jnp.int32), 0, w.shape[0] - 1)
    wy = w[y]                                          # (N, D)
    xn = jnp.linalg.norm(x, axis=1)
    wn = jnp.linalg.norm(wy, axis=1)
    fy = jnp.take_along_axis(out, y[:, None], axis=1)[:, 0]
    cos = jnp.clip(fy / jnp.maximum(xn * wn, 1e-12), -1.0, 1.0)
    # k such that theta in [k*pi/m, (k+1)*pi/m): count thresholds above cos
    j = jnp.arange(1, m + 1, dtype=jnp.float32)
    thresholds = jnp.cos(j * jnp.pi / m)               # (m,)
    k = jnp.sum(cos[:, None] < thresholds[None, :], axis=1).astype(
        jnp.float32)
    k = lax.stop_gradient(k)
    # cos(m*theta) via the Chebyshev polynomial T_m(cos theta)
    theta = jnp.arccos(cos)
    cos_m = jnp.cos(m * theta)
    psi = ((-1.0) ** k) * cos_m - 2.0 * k
    fy_new = (beta * fy + xn * wn * psi) / (1.0 + beta)
    out = out.at[jnp.arange(n), y].set(fy_new)
    return out.astype(data.dtype)
