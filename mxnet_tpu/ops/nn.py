"""Neural-network layer ops (the reference's `src/operator/*-inl.h` corpus).

Each op is a pure JAX function over jnp/lax; layout is NCHW to match the
reference default.  Convs and matmuls are expressed with
``lax.conv_general_dilated`` / ``jnp.dot`` so XLA tiles them onto the MXU;
elementwise pieces are left for XLA to fuse.

Reference citations per op are in each docstring.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .registry import register


def _pair(v, n=2):
    if isinstance(v, (tuple, list)):
        t = tuple(int(x) for x in v)
        return t if len(t) == n else t * n if len(t) == 1 else t
    return (int(v),) * n


# --------------------------------------------------------------------- dense
@register("FullyConnected", arg_names=lambda a: ("data", "weight") if a["no_bias"]
          else ("data", "weight", "bias"),
          params={"num_hidden": 0, "no_bias": False, "flatten": True},
          aliases=("fully_connected",))
def fully_connected(attrs, ctx, data, weight, bias=None):
    """Y = X W^T + b.  Reference: src/operator/fully_connected-inl.h:48-145."""
    if attrs["flatten"]:
        x = data.reshape((data.shape[0], -1))
    else:
        x = data
    # the TPU MXU accumulates bf16 dots in f32 natively; no upcast
    # annotation (preferred_element_type breaks the conv/dot transpose rule)
    y = jnp.dot(x, weight.T)
    if bias is not None:
        y = y + bias
    return y.astype(data.dtype)


# ---------------------------------------------------------------------- conv
@register("Convolution", arg_names=lambda a: ("data", "weight") if a["no_bias"]
          else ("data", "weight", "bias"),
          params={"kernel": (1, 1), "stride": (), "dilate": (), "pad": (),
                  "num_filter": 0, "num_group": 1, "no_bias": False,
                  "workspace": 1024, "cudnn_tune": None, "cudnn_off": False,
                  "layout": None},
          aliases=("convolution", "Convolution_v1"))
def convolution(attrs, ctx, data, weight, bias=None):
    """N-d convolution, NCHW/NCW/NCDHW.  Reference: src/operator/convolution-inl.h:103-325.

    Weight layout (num_filter, C/group, *kernel) as in the reference; lowered
    to one lax.conv_general_dilated so XLA maps it onto the MXU.
    """
    kernel = tuple(attrs["kernel"])
    nd = len(kernel)
    stride = tuple(attrs["stride"]) or (1,) * nd
    dilate = tuple(attrs["dilate"]) or (1,) * nd
    pad = tuple(attrs["pad"]) or (0,) * nd
    dn = lax.conv_dimension_numbers(
        data.shape, weight.shape,
        ("NCHW", "OIHW", "NCHW") if nd == 2 else
        ("NCW", "OIW", "NCW") if nd == 1 else ("NCDHW", "OIDHW", "NCDHW"))
    y = lax.conv_general_dilated(
        data, weight, window_strides=stride,
        padding=[(p, p) for p in pad], rhs_dilation=dilate,
        dimension_numbers=dn, feature_group_count=int(attrs["num_group"]))
    if bias is not None:
        y = y + bias.reshape((1, -1) + (1,) * nd)
    return y.astype(data.dtype)


@register("Deconvolution", arg_names=lambda a: ("data", "weight") if a["no_bias"]
          else ("data", "weight", "bias"),
          params={"kernel": (1, 1), "stride": (), "dilate": (), "pad": (),
                  "adj": (), "target_shape": (), "num_filter": 0,
                  "num_group": 1, "no_bias": True, "workspace": 512,
                  "cudnn_tune": None, "cudnn_off": False, "layout": None})
def deconvolution(attrs, ctx, data, weight, bias=None):
    """Transposed convolution.  Reference: src/operator/deconvolution-inl.h.

    Implemented as conv_general_dilated with lhs_dilation (the XLA-native
    formulation of conv-transpose).  Weight layout (C_in, C_out/group, *k).
    """
    kernel = tuple(attrs["kernel"])
    nd = len(kernel)
    stride = tuple(attrs["stride"]) or (1,) * nd
    pad = tuple(attrs["pad"]) or (0,) * nd
    adj = tuple(attrs["adj"]) or (0,) * nd
    groups = int(attrs["num_group"])
    # flip spatial dims and swap in/out channels -> direct conv on dilated input
    w = jnp.flip(weight, axis=tuple(range(2, 2 + nd)))
    if groups == 1:
        w = jnp.swapaxes(w, 0, 1)
    else:
        ci, co = weight.shape[0], weight.shape[1]
        w = w.reshape((groups, ci // groups, co) + kernel)
        w = jnp.swapaxes(w, 1, 2).reshape((groups * co, ci // groups) + kernel)
    dn = lax.conv_dimension_numbers(
        data.shape, w.shape,
        ("NCHW", "OIHW", "NCHW") if nd == 2 else
        ("NCW", "OIW", "NCW") if nd == 1 else ("NCDHW", "OIDHW", "NCDHW"))
    padding = [(kernel[i] - 1 - pad[i], kernel[i] - 1 - pad[i] + adj[i])
               for i in range(nd)]
    y = lax.conv_general_dilated(
        data, w, window_strides=(1,) * nd, padding=padding,
        lhs_dilation=stride, dimension_numbers=dn,
        feature_group_count=groups)
    if bias is not None:
        y = y + bias.reshape((1, -1) + (1,) * nd)
    return y.astype(data.dtype)


# ------------------------------------------------------------------- pooling
@register("Pooling",
          params={"kernel": (1, 1), "pool_type": "max", "global_pool": False,
                  "stride": (), "pad": (), "pooling_convention": "valid",
                  "cudnn_off": False},
          aliases=("pooling", "Pooling_v1"))
def pooling(attrs, ctx, data):
    """Max/avg/sum pooling via lax.reduce_window.

    Reference: src/operator/pooling-inl.h (+pooling.cc registration).
    """
    nd = data.ndim - 2
    if attrs["global_pool"]:
        kernel = data.shape[2:]
        stride = (1,) * nd
        pad = (0,) * nd
    else:
        kernel = _pair(attrs["kernel"], nd)
        # reference defaults stride to 1 (pooling-inl.h), NOT to the kernel
        stride = tuple(attrs["stride"]) or (1,) * nd
        pad = tuple(attrs["pad"]) or (0,) * nd
    window = (1, 1) + tuple(kernel)
    strides = (1, 1) + tuple(stride)
    conv = attrs.get("pooling_convention", "valid")
    padding = [(0, 0), (0, 0)]
    for i in range(nd):
        lo = hi = pad[i]
        if conv == "full":
            # ceil division convention: pad extra on the high side as needed
            in_sz = data.shape[2 + i] + 2 * pad[i]
            rem = (in_sz - kernel[i]) % stride[i]
            if rem:
                hi += stride[i] - rem
        padding.append((lo, hi))
    ptype = attrs["pool_type"]
    # init values must be python literals (the identity element) so JAX's
    # reduce_window autodiff monoid pattern-match fires
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) \
            else int(jnp.iinfo(data.dtype).min)
        return lax.reduce_window(data, init, lax.max,
                                 window, strides, padding)
    zero = 0.0 if jnp.issubdtype(data.dtype, jnp.floating) else 0
    summed = lax.reduce_window(data, zero, lax.add,
                               window, strides, padding)
    if ptype == "sum":
        return summed
    if ptype == "avg":
        # reference divides by full window size (count_include_pad)
        wsize = 1
        for k in kernel:
            wsize *= k
        return (summed / wsize).astype(data.dtype)
    raise MXNetError(f"unknown pool_type {ptype}")


# ---------------------------------------------------------------- batch norm
@register("BatchNorm",
          arg_names=("data", "gamma", "beta"),
          aux_names=("moving_mean", "moving_var"),
          num_outputs=lambda a: 3 if a.get("output_mean_var") else 1,
          params={"eps": 1e-3, "momentum": 0.9, "fix_gamma": True,
                  "use_global_stats": False, "output_mean_var": False,
                  "axis": 1, "cudnn_off": False},
          aliases=("batch_norm", "BatchNorm_v1"))
def batch_norm(attrs, ctx, data, gamma, beta, moving_mean, moving_var):
    """Batch normalization with functional aux-state threading.

    Reference: src/operator/batch_norm-inl.h / batch_norm.cc.  The reference
    mutates moving_{mean,var} aux states in forward during training; here the
    updated stats are returned as trailing outputs and threaded by the
    executor (SURVEY §7 'hard parts': aux state).
    Returns (out[, mean, var], new_moving_mean, new_moving_var).
    """
    axis = int(attrs["axis"])
    eps = float(attrs["eps"])
    momentum = float(attrs["momentum"])
    red = tuple(i for i in range(data.ndim) if i != axis)
    bshape = tuple(1 if i != axis else data.shape[axis]
                   for i in range(data.ndim))
    if attrs["fix_gamma"]:
        gamma = jnp.ones_like(gamma)
    xf = data.astype(jnp.float32)
    if ctx.is_train and not attrs["use_global_stats"]:
        mean = jnp.mean(xf, axis=red)
        var = jnp.var(xf, axis=red)
        new_mean = moving_mean * momentum + mean * (1 - momentum)
        new_var = moving_var * momentum + var * (1 - momentum)
    else:
        mean, var = moving_mean.astype(jnp.float32), moving_var.astype(jnp.float32)
        new_mean, new_var = moving_mean, moving_var
    inv = lax.rsqrt(var + eps)
    out = (xf - mean.reshape(bshape)) * inv.reshape(bshape)
    out = out * gamma.reshape(bshape) + beta.reshape(bshape)
    out = out.astype(data.dtype)
    if attrs.get("output_mean_var"):
        return (out, mean, var,
                new_mean.astype(moving_mean.dtype), new_var.astype(moving_var.dtype))
    return (out, new_mean.astype(moving_mean.dtype), new_var.astype(moving_var.dtype))


@register("InstanceNorm", arg_names=("data", "gamma", "beta"),
          params={"eps": 1e-3})
def instance_norm(attrs, ctx, data, gamma, beta):
    """Reference: src/operator/instance_norm-inl.h."""
    red = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=red, keepdims=True)
    var = jnp.var(data, axis=red, keepdims=True)
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    out = (data - mean) * lax.rsqrt(var + attrs["eps"])
    return out * gamma.reshape(bshape) + beta.reshape(bshape)


@register("L2Normalization", params={"eps": 1e-10, "mode": "instance"})
def l2_normalization(attrs, ctx, data):
    """Reference: src/operator/l2_normalization-inl.h."""
    mode = attrs["mode"]
    if mode == "instance":
        red = tuple(range(1, data.ndim))
        keep = True
    elif mode == "channel":
        red, keep = (1,), True
    elif mode == "spatial":
        red, keep = tuple(range(2, data.ndim)), True
    else:
        raise MXNetError(f"unknown mode {mode}")
    norm = jnp.sqrt(jnp.sum(jnp.square(data), axis=red, keepdims=keep)
                    + attrs["eps"])
    return data / norm


@register("LRN", params={"alpha": 1e-4, "beta": 0.75, "knorm": 2.0, "nsize": 5})
def lrn(attrs, ctx, data):
    """Local response norm across channels.  Reference: src/operator/lrn-inl.h."""
    nsize = int(attrs["nsize"])
    sq = jnp.square(data.astype(jnp.float32))
    pre = nsize // 2
    post = nsize - pre - 1
    padded = jnp.pad(sq, [(0, 0), (pre, post)] + [(0, 0)] * (data.ndim - 2))
    acc = sum(lax.slice_in_dim(padded, i, i + data.shape[1], axis=1)
              for i in range(nsize))
    scale = attrs["knorm"] + (attrs["alpha"] / nsize) * acc
    return (data * scale ** (-attrs["beta"])).astype(data.dtype)


# ------------------------------------------------------------- activations
@register("Activation", params={"act_type": "relu"}, aliases=("activation",))
def activation(attrs, ctx, data):
    """Reference: src/operator/activation-inl.h; functors mshadow_op.h."""
    t = attrs["act_type"]
    if t == "relu":
        return jax.nn.relu(data)
    if t == "sigmoid":
        return jax.nn.sigmoid(data)
    if t == "tanh":
        return jnp.tanh(data)
    if t == "softrelu":
        return jax.nn.softplus(data)
    if t == "softsign":
        return data / (1 + jnp.abs(data))
    raise MXNetError(f"unknown act_type {t}")


@register("LeakyReLU", arg_names=lambda a: ("data", "gamma")
          if a["act_type"] == "prelu" else ("data",),
          params={"act_type": "leaky", "slope": 0.25,
                  "lower_bound": 0.125, "upper_bound": 0.334},
          stochastic=lambda a: a["act_type"] == "rrelu")
def leaky_relu(attrs, ctx, data, gamma=None):
    """Reference: src/operator/leaky_relu-inl.h."""
    t = attrs["act_type"]
    if t == "leaky":
        return jnp.where(data > 0, data, data * attrs["slope"])
    if t == "prelu":
        g = gamma.reshape((1, -1) + (1,) * (data.ndim - 2))
        return jnp.where(data > 0, data, data * g)
    if t == "elu":
        return jnp.where(data > 0, data, attrs["slope"] * (jnp.exp(data) - 1))
    if t == "rrelu":
        if ctx.is_train:
            lo, hi = attrs["lower_bound"], attrs["upper_bound"]
            slope = jax.random.uniform(ctx.require_key(),
                                       data.shape, data.dtype, lo, hi)
        else:
            slope = (attrs["lower_bound"] + attrs["upper_bound"]) / 2.0
        return jnp.where(data > 0, data, data * slope)
    raise MXNetError(f"unknown act_type {t}")


@register("Dropout", params={"p": 0.5, "mode": "training"}, stochastic=True,
          aliases=("dropout",))
def dropout(attrs, ctx, data):
    """Inverted dropout.  Reference: src/operator/dropout-inl.h."""
    p = float(attrs["p"])
    if not ctx.is_train or p <= 0.0:
        return data
    keep = 1.0 - p
    mask = jax.random.bernoulli(ctx.require_key(), keep, data.shape)
    return jnp.where(mask, data / keep, 0).astype(data.dtype)


# ------------------------------------------------------------------ softmax
def _softmax(x, axis):
    return jax.nn.softmax(x.astype(jnp.float32), axis=axis).astype(x.dtype)


@register("softmax", params={"axis": -1, "temperature": None})
def softmax_op(attrs, ctx, data):
    """Reference: softmax in src/operator/nn-era tensor ops (softmax_output.cc kin)."""
    x = data
    if attrs.get("temperature"):
        x = x / attrs["temperature"]
    return _softmax(x, int(attrs["axis"]))


@register("log_softmax", params={"axis": -1})
def log_softmax_op(attrs, ctx, data):
    return jax.nn.log_softmax(data.astype(jnp.float32),
                              axis=int(attrs["axis"])).astype(data.dtype)


@register("SoftmaxActivation", params={"mode": "instance"})
def softmax_activation(attrs, ctx, data):
    """Reference: src/operator/softmax_activation-inl.h."""
    if attrs["mode"] == "channel":
        return _softmax(data, 1)
    return _softmax(data.reshape((data.shape[0], -1)), -1).reshape(data.shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6))
def _softmax_output(data, label, grad_scale, multi_output, use_ignore,
                    ignore_label, normalization):
    axis = 1 if multi_output else -1
    return _softmax(data, axis)


def _softmax_output_fwd(data, label, grad_scale, multi_output, use_ignore,
                        ignore_label, normalization):
    out = _softmax_output(data, label, grad_scale, multi_output, use_ignore,
                          ignore_label, normalization)
    return out, (out, label)


def _softmax_output_bwd(grad_scale, multi_output, use_ignore, ignore_label,
                        normalization, res, g):
    # Reference backward (src/operator/softmax_output-inl.h): grad = p - onehot,
    # ignoring the incoming head gradient (it is a terminal loss op).
    out, label = res
    axis = 1 if multi_output else -1
    nclass = out.shape[axis]
    lab = label.astype(jnp.int32)
    onehot = jax.nn.one_hot(lab, nclass, dtype=jnp.float32, axis=axis)
    grad = out.astype(jnp.float32) - onehot
    valid = None
    if use_ignore:
        keep = (lab != int(ignore_label))
        keepb = jnp.expand_dims(keep, axis=axis)
        grad = grad * keepb
        valid = jnp.maximum(jnp.sum(keep), 1)
    scale = grad_scale
    if normalization == "batch":
        scale = scale / out.shape[0]
    elif normalization == "valid" and valid is not None:
        scale = scale / valid
    elif normalization == "valid":
        scale = scale / lab.size
    return (grad * scale).astype(out.dtype), jnp.zeros_like(label)


_softmax_output.defvjp(_softmax_output_fwd, _softmax_output_bwd)


@register("SoftmaxOutput", arg_names=("data", "label"),
          params={"grad_scale": 1.0, "ignore_label": -1.0, "multi_output": False,
                  "use_ignore": False, "preserve_shape": False,
                  "normalization": "null", "out_grad": False,
                  "smooth_alpha": 0.0},
          is_loss=True, aliases=("Softmax",))
def softmax_output(attrs, ctx, data, label):
    """Softmax forward + cross-entropy-style custom backward.

    Reference: src/operator/softmax_output.cc:32,114 (+`Softmax` deprecated
    alias) — forward is softmax; backward is (p - onehot(label)) * grad_scale
    regardless of head grad.
    """
    return _softmax_output(data, label, float(attrs["grad_scale"]),
                           bool(attrs["multi_output"]), bool(attrs["use_ignore"]),
                           float(attrs["ignore_label"]), attrs["normalization"])


def _head_grad_op(fwd_fn, bwd_fn):
    """Build a custom_vjp op whose backward ignores the head gradient."""
    f = jax.custom_vjp(fwd_fn)
    f.defvjp(lambda *args: (fwd_fn(*args), args), bwd_fn)
    return f


_linreg = _head_grad_op(
    lambda data, label: data,
    lambda res, g: ((res[0] - res[1].reshape(res[0].shape)).astype(res[0].dtype),
                    jnp.zeros_like(res[1])))
_maereg = _head_grad_op(
    lambda data, label: data,
    lambda res, g: (jnp.sign(res[0] - res[1].reshape(res[0].shape)).astype(res[0].dtype),
                    jnp.zeros_like(res[1])))
_logreg = _head_grad_op(
    lambda data, label: jax.nn.sigmoid(data),
    lambda res, g: ((jax.nn.sigmoid(res[0]) - res[1].reshape(res[0].shape)).astype(res[0].dtype),
                    jnp.zeros_like(res[1])))


@register("LinearRegressionOutput", arg_names=("data", "label"),
          params={"grad_scale": 1.0}, is_loss=True)
def linear_regression_output(attrs, ctx, data, label):
    """Reference: src/operator/regression_output-inl.h (grad = pred - label)."""
    return _linreg(data, label)


@register("MAERegressionOutput", arg_names=("data", "label"),
          params={"grad_scale": 1.0}, is_loss=True)
def mae_regression_output(attrs, ctx, data, label):
    return _maereg(data, label)


@register("LogisticRegressionOutput", arg_names=("data", "label"),
          params={"grad_scale": 1.0}, is_loss=True)
def logistic_regression_output(attrs, ctx, data, label):
    return _logreg(data, label)


@register("SVMOutput", arg_names=("data", "label"),
          params={"margin": 1.0, "regularization_coefficient": 1.0,
                  "use_linear": False}, is_loss=True)
def svm_output(attrs, ctx, data, label):
    """Reference: src/operator/svm_output-inl.h."""
    margin = float(attrs["margin"])
    reg = float(attrs["regularization_coefficient"])
    use_linear = bool(attrs["use_linear"])

    def bwd(res, g):
        x, lab = res
        n = x.shape[-1]
        onehot = jax.nn.one_hot(lab.astype(jnp.int32), n, dtype=x.dtype)
        score_correct = jnp.sum(x * onehot, axis=-1, keepdims=True)
        if use_linear:
            viol = ((margin - (2 * onehot - 1) * x) > 0).astype(x.dtype)
            grad = -(2 * onehot - 1) * viol * reg
        else:
            viol = ((x - score_correct + margin) > 0).astype(x.dtype) * (1 - onehot)
            grad = viol - onehot * jnp.sum(viol, axis=-1, keepdims=True)
            grad = grad * reg
        return grad, jnp.zeros_like(lab)

    f = _head_grad_op(lambda d, l: d, bwd)
    return f(data, label)


@register("MakeLoss", params={"grad_scale": 1.0, "valid_thresh": 0.0,
                              "normalization": "null"}, is_loss=True)
def make_loss(attrs, ctx, data):
    """Forward identity; backward = grad_scale (loss source).

    Reference: src/operator/make_loss-inl.h.
    """
    scale = float(attrs["grad_scale"])
    norm = attrs["normalization"]

    def bwd(res, g):
        (x,) = res
        s = scale / x.shape[0] if norm == "batch" else scale
        return (jnp.full_like(x, s),)

    f = _head_grad_op(lambda d: d, bwd)
    return f(data)


@register("BlockGrad", aliases=("stop_gradient",))
def block_grad(attrs, ctx, data):
    """Reference: src/operator/slice_channel / blockgrad op — stops gradients."""
    return lax.stop_gradient(data)


# ----------------------------------------------------------------- shape ops
@register("Flatten", aliases=("flatten",))
def flatten_op(attrs, ctx, data):
    """Reference: reshape family in src/operator/tensor/matrix_op.cc."""
    return data.reshape((data.shape[0], -1))


@register("Concat", arg_names=lambda a: tuple(f"arg{i}" for i in range(int(a["num_args"]))),
          params={"num_args": 1, "dim": 1}, key_var_num_args="num_args",
          aliases=("concat",))
def concat(attrs, ctx, *args):
    """Reference: src/operator/concat-inl.h."""
    return jnp.concatenate(args, axis=int(attrs["dim"]))


@register("SliceChannel",
          num_outputs=lambda a: int(a["num_outputs"]),
          params={"num_outputs": 1, "axis": 1, "squeeze_axis": False},
          aliases=("split",))
def slice_channel(attrs, ctx, data):
    """Reference: src/operator/slice_channel-inl.h."""
    parts = jnp.split(data, int(attrs["num_outputs"]), axis=int(attrs["axis"]))
    if attrs["squeeze_axis"]:
        parts = [jnp.squeeze(p, axis=int(attrs["axis"])) for p in parts]
    return tuple(parts)


@register("Embedding", arg_names=("data", "weight"),
          params={"input_dim": 0, "output_dim": 0, "dtype": "float32"})
def embedding(attrs, ctx, data, weight):
    """Reference: src/operator/tensor/indexing_op.cc Embedding."""
    return jnp.take(weight, data.astype(jnp.int32), axis=0)


@register("Pad", params={"mode": "constant", "pad_width": (), "constant_value": 0.0})
def pad_op(attrs, ctx, data):
    """Reference: src/operator/pad-inl.h."""
    pw = tuple(attrs["pad_width"])
    pairs = [(int(pw[2 * i]), int(pw[2 * i + 1])) for i in range(len(pw) // 2)]
    mode = attrs["mode"]
    if mode == "constant":
        return jnp.pad(data, pairs, constant_values=attrs["constant_value"])
    if mode == "edge":
        return jnp.pad(data, pairs, mode="edge")
    if mode == "reflect":
        return jnp.pad(data, pairs, mode="reflect")
    raise MXNetError(f"unknown pad mode {mode}")


@register("UpSampling",
          arg_names=lambda a: tuple(f"arg{i}" for i in range(int(a["num_args"]))),
          params={"scale": 1, "num_filter": 0, "sample_type": "nearest",
                  "multi_input_mode": "concat", "num_args": 1, "workspace": 512},
          key_var_num_args="num_args")
def upsampling(attrs, ctx, *args):
    """Nearest-neighbour upsampling.  Reference: src/operator/upsampling-inl.h."""
    scale = int(attrs["scale"])
    outs = []
    target = args[0].shape[2] * scale
    for a in args:
        s = target // a.shape[2]
        out = jnp.repeat(jnp.repeat(a, s, axis=2), s, axis=3)
        outs.append(out)
    if len(outs) == 1:
        return outs[0]
    if attrs["multi_input_mode"] == "sum":
        return sum(outs)
    return jnp.concatenate(outs, axis=1)


@register("Crop", arg_names=lambda a: tuple(f"arg{i}" for i in range(int(a["num_args"]))),
          params={"num_args": 1, "offset": (0, 0), "h_w": (0, 0),
                  "center_crop": False},
          key_var_num_args="num_args")
def crop(attrs, ctx, *args):
    """Reference: src/operator/crop-inl.h."""
    data = args[0]
    if len(args) == 2:
        h, w = args[1].shape[2], args[1].shape[3]
    else:
        h, w = attrs["h_w"]
    if attrs["center_crop"]:
        oh = (data.shape[2] - h) // 2
        ow = (data.shape[3] - w) // 2
    else:
        oh, ow = attrs["offset"]
    return lax.dynamic_slice(data, (0, 0, oh, ow),
                             (data.shape[0], data.shape[1], h, w))


@register("SwapAxis", params={"dim1": 0, "dim2": 0}, aliases=("swapaxes",))
def swapaxis(attrs, ctx, data):
    """Reference: src/operator/swapaxis-inl.h."""
    return jnp.swapaxes(data, int(attrs["dim1"]), int(attrs["dim2"]))


# -------------------------------------------------------------- sequence ops
def _seq_mask(data, length, batch_axis, time_axis):
    steps = jnp.arange(data.shape[time_axis])
    mshape = [1] * data.ndim
    mshape[time_axis] = data.shape[time_axis]
    mask = steps.reshape(mshape) < jnp.reshape(
        length, [data.shape[batch_axis] if i == batch_axis else 1
                 for i in range(data.ndim)])
    return mask


@register("SequenceMask", arg_names=lambda a: ("data", "sequence_length")
          if a["use_sequence_length"] else ("data",),
          params={"use_sequence_length": False, "value": 0.0, "axis": 0})
def sequence_mask(attrs, ctx, data, sequence_length=None):
    """Reference: src/operator/sequence_mask-inl.h (time-major [T,B,...])."""
    if sequence_length is None:
        return data
    mask = _seq_mask(data, sequence_length, batch_axis=1, time_axis=0)
    return jnp.where(mask, data, jnp.asarray(attrs["value"], data.dtype))


@register("SequenceLast", arg_names=lambda a: ("data", "sequence_length")
          if a["use_sequence_length"] else ("data",),
          params={"use_sequence_length": False, "axis": 0})
def sequence_last(attrs, ctx, data, sequence_length=None):
    """Reference: src/operator/sequence_last-inl.h."""
    if sequence_length is None:
        return data[-1]
    idx = (sequence_length.astype(jnp.int32) - 1)
    return jnp.take_along_axis(
        data, idx.reshape((1, -1) + (1,) * (data.ndim - 2)), axis=0)[0]


@register("SequenceReverse", arg_names=lambda a: ("data", "sequence_length")
          if a["use_sequence_length"] else ("data",),
          params={"use_sequence_length": False, "axis": 0})
def sequence_reverse(attrs, ctx, data, sequence_length=None):
    """Reference: src/operator/sequence_reverse-inl.h."""
    if sequence_length is None:
        return jnp.flip(data, axis=0)
    T = data.shape[0]
    steps = jnp.arange(T).reshape((T, 1))
    lens = sequence_length.astype(jnp.int32).reshape((1, -1))
    src = jnp.where(steps < lens, lens - 1 - steps, steps)  # [T, B]
    src = src.reshape((T, -1) + (1,) * (data.ndim - 2))
    return jnp.take_along_axis(data, src, axis=0)
