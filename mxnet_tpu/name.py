"""Automatic symbol naming.

Reference: ``python/mxnet/name.py`` (NameManager / Prefix) — auto-names
anonymous symbols ``convolution0, convolution1, ...`` per hint, with a
context-manager stack so nested managers (e.g. a Prefix) override.
"""
from __future__ import annotations

import threading

__all__ = ["NameManager", "Prefix"]

_state = threading.local()


def _stack():
    if not hasattr(_state, "stack"):
        _state.stack = [NameManager()]
    return _state.stack


def current():
    return _stack()[-1]


class NameManager:
    """Names anonymous symbols by hint + running counter."""

    def __init__(self):
        self._counter = {}

    def get(self, name, hint):
        if name:
            return name
        if hint not in self._counter:
            self._counter[hint] = 0
        name = "%s%d" % (hint, self._counter[hint])
        self._counter[hint] += 1
        return name

    def __enter__(self):
        _stack().append(self)
        return self

    def __exit__(self, *exc):
        _stack().pop()


class Prefix(NameManager):
    """Prepends a prefix to every auto-generated name."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        name = super().get(name, hint)
        return self._prefix + name
