"""Stdlib HTTP front door for the serving tier.

Endpoints:

* ``POST /predict`` — body ``{"<input>": <nested list>, ...}`` plus an
  optional ``"deadline_ms"``; every other key is a model input (rows
  along axis 0; a single unbatched row is accepted).  Replies 200
  ``{"outputs": [...], "rows": N, "wall_ms": W}``, 503
  ``{"shed": reason, "rid": n, "trace_id": ...}`` when the load
  shedder refused the request, 500 ``{"error": msg}`` when the
  dispatch failed (fail fast — the chaos seam surfaces here).  Every
  request runs under a distributed trace (:mod:`..telemetry.tracing`):
  an inbound W3C ``traceparent`` header continues the caller's trace,
  and every reply carries ``X-Trace-Id`` + ``traceparent`` response
  headers naming the trace the exported record joins on;
* ``GET /healthz`` — 200 with ladder/queue state while the batcher
  thread is alive, 503 once it stopped (the fleet watchdog's liveness
  contract).  ``GET /healthz?deep=1`` additionally consults the SLO
  engine (``telemetry.slo``): the reply embeds the ``mxtpu-health/1``
  verdict under ``"health"`` and the status flips 503 when the verdict
  is ``critical`` — a load balancer or fleet supervisor can drain a
  replica whose error budget is burning, not just a dead one;
* ``GET /alerts`` — the full alert surface: the health verdict plus
  every rule's current state (``tools/health_top.py --url`` reads
  this);
* ``GET /metrics`` — the shared Prometheus exposition
  (``telemetry.exporters.render_prom``), the ``tools/serve_top.py``
  input.

Constructing a :class:`Server` arms the SLO background ticker
(``MXNET_TPU_SLO_TICK_S`` cadence; ``MXNET_TPU_SLO=0`` disables) and
binds the ``serve_queue_depth`` rule to 0.9x the batcher's real queue
depth.

One :class:`Server` per replica; ``tools/launch.py --fleet`` runs N of
them with per-rank ports (``--port`` + ``MXNET_TPU_PROCESS_ID``, the
same offset rule the telemetry exporter uses).
"""
from __future__ import annotations

import json
import os
import threading
import time

import numpy as np

from ..base import MXNetError
from ..telemetry import tracing
from .batcher import Batcher, RequestShed

__all__ = ["Server", "serve_port"]


def serve_port(port=None):
    """The replica's port: explicit ``port``, else
    ``MXNET_TPU_SERVE_PORT``, offset by the launcher rank
    (``MXNET_TPU_PROCESS_ID``) so co-located replicas never race for
    one bind."""
    if port is None:
        try:
            port = int(os.environ.get("MXNET_TPU_SERVE_PORT", "8080"))
        except ValueError:
            port = 8080
    try:
        rank = int(os.environ.get("MXNET_TPU_PROCESS_ID", "0"))
    except ValueError:
        rank = 0
    return port + rank if port > 0 else port


class Server:
    """HTTP server over a :class:`~mxnet_tpu.serving.batcher.Batcher`.

    ``batcher=None`` builds one from ``ladder`` with the env-default
    knobs.  ``port=0`` binds an ephemeral port (tests); read it back
    from :attr:`port`.  The HTTP threads only ever call
    ``batcher.submit`` — all model work stays on the scheduler
    thread."""

    def __init__(self, ladder, batcher=None, port=None):
        self._ladder = ladder
        self._batcher = batcher or Batcher(ladder)
        self._httpd = self._build(serve_port(port))
        self._thread = None
        # arm the SLO judge: the replica evaluates its serving rules on
        # a background ticker and the queue-depth rule learns the
        # batcher's REAL capacity
        from ..telemetry import slo
        if slo.enabled():
            slo.engine().configure(
                "serve_queue_depth",
                bound=0.9 * getattr(self._batcher, "_depth", 64))
            slo.start_ticker()

    @property
    def port(self):
        return self._httpd.server_address[1]

    @property
    def batcher(self):
        return self._batcher

    # ------------------------------------------------------------ lifecycle
    def start(self):
        """Serve from a daemon thread (tests / in-process benches)."""
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True,
                name="mxtpu-serve-http")
            self._thread.start()
        return self

    def serve_forever(self):
        """Serve on the calling thread (the replica main loop)."""
        self._httpd.serve_forever()

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._batcher.close()
        if self._thread is not None:
            self._thread.join(5.0)

    # ------------------------------------------------------------- handler
    def _build(self, port):
        from http.server import BaseHTTPRequestHandler, \
            ThreadingHTTPServer
        server = self

        class _Handler(BaseHTTPRequestHandler):
            def _send(self, doc, status=200,
                      ctype="application/json", trace=None):
                body = doc if isinstance(doc, bytes) else \
                    json.dumps(doc).encode("utf-8")
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                if trace is not None and trace.ctx is not None:
                    # propagation contract: every /predict reply names
                    # its trace, so a slow or shed reply is joinable to
                    # the exported trace record
                    self.send_header("X-Trace-Id", trace.trace_id)
                    self.send_header("traceparent",
                                     trace.ctx.to_traceparent())
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                from urllib.parse import parse_qs, urlparse
                parsed = urlparse(self.path)
                path = parsed.path.rstrip("/") or "/"
                if path in ("/", "/healthz"):
                    ok = server._batcher.alive
                    doc = {
                        "status": "ok" if ok else "stopped",
                        "pid": os.getpid(),
                        "queue_depth": server._batcher.queue_depth(),
                        "ladder": server._ladder.describe(),
                    }
                    status = 200 if ok else 503
                    q = parse_qs(parsed.query)
                    if q.get("deep", ["0"])[-1] not in ("", "0",
                                                        "false"):
                        from ..telemetry import slo
                        verdict = slo.health()
                        doc["health"] = verdict
                        doc["status"] = "stopped" if not ok else \
                            verdict["status"]
                        # critical = the error budget is burning: a
                        # fleet supervisor / LB drains this replica
                        if verdict["status"] == "critical":
                            status = 503
                    self._send(doc, status=status)
                    return
                if path == "/alerts":
                    from ..telemetry import slo
                    doc = slo.health()
                    doc["alerts"] = slo.engine().alerts() \
                        if slo.enabled() else []
                    self._send(doc)
                    return
                if path == "/metrics":
                    from ..telemetry import render_prom
                    self._send(render_prom().encode("utf-8"),
                               ctype="text/plain; version=0.0.4; "
                                     "charset=utf-8")
                    return
                self.send_error(404)

            def do_POST(self):
                if self.path.rstrip("/") != "/predict":
                    self.send_error(404)
                    return
                t0 = time.perf_counter()
                # one trace per request; an inbound traceparent header
                # continues the caller's trace (NULL_TRACE when off)
                tr = tracing.start_trace(
                    "serve.request",
                    traceparent=self.headers.get("traceparent"))
                with tr:
                    try:
                        n = int(self.headers.get("Content-Length", "0"))
                        doc = json.loads(self.rfile.read(n) or b"{}")
                        if not isinstance(doc, dict):
                            raise MXNetError(
                                "predict body must be a JSON object of "
                                "model inputs")
                        deadline_ms = doc.pop("deadline_ms", None)
                        outs = server._batcher.submit(
                            doc, deadline_ms=deadline_ms)
                    except RequestShed as e:
                        tr.set_status("shed", shed_reason=e.reason)
                        body = {"shed": e.reason, "error": str(e)}
                        if e.rid is not None:
                            body["rid"] = e.rid
                        if tr.trace_id is not None:
                            body["trace_id"] = tr.trace_id
                        self._send(body, status=503, trace=tr)
                        return
                    except Exception as e:  # mxlint: allow-broad-except(the front door maps EVERY failure — bad JSON, missing inputs, an injected chaos fault — to a fail-fast 4xx/5xx reply; an unhandled exception would silently drop the connection instead)
                        tr.set_status("error", error=str(e)[:200])
                        status = 400 if isinstance(
                            e, (ValueError, KeyError)) else 500
                        self._send({"error": str(e)[:500]},
                                   status=status, trace=tr)
                        return
                    rows = int(np.asarray(outs[0]).shape[0]) \
                        if outs else 0
                    tr.annotate(rows=rows)
                    self._send({
                        "outputs": [np.asarray(o).tolist()
                                    for o in outs],
                        "rows": rows,
                        "wall_ms": round(
                            (time.perf_counter() - t0) * 1e3, 3),
                    }, trace=tr)

            def log_message(self, fmt, *args):
                pass        # request logs ride the metrics, not stderr

        return ThreadingHTTPServer(("0.0.0.0", int(port)), _Handler)
