"""Serving replica CLI: ``python -m mxnet_tpu.serving --model mlp``.

Builds a zoo model with freshly initialized weights (or loads a
checkpoint prefix), AOT-compiles the batch ladder, and serves forever.
Designed to run under ``tools/launch.py --fleet -n N``: each replica
reads its rank from ``MXNET_TPU_PROCESS_ID`` and binds ``--port`` +
rank; a SIGKILLed replica is respawned by the fleet watchdog and
re-warms its ladder while its peers keep serving.

SIGTERM exits 0 after closing the batcher (queued requests fail fast
with "batcher stopped"), so supervised teardown is clean.
"""
from __future__ import annotations

import argparse
import os
import signal
import sys


def _build_predictor(opts):
    import mxnet_tpu as mx
    from mxnet_tpu import models

    dims = tuple(int(d) for d in str(opts.data_shape).split(",")
                 if d.strip())
    data_shapes = {"data": (1,) + dims}
    if opts.checkpoint:
        sym_path = opts.checkpoint + "-symbol.json"
        params = "%s-%04d.params" % (opts.checkpoint, opts.epoch)
        return mx.predictor.Predictor(sym_path, params, data_shapes)
    net = models.get_model(opts.model, num_classes=opts.classes)
    mod = mx.module.Module(net, context=mx.cpu())
    label_names = [n for n in net.list_arguments()
                   if n.endswith("label")]
    mod.bind(data_shapes=[("data", (1,) + dims)],
             label_shapes=[(n, (1,)) for n in label_names])
    mod.init_params(mx.init.Xavier(rnd_type="gaussian", factor_type="in",
                                   magnitude=2.0))
    arg_params, aux_params = mod.get_params()
    params = {}
    for d in (arg_params, aux_params):
        for k, v in d.items():
            params[k] = v
    return mx.predictor.Predictor(net.tojson(), params, data_shapes)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.serving",
        description="serve a model behind the batch-ladder runtime "
                    "(docs/api/serving.md)")
    parser.add_argument("--model", default="mlp",
                        help="zoo model name (models.get_model)")
    parser.add_argument("--classes", type=int, default=10)
    parser.add_argument("--data-shape", default="64",
                        help="comma-separated non-batch dims of the "
                             "'data' input (e.g. '64' or '3,32,32')")
    parser.add_argument("--checkpoint", default=None,
                        help="checkpoint prefix to serve instead of a "
                             "fresh zoo model (expects "
                             "<prefix>-symbol.json + "
                             "<prefix>-NNNN.params)")
    parser.add_argument("--epoch", type=int, default=0)
    parser.add_argument("--port", type=int, default=None,
                        help="base port (default MXNET_TPU_SERVE_PORT; "
                             "replicas add their launcher rank)")
    parser.add_argument("--ladder", default=None,
                        help="rung spec, e.g. '1,4,16' (default "
                             "MXNET_TPU_SERVE_LADDER)")
    parser.add_argument("--window-ms", type=float, default=None)
    parser.add_argument("--queue-depth", type=int, default=None)
    parser.add_argument("--deadline-ms", type=float, default=None)
    parser.add_argument("--no-budget-check", action="store_true",
                        help="skip the memlive MXG017 gate on the "
                             "largest rung")
    opts = parser.parse_args(argv)

    # deterministic replica identity: every restart serves the same net
    from mxnet_tpu import random as mx_random
    mx_random.seed(0)

    from mxnet_tpu.serving import BatchLadder, Batcher, Server
    pred = _build_predictor(opts)
    ladder = BatchLadder(pred, rungs=opts.ladder,
                         budget_check=not opts.no_budget_check)
    batcher = Batcher(ladder, window_ms=opts.window_ms,
                      queue_depth=opts.queue_depth,
                      default_deadline_ms=opts.deadline_ms)
    server = Server(ladder, batcher=batcher, port=opts.port)

    def _term(signum, frame):
        batcher.close(timeout=1.0)
        os._exit(0)

    signal.signal(signal.SIGTERM, _term)
    print("serving: model=%s rungs=%s port=%d pid=%d rank=%s"
          % (opts.model, ladder.rungs, server.port, os.getpid(),
             os.environ.get("MXNET_TPU_PROCESS_ID", "0")), flush=True)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    batcher.close(timeout=1.0)
    return 0


if __name__ == "__main__":
    sys.exit(main())
