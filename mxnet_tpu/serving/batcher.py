"""Continuous batcher: coalescing, EDF deadlines, load shedding.

The request path between the HTTP front door and the batch ladder:

* **coalescing** — requests accumulate in a bounded queue; the single
  scheduler thread waits at most one batching window
  (``MXNET_TPU_SERVE_WINDOW_MS``, anchored at the oldest queued
  request) for the largest rung to fill, then dispatches the largest
  rung the queued rows reach (partial fill pads —
  ``mxtpu_serve_rung_occupancy`` records the real-rows fraction);
* **deadline scheduling** — earliest-deadline-first within the queue;
  before dispatch each selected request's remaining deadline is
  checked against the ladder's estimated rung wall
  (:meth:`~mxnet_tpu.serving.ladder.BatchLadder.estimate_wall`) and
  hopeless requests are shed THEN, not after burning TPU time;
* **load shedding** — a submit over the bounded depth
  (``MXNET_TPU_SERVE_QUEUE_DEPTH``) is refused immediately with
  :class:`RequestShed` (``reason="queue_full"``); the deadline check
  sheds with ``reason="deadline"``.  Sheds count on
  ``mxtpu_serve_shed_total`` and leave a ``request_shed`` flight
  event; dispatches leave ``rung_dispatch``;
* **fail fast** — a dispatch error (the ``serve.dispatch`` chaos seam
  included) fails every request of that batch immediately and the
  scheduler moves on; the queue is never wedged behind a poisoned
  batch.

Per-request latency lands in the ``mxtpu_serve_request_seconds``
histogram split into queue/pad/dispatch/total segments.
"""
from __future__ import annotations

import itertools
import os
import threading
import time

import numpy as np

from ..base import MXNetError
from ..predictor import pad_batch
from ..telemetry import tracing

__all__ = ["Batcher", "RequestShed"]


class RequestShed(MXNetError):
    """A request refused by the load shedder (never dispatched).

    ``reason``: ``"queue_full"`` (bounded queue at depth) or
    ``"deadline"`` (remaining deadline below the estimated rung wall).
    ``rid`` is the batcher's request id — grep-able in the shed flight
    events and joinable to the request's trace.  The serving front
    door maps this to HTTP 503."""

    def __init__(self, reason, detail, rid=None):
        super().__init__("request%s shed (%s): %s"
                         % ("" if rid is None else " %d" % rid,
                            reason, detail))
        self.reason = reason
        self.rid = rid


def _env_float(name, default):
    try:
        v = float(os.environ.get(name, "") or default)
    except ValueError:
        return default
    return v if v > 0 else default


class _Request:
    __slots__ = ("rid", "feed", "rows", "deadline", "enqueue_t",
                 "dequeue_t", "done", "outputs", "error", "trace")

    def __init__(self, rid, feed, rows, deadline, now, trace=None):
        self.rid = rid
        self.feed = feed
        self.rows = rows
        self.deadline = deadline
        self.enqueue_t = now
        self.dequeue_t = None
        self.done = threading.Event()
        self.outputs = None
        self.error = None
        self.trace = trace      # the submitter's TraceContext (or None)


class Batcher:
    """Thread-safe request queue + single scheduler thread over a
    :class:`~mxnet_tpu.serving.ladder.BatchLadder` (or any object with
    ``rungs``/``max_rung``/``input_names``/``pick_rung``/
    ``estimate_wall``/``observe_wall``/``dispatch`` — the unit tests
    drive the scheduler with a fake ladder, no accelerator needed).

    ``window_ms``/``queue_depth``/``default_deadline_ms`` default to
    the ``MXNET_TPU_SERVE_*`` knobs."""

    def __init__(self, ladder, window_ms=None, queue_depth=None,
                 default_deadline_ms=None, start=True):
        from .. import telemetry
        from ..telemetry.catalog import OCCUPANCY_BUCKETS
        self._ladder = ladder
        self._window = (window_ms if window_ms is not None else
                        _env_float("MXNET_TPU_SERVE_WINDOW_MS", 5.0)) \
            / 1e3
        self._depth = int(queue_depth if queue_depth is not None else
                          _env_float("MXNET_TPU_SERVE_QUEUE_DEPTH", 64))
        self._deadline = (default_deadline_ms if default_deadline_ms
                          is not None else
                          _env_float("MXNET_TPU_SERVE_DEADLINE_MS",
                                     1000.0)) / 1e3
        self._cv = threading.Condition()
        self._pending = []
        self._stopped = False
        self._ids = itertools.count(1)
        # instruments (created once; .labels children cached per use
        # site below)
        self._m_requests = telemetry.counter("mxtpu_serve_requests_total")
        self._m_shed = telemetry.counter("mxtpu_serve_shed_total")
        self._m_rung = telemetry.counter(
            "mxtpu_serve_rung_dispatch_total")
        self._m_latency = telemetry.histogram(
            "mxtpu_serve_request_seconds")
        self._m_occupancy = telemetry.histogram(
            "mxtpu_serve_rung_occupancy", buckets=OCCUPANCY_BUCKETS)
        self._m_depth = telemetry.gauge("mxtpu_serve_queue_depth")
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="mxtpu-serve-batcher")
        if start:
            self._thread.start()

    # ------------------------------------------------------------ lifecycle
    def close(self, timeout=5.0):
        """Stop the scheduler; queued requests fail with a stopped
        error."""
        with self._cv:
            self._stopped = True
            pending, self._pending = self._pending, []
            self._cv.notify_all()
        for req in pending:
            req.error = MXNetError("batcher stopped")
            req.done.set()
        self._thread.join(timeout)

    @property
    def alive(self):
        return self._thread.is_alive() and not self._stopped

    def queue_depth(self):
        with self._cv:
            return len(self._pending)

    # -------------------------------------------------------------- submit
    def submit(self, inputs, deadline_ms=None, timeout=None):
        """Block until the request is served; returns the list of
        output arrays (sliced to this request's rows).

        ``inputs``: name -> array whose axis 0 is the request's batch
        (1..max_rung rows; every input must agree).  ``deadline_ms``:
        remaining deadline from NOW (default
        ``MXNET_TPU_SERVE_DEADLINE_MS``).  Raises :class:`RequestShed`
        when shed, or re-raises the dispatch error (fail fast — an
        injected ``serve.dispatch`` fault surfaces here)."""
        feed, rows = self._validate(inputs)
        now = time.monotonic()
        # deadline_ms is EXPLICIT whenever it is not None: an explicit
        # 0 or negative deadline is already expired, never the default
        if deadline_ms is not None:
            ddl = now + deadline_ms / 1e3
        else:
            ddl = now + self._deadline
        req = _Request(next(self._ids), feed, rows, ddl, now,
                       trace=tracing.current())
        with self._cv:
            if self._stopped:
                raise MXNetError("batcher stopped")
            if deadline_ms is not None and deadline_ms <= 0:
                self._count_shed("deadline", req,
                                 "explicit deadline_ms=%r expired on "
                                 "arrival" % (deadline_ms,))
                raise RequestShed(
                    "deadline", "explicit deadline_ms=%r is already "
                    "expired on arrival" % (deadline_ms,), rid=req.rid)
            if len(self._pending) >= self._depth:
                self._count_shed("queue_full", req,
                                 "queue depth %d" % self._depth)
                raise RequestShed(
                    "queue_full", "queue at bounded depth %d"
                    % self._depth, rid=req.rid)
            # shed EARLY: even alone in the smallest rung this request
            # cannot finish inside its deadline
            min_wall = self._ladder.estimate_wall(
                self._ladder.pick_rung(rows) or self._ladder.max_rung)
            if ddl - now < min_wall:
                self._count_shed("deadline", req,
                                 "deadline %.1fms < estimated wall "
                                 "%.1fms" % ((ddl - now) * 1e3,
                                             min_wall * 1e3))
                raise RequestShed(
                    "deadline", "remaining deadline %.1fms cannot cover "
                    "the estimated rung wall %.1fms"
                    % ((ddl - now) * 1e3, min_wall * 1e3), rid=req.rid)
            self._pending.append(req)
            self._m_depth.set(len(self._pending))
            self._cv.notify_all()
        wait = timeout if timeout is not None else \
            max(0.05, ddl - now) + 4.0 * max(
                0.025, self._ladder.estimate_wall(self._ladder.max_rung))
        if not req.done.wait(wait):
            raise MXNetError("request %d timed out after %.1fs in the "
                             "batcher" % (req.rid, wait))
        if req.error is not None:
            raise req.error
        return req.outputs

    def _validate(self, inputs):
        names = list(self._ladder.input_names)
        feed, rows = {}, None
        for n in names:
            if n not in inputs:
                raise MXNetError("missing input %r (serving inputs: %s)"
                                 % (n, names))
            arr = np.asarray(inputs[n],
                             dtype=self._ladder.input_dtype(n))
            tail = tuple(self._ladder.input_tail(n))
            if arr.shape == tail:
                arr = arr[None]          # one unbatched row
            if arr.ndim != len(tail) + 1 or tuple(arr.shape[1:]) != tail:
                raise MXNetError(
                    "input %r: expected rows of shape %r, got %r"
                    % (n, tail, tuple(arr.shape)))
            if rows is None:
                rows = arr.shape[0]
            elif arr.shape[0] != rows:
                raise MXNetError(
                    "inputs disagree on batch rows (%d vs %d)"
                    % (rows, arr.shape[0]))
            feed[n] = arr
        if rows < 1:
            raise MXNetError("empty request (0 rows)")
        if rows > self._ladder.max_rung:
            raise MXNetError(
                "request rows %d exceed the largest ladder rung %d — "
                "split the request or extend MXNET_TPU_SERVE_LADDER"
                % (rows, self._ladder.max_rung))
        return feed, rows

    # ------------------------------------------------------------- shedding
    def _count_shed(self, reason, req, detail):
        from ..telemetry import flight
        self._m_shed.labels(reason=reason).inc()
        self._m_requests.labels(outcome="shed").inc()
        if req.trace is not None:
            # the trace outlives the refusal: mark it shed so tail-
            # sampling ALWAYS keeps it and trace_top can explain it
            tracing.set_trace_status(req.trace, "shed",
                                     shed_reason=reason, rid=req.rid)
        extra = {} if req.trace is None else \
            {"trace_id": req.trace.trace_id}
        flight.record("request_shed", reason=reason, rid=req.rid,
                      rows=req.rows,
                      waited_ms=round(
                          (time.monotonic() - req.enqueue_t) * 1e3, 3),
                      detail="rid %d: %s" % (req.rid, detail), **extra)

    def _shed_queued(self, req, reason, detail):
        self._count_shed(reason, req, detail)
        req.error = RequestShed(reason, detail, rid=req.rid)
        req.done.set()

    # ------------------------------------------------------------ scheduler
    def _run(self):
        while True:
            batch = self._collect()
            if batch is None:
                return
            if batch:
                self._dispatch(batch)

    def _collect(self):
        """Wait out the batching window and select the EDF batch.
        Returns None on stop, possibly-empty list otherwise."""
        with self._cv:
            while not self._pending and not self._stopped:
                self._cv.wait()
            if self._stopped:
                return None
            # window anchored at the OLDEST queued request: it has
            # already waited, so its window credit is spent first
            window_end = min(r.enqueue_t for r in self._pending) \
                + self._window
            while (not self._stopped
                   and sum(r.rows for r in self._pending)
                   < self._ladder.max_rung):
                left = window_end - time.monotonic()
                if left <= 0:
                    break
                self._cv.wait(left)
            if self._stopped:
                return None
            # EDF: most urgent first, into the largest rung that the
            # queue reaches; overflow stays queued for the next round
            self._pending.sort(key=lambda r: r.deadline)
            batch, rows = [], 0
            for req in list(self._pending):
                if rows + req.rows > self._ladder.max_rung:
                    break
                batch.append(req)
                rows += req.rows
            for req in batch:
                self._pending.remove(req)
                req.dequeue_t = time.monotonic()
            self._m_depth.set(len(self._pending))
            return batch

    def _dispatch(self, batch):
        from ..telemetry import flight
        from .. import resilience
        # deadline feasibility at the LAST moment before TPU time is
        # spent; shedding shrinks the batch, which can shrink the rung
        # and the estimate, so iterate to a fixed point
        while batch:
            rows = sum(r.rows for r in batch)
            rung = self._ladder.pick_rung(rows)
            est = self._ladder.estimate_wall(rung)
            now = time.monotonic()
            hopeless = [r for r in batch if r.deadline - now < est]
            if not hopeless:
                break
            for req in hopeless:
                batch.remove(req)
                self._shed_queued(
                    req, "deadline",
                    "%.1fms left < estimated rung-%d wall %.1fms"
                    % ((req.deadline - now) * 1e3, rung, est * 1e3))
        if not batch:
            return
        # batch fan-in: ONE dispatch span id is recorded into EVERY
        # member trace (each parented under that trace's root) with
        # span links naming all member roots, so trace_top can walk
        # from any member to its batchmates
        traced = [r for r in batch if r.trace is not None]
        links = [{"trace_id": r.trace.trace_id,
                  "span_id": r.trace.span_id} for r in traced]
        disp_sid = tracing.new_span_id() if traced else None
        t_pad = time.monotonic()
        feed = {}
        for n in self._ladder.input_names:
            stacked = np.concatenate([r.feed[n] for r in batch], axis=0) \
                if len(batch) > 1 else batch[0].feed[n]
            feed[n] = pad_batch(stacked, rung)
        t_disp = time.monotonic()
        # attach the first member's context (scheduler thread has none
        # of its own) so the ladder's tracing.annotate() calls and any
        # fault delay land on THIS dispatch
        dctx = prev_ctx = None
        if traced:
            home = traced[0].trace
            dctx = tracing.TraceContext(home.trace_id, disp_sid,
                                        home.span_id)
            prev_ctx = tracing.attach(dctx)
        try:
            resilience.fault_point("serve.dispatch")
            outs = self._ladder.dispatch(rung, feed)
        except BaseException as e:  # mxlint: allow-broad-except(fail fast: every request of a poisoned batch gets THE error and the scheduler keeps draining — a wedged queue would turn one bad dispatch into an outage)
            t_err = time.monotonic()
            notes = tracing.take_annotations()
            if dctx is not None:
                tracing.detach(prev_ctx)
            epoch_off = time.time() - time.monotonic()
            for req in batch:
                if req.trace is not None:
                    attrs = dict(notes, rung=rung, rows=rows,
                                 requests=len(batch),
                                 error=str(e)[:200])
                    tracing.record_span(
                        req.trace, "serve.dispatch",
                        t_disp + epoch_off, t_err - t_disp,
                        attrs=attrs, links=links, status="error",
                        span_id=disp_sid)
                req.error = e if isinstance(e, Exception) else \
                    MXNetError("dispatch aborted: %r" % (e,))
                req.done.set()
            self._m_requests.labels(outcome="error").inc(len(batch))
            flight.record("rung_dispatch", rung=rung, rows=rows,
                          requests=len(batch), error=str(e)[:200])
            if not isinstance(e, Exception):
                raise
            return
        t_done = time.monotonic()
        notes = tracing.take_annotations()
        if dctx is not None:
            tracing.detach(prev_ctx)
        wall = t_done - t_disp
        self._ladder.observe_wall(rung, wall)
        self._m_rung.labels(rung=str(rung)).inc()
        self._m_occupancy.labels(rung=str(rung)).observe(
            rows / float(rung))
        flight.record("rung_dispatch", rung=rung, rows=rows,
                      requests=len(batch),
                      wall_ms=round(wall * 1e3, 3),
                      **({"trace_id": traced[0].trace.trace_id}
                         if traced else {}))
        epoch_off = time.time() - time.monotonic()
        disp_attrs = dict(notes, rung=rung, rows=rows,
                          requests=len(batch), pad_rows=rung - rows)
        lat = self._m_latency
        off = 0
        for req in batch:
            req.outputs = [o[off:off + req.rows] if getattr(o, "ndim", 0)
                           else o for o in outs]
            off += req.rows
            # spans are recorded BEFORE done.set(): once the submitter
            # wakes, its root trace may exit and stop accepting spans
            if req.trace is not None:
                ctx = req.trace
                tracing.record_span(
                    ctx, "serve.queue", req.enqueue_t + epoch_off,
                    req.dequeue_t - req.enqueue_t,
                    attrs={"rid": req.rid})
                tracing.record_span(
                    ctx, "serve.coalesce", req.dequeue_t + epoch_off,
                    t_pad - req.dequeue_t,
                    attrs={"requests": len(batch)})
                tracing.record_span(
                    ctx, "serve.pad", t_pad + epoch_off, t_disp - t_pad,
                    attrs={"rows": rows, "pad_rows": rung - rows})
                tracing.record_span(
                    ctx, "serve.dispatch", t_disp + epoch_off, wall,
                    attrs=disp_attrs, links=links, span_id=disp_sid)
                t_slice = time.monotonic()
                tracing.record_span(
                    ctx, "serve.slice", t_done + epoch_off,
                    t_slice - t_done, attrs={"rows": req.rows})
            req.done.set()
            lat.labels(segment="queue").observe(
                req.dequeue_t - req.enqueue_t)
            lat.labels(segment="pad").observe(t_disp - t_pad)
            lat.labels(segment="dispatch").observe(wall)
            lat.labels(segment="total").observe(
                t_done - req.enqueue_t,
                exemplar=None if req.trace is None
                else req.trace.trace_id)
        self._m_requests.labels(outcome="ok").inc(len(batch))
