"""Production serving tier: batch-ladder AOT on the predict path.

The deployment story so far was ``predictor.py``'s one-shot
MXPredCreate/Forward/GetOutput surface; this package turns it into a
serving runtime in the spirit of full-program TPU compilation
(arXiv:1810.09868 — compile everything ahead of time, dispatch only):

* :class:`~mxnet_tpu.serving.ladder.BatchLadder` — AOT-compiles the
  model at a configured ladder of batch sizes at STARTUP via
  ``telemetry.memory.planned_executable`` (each rung picks up the
  tuned-kernel cache and the committed ``graph_plan`` entry, whose
  digest is batch-size-independent by design), memlive-budget-checks
  the largest rung BEFORE any compile, and never compiles again:
  partial batches pad to the nearest rung and slice outputs
  (:func:`mxnet_tpu.predictor.pad_batch`);
* :class:`~mxnet_tpu.serving.batcher.Batcher` — a thread-safe request
  queue that coalesces requests into the largest rung that fills
  within a batching window, schedules earliest-deadline-first, and
  sheds load EARLY (bounded queue depth; a request whose remaining
  deadline cannot cover the estimated rung wall is refused before
  burning TPU time);
* :class:`~mxnet_tpu.serving.server.Server` — the stdlib HTTP front
  door (``POST /predict``, ``GET /healthz``, the Prometheus
  ``/metrics`` exposition), run standalone or as a multi-replica fleet
  under ``tools/launch.py --fleet`` supervision (a killed replica is
  restarted alone; its in-flight requests fail fast, peers keep
  serving).

``python -m mxnet_tpu.serving --model mlp`` starts a replica on a zoo
model; ``tools/serve_top.py`` names the hot rung and the dominant shed
reason from the exported metrics; ``bench.py --serve`` is the
closed-loop load test.  See docs/api/serving.md.
"""
from __future__ import annotations

from .ladder import BatchLadder, ladder_rungs, DEFAULT_RUNGS
from .batcher import Batcher, RequestShed
from .server import Server

__all__ = ["BatchLadder", "ladder_rungs", "DEFAULT_RUNGS",
           "Batcher", "RequestShed", "Server"]
