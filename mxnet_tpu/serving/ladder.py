"""Batch ladder: startup AOT compilation of the predict path.

One :class:`~mxnet_tpu.predictor.Predictor` handle per batch size is
the documented reference pattern (MXPredReshape hands out independent
handles over shared weights); the ladder builds the whole set at
startup and owns the "zero compiles in the request path" contract:

* every rung dispatches through the executor's AOT cache
  (``telemetry.memory.planned_executable`` — the same compile that
  registers the rung's memory plan runs the requests), so after
  :meth:`BatchLadder.warm_up` the ``mxtpu_compile_total`` counter
  stays flat under traffic;
* the LARGEST rung is budget-checked by the static liveness analyzer
  (``analysis.memlive``, MXG017) BEFORE anything compiles — a ladder
  that cannot fit fails at startup with the per-category breakdown,
  not with a mid-traffic OOM;
* rung walls are priced for the deadline scheduler: a fitted cost
  model (``MXNET_TPU_SERVE_COST_MODEL`` → ``autotune.model``) seeds
  the estimate from the compiled program's flops/bytes, warm-up
  measurements replace it, and every live dispatch folds into an EWMA
  (:meth:`BatchLadder.observe_wall`).

The ladder itself is NOT thread-safe — one executor dispatches at a
time.  The :class:`~mxnet_tpu.serving.batcher.Batcher` owns it from a
single scheduler thread.
"""
from __future__ import annotations

import logging
import os
import time

import numpy as np

from ..base import MXNetError
from ..predictor import Predictor, pad_batch
from ..telemetry import tracing

__all__ = ["BatchLadder", "ladder_rungs", "DEFAULT_RUNGS"]

log = logging.getLogger(__name__)

#: default rung set: powers-of-4 ladder (MXNET_TPU_SERVE_LADDER overrides)
DEFAULT_RUNGS = (1, 4, 16, 64)

#: EWMA weight of a newly observed dispatch wall
_EWMA_ALPHA = 0.2


def ladder_rungs(spec=None):
    """Parse a ladder spec (``"1,4,16,64"``) into a sorted tuple of
    distinct positive batch sizes.  ``spec=None`` reads
    ``MXNET_TPU_SERVE_LADDER``; empty/unset falls back to
    :data:`DEFAULT_RUNGS`."""
    if spec is None:
        spec = os.environ.get("MXNET_TPU_SERVE_LADDER", "")
    if isinstance(spec, (tuple, list)):
        rungs = tuple(sorted({int(r) for r in spec}))
    else:
        toks = [t for t in str(spec).replace(";", ",").split(",")
                if t.strip()]
        if not toks:
            return DEFAULT_RUNGS
        try:
            rungs = tuple(sorted({int(t) for t in toks}))
        except ValueError:
            raise MXNetError(
                "bad ladder spec %r (MXNET_TPU_SERVE_LADDER): expected "
                "comma-separated batch sizes like '1,4,16,64'" % (spec,))
    if not rungs or rungs[0] < 1:
        raise MXNetError("ladder rungs must be positive, got %r"
                         % (rungs,))
    return rungs


class BatchLadder:
    """AOT-compiled predictors at a ladder of batch sizes.

    ``predictor``: a bound :class:`~mxnet_tpu.predictor.Predictor` (its
    own batch size need not be a rung — each rung is an independent
    ``reshaped()`` handle over the shared weights).  ``rungs``: ladder
    spec (see :func:`ladder_rungs`).  ``budget_check``: run the memlive
    MXG017 gate on the largest rung before any compile (on by
    default; it is skipped silently when no device budget is armed —
    see ``MXNET_TPU_MEMORY_BUDGET`` / ``MXNET_TPU_HBM_LIMIT_BYTES``).
    ``warm``: compile + measure every rung now (pass ``False`` to defer
    to an explicit :meth:`warm_up`)."""

    def __init__(self, predictor, rungs=None, budget_check=True,
                 warm=True):
        if not isinstance(predictor, Predictor):
            raise MXNetError("BatchLadder needs a Predictor, got %r"
                             % type(predictor).__name__)
        self._rungs = ladder_rungs(rungs)
        self._input_names = list(predictor._input_names)
        # per-input trailing (non-batch) dims + dtype from the bound
        # executor: the rung handles only ever change axis 0
        self._tails, self._dtypes = {}, {}
        for n in self._input_names:
            arr = predictor._executor.arg_dict[n]
            self._tails[n] = tuple(arr.shape)[1:]
            self._dtypes[n] = np.dtype(arr.dtype)
        if budget_check:
            self._budget_gate(predictor, self._rungs[-1])
        self._preds = {}
        for r in self._rungs:
            shapes = {n: (r,) + self._tails[n] for n in self._input_names}
            self._preds[r] = predictor.reshaped(shapes)
        self._wall = {}          # rung -> EWMA wall estimate (seconds)
        self._cost_est = {}      # rung -> cost-model estimate (seconds)
        self._model = self._load_cost_model()
        self._warmed = False
        if warm:
            self.warm_up()

    # ------------------------------------------------------------ properties
    @property
    def rungs(self):
        """The sorted rung tuple."""
        return self._rungs

    @property
    def max_rung(self):
        return self._rungs[-1]

    @property
    def input_names(self):
        return list(self._input_names)

    @property
    def warmed(self):
        """True once every rung has been AOT-compiled and measured."""
        return self._warmed

    def input_tail(self, name):
        """Non-batch dims of one input (the shape a request row must
        have)."""
        return self._tails[name]

    def input_dtype(self, name):
        return self._dtypes[name]

    # ------------------------------------------------------------ budget gate
    @staticmethod
    def _budget_gate(predictor, rung):
        """Static-liveness (MXG017) check of the LARGEST rung before
        any compile: the whole ladder shares weights, so the biggest
        rung's predicted peak bounds the ladder's footprint.  Analysis
        failures degrade to a debug log (the gate is advisory
        infrastructure); an actual budget excess raises."""
        findings = []
        try:
            from ..analysis import memlive
            from ..analysis.verifier import Report
            shapes = {}
            for n in predictor._input_names:
                bound = tuple(predictor._executor.arg_dict[n].shape)
                shapes[n] = (rung,) + bound[1:]
            rep = Report()
            memlive.check_memory(predictor._symbol, shapes, report=rep,
                                 is_train=False, record=True,
                                 program="serve.rung%d" % rung)
            findings = [str(d) for d in rep if d.rule == "MXG017"]
        except MXNetError:
            raise
        except Exception as e:  # mxlint: allow-broad-except(the static analyzer may not cover every op; an unanalyzable graph skips the gate rather than blocking serving — the dispatch-time check_budget still guards the compile)
            log.debug("serving ladder: memlive budget gate skipped "
                      "(%s: %s)", type(e).__name__, e)
        if findings:
            raise MXNetError(
                "serving ladder refused: largest rung %d exceeds the "
                "armed HBM budget before compile (shrink "
                "MXNET_TPU_SERVE_LADDER or raise "
                "MXNET_TPU_MEMORY_BUDGET):\n  %s"
                % (rung, "\n  ".join(findings)))

    # ------------------------------------------------------------ cost model
    @staticmethod
    def _load_cost_model():
        path = os.environ.get("MXNET_TPU_SERVE_COST_MODEL", "")
        if not path:
            return None
        try:
            from ..autotune.model import load_model
            return load_model(path)
        except Exception as e:  # mxlint: allow-broad-except(a stale/foreign model file must not stop serving; the ladder falls back to measured walls)
            log.warning("serving ladder: cost model %r unusable (%s); "
                        "pricing rungs from warm-up measurements", path, e)
            return None

    def _price_rung(self, rung):
        """Cost-model estimate of one rung's wall from the compiled
        executable's flops/bytes (None when no model is configured or
        the analyses are unavailable)."""
        if self._model is None:
            return None
        try:
            from ..telemetry import memory as tmem
            exes = getattr(self._preds[rung]._executor, "_aot_exes", {})
            for (prog, _fid), exe in exes.items():
                if prog == "executor.forward":
                    ca = tmem.cost_analysis_of(exe)
                    if ca and ca.get("flops"):
                        est = float(self._model.predict(
                            flops=ca["flops"],
                            bytes_accessed=ca.get("bytes_accessed", 0)))
                        if est > 0:
                            return est
        except Exception as e:  # mxlint: allow-broad-except(cost pricing is an estimate source, never a failure source)
            log.debug("serving ladder: cost pricing of rung %d failed "
                      "(%s)", rung, e)
        return None

    # -------------------------------------------------------------- warm-up
    def warm_up(self):
        """Compile and measure every rung (ascending).  The first
        forward per rung triggers the one AOT compile
        (``planned_executable`` registers + budget-checks its memory
        plan); the second measures the steady-state wall that seeds the
        scheduler's estimate.  Returns {rung: wall_seconds}."""
        from ..telemetry import compile as _compile
        _compile.install()
        for r in self._rungs:
            feed = {n: np.zeros((r,) + self._tails[n],
                                dtype=self._dtypes[n])
                    for n in self._input_names}
            pred = self._preds[r]
            pred.forward(**feed)
            pred.get_output(0)              # close the compile dispatch
            t0 = time.perf_counter()
            pred.forward(**feed)
            pred.get_output(0)
            wall = time.perf_counter() - t0
            self._wall[r] = wall
            est = self._price_rung(r)
            if est is not None:
                self._cost_est[r] = est
            log.info("serving ladder: rung %d warm (wall %.2f ms%s)",
                     r, wall * 1e3,
                     ", cost model %.2f ms" % (est * 1e3)
                     if est is not None else "")
        self._warmed = True
        return dict(self._wall)

    # ------------------------------------------------------------- dispatch
    def pick_rung(self, rows):
        """Smallest rung that fits ``rows`` (None when rows exceed the
        largest rung — the caller splits or refuses)."""
        for r in self._rungs:
            if rows <= r:
                return r
        return None

    def estimate_wall(self, rung):
        """Scheduler-facing wall estimate for one rung: the measured
        EWMA when available, else the cost-model price, else the
        largest known wall (conservative — an unknown rung must not
        look free to the deadline check)."""
        if rung in self._wall:
            return self._wall[rung]
        if rung in self._cost_est:
            return self._cost_est[rung]
        known = list(self._wall.values()) or list(self._cost_est.values())
        return max(known) if known else 0.0

    def observe_wall(self, rung, wall):
        """Fold a measured dispatch wall into the rung's EWMA."""
        prev = self._wall.get(rung)
        self._wall[rung] = wall if prev is None else \
            (1.0 - _EWMA_ALPHA) * prev + _EWMA_ALPHA * wall

    def dispatch(self, rung, feed):
        """Run one batch at ``rung``.  ``feed``: name -> array with
        EXACTLY ``rung`` rows (the batcher pads with
        :func:`~mxnet_tpu.predictor.pad_batch` before calling).
        Returns the list of output arrays (all ``rung`` rows — the
        caller slices per request).  Never compiles after warm-up: the
        executor dispatches the cached AOT executable."""
        if rung not in self._preds:
            raise MXNetError("no rung %r in ladder %r"
                             % (rung, self._rungs))
        pred = self._preds[rung]
        pad_rows = 0
        for n in self._input_names:
            arr = feed[n]
            if arr.shape[0] != rung:
                pad_rows = max(pad_rows, rung - arr.shape[0])
                arr = pad_batch(arr, rung)
            pred.set_input(n, arr)
        pred._partial_rows.clear()      # the batcher owns slicing
        pred._executor.forward(is_train=False)
        outs = pred._executor.outputs
        # detail for the batcher's serve.dispatch trace span (no-op
        # without an attached context): the rung actually run, rows the
        # ladder itself had to pad, and the slice handed back for the
        # batcher to split per request
        tracing.annotate(ladder_rung=rung, ladder_pad_rows=pad_rows,
                         ladder_slice_outputs=len(outs))
        return [outs[i].asnumpy() for i in range(len(outs))]

    def describe(self):
        """Structured ladder state for /healthz and serve_top."""
        return {
            "rungs": list(self._rungs),
            "warmed": self._warmed,
            "wall_ms": {str(r): round(self._wall[r] * 1e3, 3)
                        for r in sorted(self._wall)},
            "cost_model_ms": {str(r): round(self._cost_est[r] * 1e3, 3)
                              for r in sorted(self._cost_est)},
            "inputs": {n: {"tail": list(self._tails[n]),
                           "dtype": str(self._dtypes[n])}
                       for n in self._input_names},
        }
