"""Executor: a bound, jit-compiled symbolic graph.

Reference: ``include/mxnet/executor.h`` + ``src/executor/graph_executor.cc``
(the Init pass pipeline, SURVEY §3.4) and the python wrapper
``python/mxnet/executor.py``.  TPU-native design: ``bind`` closes the Symbol
DAG over its argument arrays; ``forward`` runs one ``jax.jit``-compiled
function (XLA performs gradient, memory planning, fusion — the whole
reference pass pipeline); ``backward`` runs a jitted ``jax.vjp`` of the same
trace, re-using the forward PRNG key so stochastic ops (Dropout) replay
bit-identically (the reference reuses saved forward state instead,
``autograd.cc:149-240``).

grad_req semantics match the reference ``OpReqType`` (`operator.h:24`):
'write' overwrites the grad array, 'add' accumulates (kAddTo — model-parallel
LSTM relies on it), 'null' skips.
"""
from __future__ import annotations

import functools

from .base import MXNetError
from .context import Context, current_context
from .ndarray import NDArray, zeros as _nd_zeros
from .symbol import eval_graph, _classify_vars

__all__ = ["Executor"]


def _as_jnp(v, dtype):
    import numpy as np
    import jax.numpy as jnp
    return jnp.asarray(np.asarray(v), dtype=dtype)


def _normalize(values, names, kind, default_ctor=None):
    """Accept list/tuple ordered by ``names`` or a dict; return dict."""
    if values is None:
        return {}
    if isinstance(values, dict):
        return dict(values)
    if isinstance(values, (list, tuple)):
        if len(values) != len(names):
            raise MXNetError(
                "%s: expected %d arrays, got %d" % (kind, len(names),
                                                    len(values)))
        return dict(zip(names, values))
    raise TypeError("%s must be list or dict" % kind)


class Executor:
    def __init__(self, symbol, ctx, args, args_grad=None, grad_req="write",
                 aux_states=None, group2ctx=None, shared_exec=None,
                 strict=False):
        self._symbol = symbol
        self._ctx = ctx if isinstance(ctx, Context) else current_context()
        self._group2ctx = group2ctx or {}
        self._monitor_callback = None
        self._monitor_all = False
        # jit-safe stats monitor (telemetry.numerics): per matched node
        # output, a small in-graph stat bundle returned as extra outputs
        # of ONE compiled program — the default Monitor path; the eager
        # per-node _forward_monitored route is opt-in (Monitor(eager=True))
        self._stats_cb = None
        self._stats_pattern = None
        self._stats_active = None
        self._stats_cache = {}

        # model-parallel placement: ctx_group attr -> device (reference
        # AssignContext + PlaceDevice, graph_executor.cc:249-341)
        self._device_map = {}
        if self._group2ctx:
            topo_nodes = symbol._topo()
            for node in topo_nodes:
                if node.is_variable:
                    continue
                grp = node.raw_attr.get("ctx_group")
                dev_ctx = self._group2ctx.get(grp, self._ctx) if grp \
                    else self._ctx
                self._device_map[id(node)] = dev_ctx.jax_device()

        self._topo = symbol._topo()
        self._arg_nodes, self._aux_nodes = _classify_vars(self._topo)
        self._arg_names = [n.name for n in self._arg_nodes]
        self._aux_names = [n.name for n in self._aux_nodes]
        self._output_names = symbol.list_outputs()

        self.arg_dict = _normalize(args, self._arg_names, "args")
        missing = [n for n in self._arg_names if n not in self.arg_dict]
        if missing:
            raise MXNetError("bind: missing argument arrays for %s" % missing)
        self.aux_dict = _normalize(aux_states, self._aux_names, "aux_states")
        for n in self._aux_names:
            if n not in self.aux_dict:
                raise MXNetError("bind: missing auxiliary state %r" % n)

        if isinstance(grad_req, str):
            self._grad_req = {n: grad_req for n in self._arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self._grad_req = dict(zip(self._arg_names, grad_req))
        else:
            self._grad_req = {n: grad_req.get(n, "null")
                              for n in self._arg_names}

        self.grad_dict = _normalize(args_grad, self._arg_names, "args_grad")
        for n, req in self._grad_req.items():
            if req != "null" and n not in self.grad_dict:
                src = self.arg_dict[n]
                self.grad_dict[n] = _nd_zeros(src.shape, ctx=self._ctx,
                                              dtype=src.dtype)

        # strict bind: run the static graph verifier over the EXACT
        # shapes/dtypes being bound, before any jit compile is attempted
        # (the bind-time equivalent of the reference's InferShape pass,
        # with node-level diagnostics instead of a mid-bind throw).
        # MXNET_TPU_STRICT_BIND=1 turns it on globally.
        from . import config as _config
        if strict or _config.get_bool("MXNET_TPU_STRICT_BIND"):
            from .analysis import verify_symbol
            shapes = {n: tuple(self.arg_dict[n].shape)
                      for n in self._arg_names}
            shapes.update({n: tuple(self.aux_dict[n].shape)
                           for n in self._aux_names})
            types = {n: self.arg_dict[n].dtype for n in self._arg_names}
            types.update({n: self.aux_dict[n].dtype
                          for n in self._aux_names})
            # memory-liveness leg (analysis.memlive, MXG017-021): armed
            # only when a budget signal exists — device capacity (or
            # MXNET_TPU_HBM_LIMIT_BYTES) with MXNET_TPU_MEMORY_BUDGET
            # > 0 — so an over-budget graph is rejected HERE, naming
            # its peak node, before any XLA compile is attempted.
            memory = None
            from .telemetry import memory as _tmem
            if _tmem.budget_fraction() > 0 \
                    and _tmem.device_capacity_bytes():
                is_train = any(req != "null"
                               for req in self._grad_req.values())
                memory = {
                    "is_train": is_train,
                    "inputs": {n for n in self._arg_names
                               if self._grad_req.get(n) == "null"},
                    "donate": (),
                    "record": True,
                    "program": ("executor.fused" if is_train
                                else "executor.forward"),
                }
            verify_symbol(symbol, shapes=shapes, types=types,
                          memory=memory).raise_if_errors(
                              "bind strict=True")

        # block-granularity fusion (analysis.fusion): the enable flag is
        # captured at bind time (trace flags are read when jit traces,
        # which happens lazily at first call — long after any caller's
        # context manager exited), and re-activated around every
        # eval_graph trace below so forward, backward, and the fused
        # train path all lower through the same plan.
        from .ops import fused as _fused_mod
        self._block_fusion = _fused_mod.block_fusion_enabled()
        # plan-search decisions (analysis.plansearch): an ambient
        # plan_decisions context is captured like the fusion flag;
        # otherwise the committed graph_plan tuning-cache entry for
        # this graph (keyed by structural digest + trace layout +
        # backend) is consulted ONCE here — a hit activates the
        # searched plan around every trace below, a miss stays greedy
        # with zero per-trace cost (MXNET_TPU_PLAN_SEARCH=off skips
        # the lookup entirely).
        from .analysis import fusion as _fusion_mod
        self._plan_decisions = _fusion_mod.active_decisions()
        if self._plan_decisions is None and self._block_fusion:
            from .analysis import plansearch as _plansearch
            from .ops.nn import current_image_layout
            self._plan_decisions = _plansearch.committed_decisions(
                self._topo, symbol._entries, current_image_layout())

        self._outputs = None
        self._last_key = None
        self._last_train = False
        self._fwd_cache = {}
        self._bwd_cache = {}
        # AOT executables keyed (program, id(jit fn)) — the memory plan
        # comes from the same compile that runs the graph (see
        # telemetry.memory.planned_executable)
        self._aot_exes = {}
        # costdb dispatch scope: process-unique per executor, so id(fn)
        # reuse after another instance's GC cannot alias its counters
        from .telemetry import costdb as _costdb
        self._costdb_scope = _costdb.next_scope()
        # is_loss flag per head (loss heads seed ones, others zeros, when
        # backward() is called without explicit head gradients)
        self._head_is_loss = tuple(
            bool(node.op is not None and node.op.is_loss)
            for (node, _i) in symbol._entries)

    # ------------------------------------------------------------- properties
    @property
    def outputs(self):
        if self._outputs is None:
            raise MXNetError("call forward() first")
        return self._outputs

    @property
    def output_dict(self):
        return dict(zip(self._output_names, self.outputs))

    @property
    def arg_arrays(self):
        return [self.arg_dict[n] for n in self._arg_names]

    @property
    def grad_arrays(self):
        return [self.grad_dict.get(n) if self._grad_req[n] != "null" else None
                for n in self._arg_names]

    @property
    def aux_arrays(self):
        return [self.aux_dict[n] for n in self._aux_names]

    @property
    def grad_req(self):
        return dict(self._grad_req)

    # -------------------------------------------------------------- compile
    def _var_ids(self):
        return [id(n) for n in self._arg_nodes + self._aux_nodes]

    def _get_forward_fn(self, is_train):
        fn = self._fwd_cache.get(is_train)
        if fn is not None:
            return fn
        import jax
        topo, entries = self._topo, self._symbol._entries
        var_ids = self._var_ids()

        from .ops.fused import block_fusion
        from .analysis.fusion import plan_decisions

        def raw(vals, key):
            var_values = dict(zip(var_ids, vals))
            bsz = vals[0].shape[0] if vals and vals[0].ndim else None
            with block_fusion(self._block_fusion), \
                    plan_decisions(self._plan_decisions):
                heads, aux_updates = eval_graph(
                    topo, entries, var_values, is_train=is_train,
                    key=key, batch_size=bsz,
                    device_map=self._device_map)
            n_args = len(self._arg_nodes)
            aux_out = [aux_updates.get(id(n), vals[n_args + i])
                       for i, n in enumerate(self._aux_nodes)]
            return heads, aux_out

        fn = self._compile(raw)
        self._fwd_cache[is_train] = fn
        return fn

    def _multi_device_placed(self):
        return len(set(self._device_map.values())) > 1

    def _compile(self, raw):
        """One XLA program even for a ctx_group-placed graph: the per-node
        jax.device_put calls inside eval_graph become sharding constraints
        under jit, and the GSPMD partitioner pins each segment to its
        device with cross-device copies at the boundaries — the compiled
        equivalent of the reference's PlaceDevice + _CrossDeviceCopy pass
        (graph_executor.cc:249-341), with fusion and donation intact."""
        import jax
        return jax.jit(raw)

    def _place_heads(self, heads):
        """Reference parity: a head produced by a ctx_group-placed node
        lives on that group's device.  jit returns outputs on the default
        device, so placed heads take one device-to-device copy here."""
        if not self._multi_device_placed():
            return heads
        import jax
        placed = []
        for h, (node, _i) in zip(heads, self._symbol._entries):
            dev = self._device_map.get(id(node))
            placed.append(jax.device_put(h, dev) if dev is not None else h)
        return placed

    @staticmethod
    def _maybe_mirror(f):
        """See :func:`mxnet_tpu.ops.nn.maybe_mirror` (kept as a
        late-binding hook so tests can assert the wiring)."""
        from .ops import nn as _nn
        return _nn.maybe_mirror(f)

    def _get_backward_fn(self, with_head_grads):
        key_ = with_head_grads
        fn = self._bwd_cache.get(key_)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp
        topo, entries = self._topo, self._symbol._entries
        var_ids = self._var_ids()
        diff_idx = tuple(i for i, n in enumerate(self._arg_names)
                         if self._grad_req[n] != "null")
        head_is_loss = self._head_is_loss

        from .ops.fused import block_fusion
        from .analysis.fusion import plan_decisions

        def raw(vals, key, out_grads):
            diff_vals = tuple(vals[i] for i in diff_idx)

            def f(diff):
                full = list(vals)
                for j, i in enumerate(diff_idx):
                    full[i] = diff[j]
                var_values = dict(zip(var_ids, full))
                bsz = full[0].shape[0] if full and full[0].ndim else None
                with block_fusion(self._block_fusion), \
                        plan_decisions(self._plan_decisions):
                    heads, _aux = eval_graph(topo, entries, var_values,
                                             is_train=True, key=key,
                                             batch_size=bsz,
                                             device_map=self._device_map)
                return heads

            heads, vjp = jax.vjp(self._maybe_mirror(f), diff_vals)
            if with_head_grads:
                cot = list(out_grads)
            else:
                cot = [jnp.ones_like(h) if is_loss else jnp.zeros_like(h)
                       for h, is_loss in zip(heads, head_is_loss)]
            (grads,) = vjp(list(cot))
            return grads

        fn = self._compile(raw)
        self._bwd_cache[key_] = fn
        return fn

    def _get_fused_fn(self):
        """Forward + backward + aux update as ONE compiled program — the
        training hot path (Module.forward_backward).  XLA shares the
        forward computation between the primal and the vjp, which the
        separate forward()/backward() pair cannot."""
        fn = self._bwd_cache.get("fused")
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp
        topo, entries = self._topo, self._symbol._entries
        var_ids = self._var_ids()
        diff_idx = tuple(i for i, n in enumerate(self._arg_names)
                         if self._grad_req[n] != "null")
        head_is_loss = self._head_is_loss
        n_args = len(self._arg_nodes)

        from .ops.fused import block_fusion
        from .analysis.fusion import plan_decisions

        def raw(vals, key):
            diff_vals = tuple(vals[i] for i in diff_idx)

            def f(diff):
                full = list(vals)
                for j, i in enumerate(diff_idx):
                    full[i] = diff[j]
                var_values = dict(zip(var_ids, full))
                bsz = full[0].shape[0] if full and full[0].ndim else None
                with block_fusion(self._block_fusion), \
                        plan_decisions(self._plan_decisions):
                    heads, aux_upd = eval_graph(
                        topo, entries, var_values, is_train=True,
                        key=key, batch_size=bsz,
                        device_map=self._device_map)
                return heads, aux_upd

            heads, vjp, aux_upd = jax.vjp(self._maybe_mirror(f), diff_vals,
                                          has_aux=True)
            cot = [jnp.ones_like(h) if il else jnp.zeros_like(h)
                   for h, il in zip(heads, head_is_loss)]
            (grads,) = vjp(list(cot))
            aux_out = [aux_upd.get(id(n), vals[n_args + i])
                       for i, n in enumerate(self._aux_nodes)]
            return heads, aux_out, grads

        fn = self._compile(raw)
        self._bwd_cache["fused"] = fn
        return fn

    def _dispatch(self, program, fn, args):
        """Run a compiled graph function through its AOT executable,
        registering/budget-checking its memory plan on first use and
        annotating a backend RESOURCE_EXHAUSTED with the plan + live
        HBM forensics (telemetry.memory.dispatch_planned semantics:
        aval drift downgrades to the jit wrapper permanently).

        Cost-database seam (telemetry.costdb): fused blocks traced by
        the compile bind to this program, and sampled dispatches
        (MXNET_TPU_COSTDB_SAMPLE) measure a synchronized wall time
        that lands — with the program's cost_analysis flops/bytes —
        as persistent MFU/roofline records.  Off the hot path: the
        unsampled cost is one counter bump."""
        from .telemetry import costdb as _costdb, memory as _tmem
        obs = _costdb.begin_dispatch(
            program, key=(self._costdb_scope, id(fn)))
        try:
            with _tmem.annotate_oom(program):
                out = _tmem.dispatch_planned(self._aot_exes, program,
                                             fn, args)
        except BaseException:  # mxlint: allow-broad-except(re-raised unchanged — the handler only closes the costdb observation bind-only, so the compile's traced signatures cannot dangle and attach to the next program dispatched)
            _costdb.end_dispatch(obs, failed=True)
            raise
        _costdb.end_dispatch(obs, out=out, args=args)
        return out

    def forward_backward(self, **kwargs):
        """Fused training step: outputs + gradients in one XLA program.
        Equivalent to forward(is_train=True) followed by backward()."""
        from . import telemetry
        with telemetry.span("executor.forward_backward",
                            category="executor"):
            return self._forward_backward(**kwargs)

    def _forward_backward(self, **kwargs):
        if self._monitor_callback is not None or self._stats_active_now():
            self.forward(is_train=True, **kwargs)
            self.backward()
            return self._outputs
        for k, v in kwargs.items():
            arr = self.arg_dict[k]
            arr._set_data(v.data.astype(arr.dtype) if isinstance(v, NDArray)
                          else _as_jnp(v, arr.dtype))
        from . import random as _random
        key = _random.take_key()
        self._last_key = key
        self._last_train = True
        fn = self._get_fused_fn()
        heads, aux_out, grads = self._dispatch(
            "executor.fused", fn, (self._gather_vals(), key))
        for n, upd in zip(self._aux_names, aux_out):
            self.aux_dict[n]._set_data(upd)
        diff_names = [n for n in self._arg_names
                      if self._grad_req[n] != "null"]
        for n, g in zip(diff_names, grads):
            tgt = self.grad_dict[n]
            if self._grad_req[n] == "add":
                tgt._set_data(tgt.data + g)
            else:
                tgt._set_data(g.astype(tgt.dtype))
        self._outputs = [NDArray(h) for h in self._place_heads(heads)]
        return self._outputs

    # ---------------------------------------------------------------- run
    def _gather_vals(self):
        return tuple([self.arg_dict[n].data for n in self._arg_names] +
                     [self.aux_dict[n].data for n in self._aux_names])

    def forward(self, is_train=False, **kwargs):
        """Run the forward graph.  kwargs update named input arrays
        (reference python/mxnet/executor.py:95)."""
        from . import telemetry
        with telemetry.span("executor.forward", category="executor"):
            return self._forward(is_train, **kwargs)

    def _forward(self, is_train=False, **kwargs):
        import numpy as np
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError("unknown input %r" % k)
            arr = self.arg_dict[k]
            if isinstance(v, NDArray):
                arr._set_data(v.data.astype(arr.dtype))
            else:
                import jax.numpy as jnp
                arr._set_data(jnp.asarray(np.asarray(v), dtype=arr.dtype))

        from . import random as _random
        key = _random.take_key()
        self._last_key = key
        self._last_train = bool(is_train)

        if self._monitor_callback is not None:
            heads, aux_out = self._forward_monitored(is_train, key)
        elif self._stats_active_now():
            heads, aux_out = self._forward_stats(bool(is_train), key)
        else:
            fn = self._get_forward_fn(bool(is_train))
            heads, aux_out = self._dispatch(
                "executor.forward", fn, (self._gather_vals(), key))
        if is_train:
            for n, upd in zip(self._aux_names, aux_out):
                self.aux_dict[n]._set_data(upd)
        self._outputs = [NDArray(h) for h in self._place_heads(heads)]
        return self._outputs

    def _stats_active_now(self):
        """True when the jit-safe stats monitor should run THIS call
        (installed, and its activation gate — Monitor's interval —
        says so)."""
        return self._stats_cb is not None and \
            (self._stats_active is None or self._stats_active())

    def _get_forward_stats_fn(self, is_train):
        """The jit-safe monitored forward: the same graph trace with a
        per-matched-node stat bundle (telemetry.numerics.tensor_stats —
        a handful of scalar reductions each) as extra outputs.  ONE
        compiled program, no per-node host sync; the per-node monitor
        trace path stays unfused, so every output is visible exactly as
        in the eager route."""
        pattern = self._stats_pattern
        key_ = (bool(is_train), pattern.pattern)
        hit = self._stats_cache.get(key_)
        if hit is not None:
            return hit
        import jax
        from .telemetry import numerics as _numerics
        topo, entries = self._topo, self._symbol._entries
        var_ids = self._var_ids()
        # matched names in TRACE (graph/topo) order — jit returns the
        # stats dict with pytree-sorted keys, but callbacks must fire
        # in the same order the eager monitored route delivers them
        order = []

        def raw(vals, key):
            stats = {}
            order.clear()     # retrace (new shapes) rebuilds the order

            def mon(name, val):
                if pattern.match(str(name)):
                    order.append(str(name))
                    stats[str(name)] = _numerics.tensor_stats(val)

            var_values = dict(zip(var_ids, vals))
            bsz = vals[0].shape[0] if vals and vals[0].ndim else None
            heads, aux_updates = eval_graph(
                topo, entries, var_values, is_train=is_train,
                key=key, monitor=mon, batch_size=bsz,
                device_map=self._device_map)
            n_args = len(self._arg_nodes)
            aux_out = [aux_updates.get(id(n), vals[n_args + i])
                       for i, n in enumerate(self._aux_nodes)]
            return heads, aux_out, stats

        hit = (self._compile(raw), order)
        self._stats_cache[key_] = hit
        return hit

    def _forward_stats(self, is_train, key):
        """Dispatch the stats-monitored forward and deliver each
        matched tensor's host stat bundle to the installed callback
        (one device fetch for ALL bundles, then per-name invocation in
        topo order — non-finite anomalies feed telemetry.numerics)."""
        import jax
        fn, order = self._get_forward_stats_fn(is_train)
        heads, aux_out, stats = self._dispatch(
            "executor.forward_stats", fn, (self._gather_vals(), key))
        host = jax.device_get(stats)
        host = {n: {k: (int(v) if k == "nonfinite" else float(v))
                    for k, v in st.items()}
                for n, st in host.items()}
        from .telemetry import numerics as _numerics
        _numerics.note_monitored(host, program="executor.forward_stats")
        cb = self._stats_cb
        for name in order if len(order) == len(host) else sorted(host):
            cb(name, host[name])
        return heads, aux_out

    def _forward_monitored(self, is_train, key):
        """Eager per-node execution with the monitor callback installed
        (reference GraphExecutor::ExecuteMonCallback, disables bulk exec)."""
        cb = self._monitor_callback

        def monitor(name, val):
            cb(name, NDArray(val))

        vals = self._gather_vals()
        var_values = dict(zip(self._var_ids(), vals))
        bsz = vals[0].shape[0] if vals and vals[0].ndim else None
        heads, aux_updates = eval_graph(
            self._topo, self._symbol._entries, var_values,
            is_train=bool(is_train), key=key, monitor=monitor,
            batch_size=bsz, device_map=self._device_map)
        n_args = len(self._arg_nodes)
        vals = self._gather_vals()
        aux_out = [aux_updates.get(id(n), vals[n_args + i])
                   for i, n in enumerate(self._aux_nodes)]
        return heads, aux_out

    def backward(self, out_grads=None, is_train=True):
        """Accumulate gradients into the bound grad arrays."""
        from . import telemetry
        with telemetry.span("executor.backward", category="executor"):
            return self._backward(out_grads, is_train)

    def _backward(self, out_grads=None, is_train=True):
        if self._outputs is None:
            raise MXNetError("call forward(is_train=True) before backward()")
        if not self._last_train:
            raise MXNetError("backward() requires forward(is_train=True)")
        if out_grads is not None and not isinstance(out_grads, (list, tuple)):
            out_grads = [out_grads]

        with_heads = out_grads is not None
        fn = self._get_backward_fn(with_heads)
        og = tuple(g.data if isinstance(g, NDArray) else g
                   for g in (out_grads or ()))
        grads = self._dispatch("executor.backward", fn,
                               (self._gather_vals(), self._last_key, og))

        diff_names = [n for n in self._arg_names
                      if self._grad_req[n] != "null"]
        for n, g in zip(diff_names, grads):
            tgt = self.grad_dict[n]
            if self._grad_req[n] == "add":
                tgt._set_data(tgt.data + g)
            else:
                tgt._set_data(g.astype(tgt.dtype))

    # ------------------------------------------------------------- utility
    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for k, v in arg_params.items():
            if k in self.arg_dict:
                self.arg_dict[k]._set_data(
                    v.data.astype(self.arg_dict[k].dtype)
                    if isinstance(v, NDArray) else v)
            elif not allow_extra_params:
                raise MXNetError("unknown argument %r" % k)
        if aux_params:
            for k, v in aux_params.items():
                if k in self.aux_dict:
                    self.aux_dict[k]._set_data(
                        v.data.astype(self.aux_dict[k].dtype)
                        if isinstance(v, NDArray) else v)
                elif not allow_extra_params:
                    raise MXNetError("unknown aux state %r" % k)

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Rebind with new input shapes (reference executor.py reshape).
        Returns a new Executor sharing parameter arrays whose shapes are
        unchanged."""
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**kwargs)
        new_args, new_grads, new_aux = {}, {}, {}
        for n, s in zip(self._arg_names, arg_shapes):
            cur = self.arg_dict[n]
            if tuple(cur.shape) == tuple(s):
                new_args[n] = cur
                if n in self.grad_dict:
                    new_grads[n] = self.grad_dict[n]
            else:
                if not (partial_shaping or n in kwargs):
                    raise MXNetError("unexpected shape change for %r" % n)
                old_size = 1
                for d in cur.shape:
                    old_size *= d
                new_size = 1
                for d in s:
                    new_size *= d
                if new_size > old_size:
                    # reference executor.py:402-407: growing an array needs
                    # an explicit opt-in (fresh allocation, values lost)
                    if not allow_up_sizing:
                        raise MXNetError(
                            "new shape of arg %r larger than original; set "
                            "allow_up_sizing=True to allocate new arrays" % n)
                    new_args[n] = _nd_zeros(s, ctx=self._ctx, dtype=cur.dtype)
                    if self._grad_req.get(n, "null") != "null":
                        new_grads[n] = _nd_zeros(s, ctx=self._ctx,
                                                 dtype=cur.dtype)
                else:
                    # same-or-smaller: reinterpret the existing storage
                    # (reference keeps memory shared via arr.reshape)
                    new_args[n] = cur.reshape(s) if new_size == old_size \
                        else _nd_zeros(s, ctx=self._ctx, dtype=cur.dtype)
                    if self._grad_req.get(n, "null") != "null":
                        g = self.grad_dict.get(n)
                        new_grads[n] = (g.reshape(s)
                                        if g is not None and new_size == old_size
                                        else _nd_zeros(s, ctx=self._ctx,
                                                       dtype=cur.dtype))
        for n, s in zip(self._aux_names, aux_shapes):
            cur = self.aux_dict[n]
            new_aux[n] = cur if tuple(cur.shape) == tuple(s) else \
                _nd_zeros(s, ctx=self._ctx, dtype=cur.dtype)
        return Executor(self._symbol, self._ctx, new_args, new_grads,
                        self._grad_req, new_aux, group2ctx=self._group2ctx)

    def set_monitor_callback(self, callback, monitor_all=False):
        """Install the EAGER per-node monitor (reference semantics:
        ``_forward_monitored`` executes node-by-node with a host sync
        per callback).  The jit-safe default is
        :meth:`set_stats_monitor`."""
        self._monitor_callback = callback
        self._monitor_all = monitor_all

    def set_stats_monitor(self, callback, pattern=".*", active=None):
        """Install the jit-safe stats monitor: ``callback(name,
        stats)`` fires per node output matching ``pattern`` with the
        in-graph stat bundle (``l2``/``mean_abs``/``max_abs``/
        ``nonfinite``/``zero_frac`` floats — telemetry.numerics), all
        computed inside ONE compiled forward.  ``active``: optional
        zero-arg gate (Monitor passes its interval latch) — when it
        returns False the plain forward program runs untouched.
        ``callback=None`` uninstalls."""
        import re as _re
        self._stats_cb = callback
        self._stats_pattern = (pattern if hasattr(pattern, "match")
                               else _re.compile(pattern))
        self._stats_active = active
        self._stats_cache = {}

    def debug_str(self):
        lines = ["Symbol Outputs:"]
        for n in self._output_names:
            lines.append("\toutput[%s]" % n)
        for node in self._topo:
            if node.is_variable:
                lines.append("Variable:%s" % node.name)
            else:
                lines.append("--------------------")
                lines.append("Op:%s, Name=%s" % (node.op.name, node.name))
                for (src, idx) in node.inputs:
                    lines.append("\targ[%d]=%s" % (idx, src.name))
        return "\n".join(lines)
