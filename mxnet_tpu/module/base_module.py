"""BaseModule: abstract training/inference interface + fit loop.

Reference: ``python/mxnet/module/base_module.py`` (969 L) — the high-level
compute-machine API (`bind` → `init_params` → `init_optimizer` →
`forward_backward`/`update` per batch) and the `fit()` training loop
(base_module.py:375-533).
"""
from __future__ import annotations

import logging
import time

import numpy as np

from ..base import MXNetError
from .. import metric as metric_mod
from .. import io as io_mod
from ..model import BatchEndParam
from ..initializer import Uniform
from ..ndarray import NDArray

__all__ = ["BaseModule"]


def _as_list(obj):
    if isinstance(obj, list):
        return obj
    return [obj]


def _batch_samples(batch, data_iter):
    """Samples one batch contributes to throughput accounting: the batch
    dim of the first data array (pad rows excluded when declared)."""
    try:
        n = int(batch.data[0].shape[0])
    except (AttributeError, IndexError, TypeError):
        n = int(getattr(data_iter, "batch_size", 0) or 0)
    pad = getattr(batch, "pad", None)
    if pad:
        n = max(0, n - int(pad))
    return n


def _check_input_names(symbol, names, typename, throw):
    """Every requested input name must be a symbol argument; on a miss,
    suggest the non-aux arguments (same diagnostic contract as reference
    base_module.py _check_input_names)."""
    args = symbol.list_arguments()
    missing = [n for n in names if n not in args]
    if not missing:
        return
    suggestions = "\n\t".join(
        a for a in args if a not in symbol.list_auxiliary_states())
    for name in missing:
        msg = ("\033[91mYou created Module with Module(..., %s_names=%s) "
               "but input with name '%s' is not found in "
               "symbol.list_arguments(). Did you mean one of:\n\t%s\033[0m"
               % (typename, names, name, suggestions))
        if throw:
            raise ValueError(msg)
        logging.warning(msg)


class BaseModule:
    """The base class of a module (reference base_module.py BaseModule)."""

    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.inputs_need_grad = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None
        self._total_exec_bytes = 0

    # ---------------------------------------------------------- high level
    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0):
        """Evaluate ``eval_metric`` over (up to ``num_batch`` batches of)
        ``eval_data`` with inference forwards; same contract as reference
        base_module.py score."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        eval_metric.reset()

        seen = 0
        for eval_batch in eval_data:
            if num_batch is not None and seen == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                # locals() of THIS frame: callbacks reading
                # param.locals['eval_batch'] (reference pattern) keep
                # working
                info = BatchEndParam(epoch=epoch, nbatch=seen,
                                     eval_metric=eval_metric,
                                     locals=locals())
                for cb in _as_list(batch_end_callback):
                    cb(info)
            seen += 1
        if score_end_callback is not None:
            info = BatchEndParam(epoch=epoch, nbatch=seen,
                                 eval_metric=eval_metric, locals=locals())
            for cb in _as_list(score_end_callback):
                cb(info)
        return eval_metric.get_name_value()

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0:out.shape[0] - pad] for out in
                       self.get_outputs()]
            yield (outputs, nbatch, eval_batch)

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False):
        """Collect inference outputs over ``eval_data``, de-padded per
        batch; ``merge_batches`` concatenates along the batch dim.  Same
        contract as reference base_module.py predict."""
        per_batch = [outs for outs, _n, _b
                     in self.iter_predict(eval_data, num_batch=num_batch,
                                          reset=reset)]
        if not per_batch or not merge_batches:
            return per_batch
        counts = {len(outs) for outs in per_batch}
        assert len(counts) == 1, \
            "Cannot merge batches: mismatched output counts %s" % counts
        from .. import ndarray as nd
        merged = [nd.array(np.concatenate([o.asnumpy() for o in outs]))
                  for outs in zip(*per_batch)]
        if len(merged) == 1 and not always_output_list:
            return merged[0]
        return merged

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=Uniform(0.01), arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None):
        """The high-level training loop: bind + init from the iterator's
        shapes, then per epoch run fused step + update + metric +
        callbacks over every batch, sync params off the devices, and
        optionally score a validation set.  Call contract (argument
        surface, callback firing points, log lines) matches reference
        base_module.py:375-533; the loop itself is a plain for — the
        reference's one-ahead batch prefetch fed a host pipeline this
        backend doesn't need (XLA dispatch is already async).
        """
        assert num_epoch is not None, "please specify number of epochs"

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)

        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)

        from .. import telemetry
        from .. import io_resume
        fetch_span = telemetry.span("data.fetch", category="io")
        # data-plane observability (telemetry.ioview): the training
        # iterator's position() AND durable state() ride sampled step
        # records and checkpoint manifests for the rest of the run
        telemetry.ioview.track(train_data)
        # mid-epoch resume (mxnet_tpu.io_resume): a checkpoint loaded
        # before this fit may have stashed the iterator's durable
        # state — restoring it here puts the FIRST epoch of the loop at
        # the exact next sample instead of replaying from sample zero
        io_resume.apply_pending(train_data)
        # backpressure actuation (MXNET_TPU_BACKPRESSURE): the
        # controller reads the ioview bottleneck verdict each batch and
        # retunes pipeline knobs (device prefetch depth) with hysteresis
        backpressure = io_resume.maybe_controller(train_data)

        for epoch in range(begin_epoch, num_epoch):
            started = time.time()
            eval_metric.reset()
            nbatch = 0
            data_iter = iter(train_data)
            while True:
                # the fetch is a traced span of its own: a loop starved
                # by the input pipeline shows up as data.fetch time, not
                # as mysteriously slow steps
                with fetch_span:
                    batch = next(data_iter, None)
                if batch is None:
                    break
                step_t0 = time.perf_counter()
                if monitor is not None:
                    monitor.tic()
                self.forward_backward(batch)
                self.update()
                self.update_metric(eval_metric, batch.label)
                if monitor is not None:
                    monitor.toc_print()
                if batch_end_callback is not None:
                    info = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                         eval_metric=eval_metric,
                                         locals=locals())
                    for cb in _as_list(batch_end_callback):
                        cb(info)
                telemetry.step_end(
                    samples=_batch_samples(batch, train_data),
                    step_time=time.perf_counter() - step_t0)
                if backpressure is not None:
                    backpressure.tick()
                nbatch += 1

            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.time() - started)

            # pull the trained values off the devices so get_params()
            # callers (and the epoch callbacks below) see current weights
            args, auxs = self.get_params()
            self.set_params(args, auxs)
            if epoch_end_callback is not None:
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, args, auxs)

            if eval_data:
                for name, val in self.score(
                        eval_data, validation_metric,
                        score_end_callback=eval_end_callback,
                        batch_end_callback=eval_batch_end_callback,
                        epoch=epoch):
                    self.logger.info("Epoch[%d] Validation-%s=%f",
                                     epoch, name, val)

            train_data.reset()

    # ------------------------------------------------------------ symbol
    @property
    def symbol(self):
        return self._symbol

    # ---------------------------------------------------------- abstract
    @property
    def data_names(self):
        raise NotImplementedError()

    @property
    def output_names(self):
        raise NotImplementedError()

    @property
    def data_shapes(self):
        raise NotImplementedError()

    @property
    def label_shapes(self):
        raise NotImplementedError()

    @property
    def output_shapes(self):
        raise NotImplementedError()

    def get_params(self):
        raise NotImplementedError()

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        raise NotImplementedError()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    def save_params(self, fname):
        from .. import ndarray as nd
        arg_params, aux_params = self.get_params()
        save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
        save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
        nd.save(fname, save_dict)

    def load_params(self, fname):
        from .. import ndarray as nd
        save_dict = nd.load(fname)
        arg_params = {}
        aux_params = {}
        for k, value in save_dict.items():
            arg_type, name = k.split(":", 1)
            if arg_type == "arg":
                arg_params[name] = value
            elif arg_type == "aux":
                aux_params[name] = value
            else:
                raise ValueError("Invalid param file " + fname)
        self.set_params(arg_params, aux_params)

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError()

    def backward(self, out_grads=None):
        raise NotImplementedError()

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError()

    def get_input_grads(self, merge_multi_context=True):
        raise NotImplementedError()

    def update(self):
        raise NotImplementedError()

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError()

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        raise NotImplementedError()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        raise NotImplementedError()

    def install_monitor(self, mon):
        raise NotImplementedError()
