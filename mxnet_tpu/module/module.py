"""Module: the concrete single-symbol compute machine.

Reference: ``python/mxnet/module/module.py`` (724 L) — bind creates a
DataParallelExecutorGroup; init_optimizer decides update_on_kvstore and sets
rescale_grad = 1/(batch_size * num_workers) (module.py:432-510); update()
routes kvstore vs local updater (module.py:561-581).
"""
from __future__ import annotations

import logging

import numpy as np

from ..base import MXNetError
from .. import context as ctx_mod
from .. import ndarray as nd
from .. import optimizer as opt_mod
from ..initializer import Uniform, InitDesc
from ..model import (_create_kvstore, _initialize_kvstore, _update_params,
                     _update_params_on_kvstore, load_checkpoint,
                     save_checkpoint)
from .base_module import BaseModule, _check_input_names
from .executor_group import DataParallelExecutorGroup

__all__ = ["Module"]


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None):
        super().__init__(logger=logger)

        if context is None:
            context = ctx_mod.current_context()
        if isinstance(context, ctx_mod.Context):
            context = [context]
        self._context = context
        if work_load_list is None:
            work_load_list = [1] * len(self._context)
        assert len(work_load_list) == len(self._context)
        self._work_load_list = work_load_list

        self._symbol = symbol

        data_names = list(data_names) if data_names is not None else []
        label_names = list(label_names) if label_names is not None else []
        state_names = list(state_names) if state_names is not None else []
        fixed_param_names = list(fixed_param_names) \
            if fixed_param_names is not None else []

        _check_input_names(symbol, data_names, "data", True)
        _check_input_names(symbol, label_names, "label", False)
        _check_input_names(symbol, state_names, "state", True)
        _check_input_names(symbol, fixed_param_names, "fixed_param", True)

        arg_names = symbol.list_arguments()
        input_names = data_names + label_names + state_names
        self._param_names = [x for x in arg_names if x not in input_names]
        self._fixed_param_names = fixed_param_names
        self._aux_names = symbol.list_auxiliary_states()
        self._data_names = data_names
        self._label_names = label_names
        self._state_names = state_names
        self._output_names = symbol.list_outputs()

        self._arg_params = None
        self._aux_params = None
        self._params_dirty = False

        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None
        self._preload_opt_states = None

        self._exec_group = None
        self._data_shapes = None
        self._label_shapes = None

    # ------------------------------------------------------------- static
    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        """Create a module from a saved checkpoint (reference
        module.py:96-131)."""
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        return mod

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        """Save symbol + params (+ optimizer states) (reference
        module.py:133-155).  Writes are atomic (tmp + fsync + rename)
        and committed by a CRC32 manifest, like model.save_checkpoint —
        see docs/api/resilience.md."""
        from .. import resilience
        resilience.atomic_write("%s-symbol.json" % prefix,
                                self._symbol.save)
        param_name = "%s-%04d.params" % (prefix, epoch)
        resilience.atomic_write(param_name, self.save_params,
                                fault_site="checkpoint.save")
        logging.info("Saved checkpoint to \"%s\"", param_name)
        files = [param_name]
        if save_optimizer_states:
            state_name = "%s-%04d.states" % (prefix, epoch)
            resilience.atomic_write(state_name,
                                    self.save_optimizer_states)
            logging.info("Saved optimizer state to \"%s\"", state_name)
            files.append(state_name)
        arg_params, aux_params = self.get_params()
        arrays = {("arg:%s" % k): v for k, v in arg_params.items()}
        arrays.update({("aux:%s" % k): v for k, v in aux_params.items()})
        resilience.write_manifest(prefix, epoch, files, arrays=arrays)

    # ---------------------------------------------------------- properties
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        assert self.binded
        return self._exec_group.data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._exec_group.label_shapes

    @property
    def output_shapes(self):
        """Inferred output shapes — available right after bind, before any
        forward (reference module.py output_shapes reads the bound
        executor's inferred shapes, not computed outputs)."""
        assert self.binded
        shapes = {d.name: d.shape for d in self._data_shapes}
        for d in (self._label_shapes or []):
            shapes[d.name] = d.shape
        _, out_shapes, _ = self._symbol.infer_shape(**shapes)
        return list(zip(self._output_names, out_shapes))

    # -------------------------------------------------------------- params
    def get_params(self):
        assert self.binded and self.params_initialized
        if self._params_dirty:
            self._sync_params_from_devices()
        return (self._arg_params, self._aux_params)

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        """Initialize parameters (reference module.py:157-226)."""
        if self.params_initialized and not force_init:
            logging.warning("Parameters already initialized and force_init "
                            "set to False; init_params call ignored.")
            return
        assert self.binded, "call bind before initializing the parameters"

        def _impl(name, arr, cache):
            if cache is not None:
                if name in cache:
                    cache_arr = cache[name]
                    if cache_arr is not arr:
                        arr[:] = cache_arr
                elif not allow_missing:
                    raise RuntimeError("%s is not presented" % name)
                elif initializer is not None:
                    initializer(name, arr)
            else:
                initializer(name, arr)

        if not allow_extra:
            # reference module.py raises on cache keys the model has no
            # slot for — silence here would drop a typo'd key unnoticed
            extra = set(arg_params or ()) - set(self._arg_params)
            extra |= set(aux_params or ()) - set(self._aux_params)
            if extra:
                raise ValueError(
                    "set_params/init_params got params not in the "
                    "module: %s (pass allow_extra=True to ignore)"
                    % sorted(extra))

        attrs = self._symbol.attr_dict()
        for name, arr in sorted(self._arg_params.items()):
            desc = InitDesc(name, attrs.get(name, None))
            _impl(desc, arr, arg_params)
        for name, arr in sorted(self._aux_params.items()):
            desc = InitDesc(name, attrs.get(name, None))
            _impl(desc, arr, aux_params)

        self.params_initialized = True
        self._params_dirty = False
        self._exec_group.set_params(self._arg_params, self._aux_params)

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        if not allow_missing:
            self.init_params(initializer=None, arg_params=arg_params,
                             aux_params=aux_params,
                             allow_missing=allow_missing,
                             force_init=force_init,
                             allow_extra=allow_extra)
            return
        if self.params_initialized and not force_init:
            logging.warning("Parameters already initialized and force_init "
                            "set to False; set_params call ignored.")
            return
        self._exec_group.set_params(arg_params, aux_params)
        self._params_dirty = True
        self.params_initialized = True

    # ---------------------------------------------------------------- bind
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write", strict=False):
        """Bind executors (reference module.py:228-323).

        ``strict=True`` first runs the static graph verifier
        (:mod:`mxnet_tpu.analysis`) over the declared data/label shapes
        and raises with node-level diagnostics before any executor is
        built or compiled.  (MXNET_TPU_STRICT_BIND=1 verifies at the
        Executor layer instead, with the full bound shapes.)"""
        if force_rebind:
            self._reset_bind()

        if self.binded:
            self.logger.warning("Already binded, ignoring bind()")
            return

        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True

        if not for_training:
            assert not inputs_need_grad

        self._data_shapes, self._label_shapes = _parse_data_desc(
            self.data_names, self.label_names, data_shapes, label_shapes)

        # explicit strict only: MXNET_TPU_STRICT_BIND is handled once at
        # the Executor layer (with the full bound shapes, which subsume
        # this data/label-shape pass) — checking the env var here too
        # would run the whole abstract-interpretation pass twice per bind
        if strict:
            shapes = {d.name: tuple(d.shape) for d in self._data_shapes}
            for d in (self._label_shapes or []):
                shapes[d.name] = tuple(d.shape)
            self._symbol.verify(shapes=shapes).raise_if_errors(
                "Module.bind strict=True")

        if shared_module is not None:
            assert isinstance(shared_module, Module) and \
                shared_module.binded and shared_module.params_initialized
            shared_group = shared_module._exec_group
        else:
            shared_group = None

        self._exec_group = DataParallelExecutorGroup(
            self._symbol, self._context, self._work_load_list,
            self._data_shapes, self._label_shapes, self._param_names,
            for_training, inputs_need_grad, shared_group,
            logger=self.logger, fixed_param_names=self._fixed_param_names,
            grad_req=grad_req, state_names=self._state_names)

        if shared_module is not None:
            self.params_initialized = True
            self._arg_params = shared_module._arg_params
            self._aux_params = shared_module._aux_params
        elif self.params_initialized:
            # called again after being initialized: sync existing params
            self._exec_group.set_params(self._arg_params, self._aux_params)
        else:
            assert self._arg_params is None and self._aux_params is None
            param_arrays = [nd.zeros(x[0].shape, dtype=x[0].dtype)
                            for x in self._exec_group.param_arrays]
            self._arg_params = dict(zip(self._param_names, param_arrays))
            aux_arrays = [nd.zeros(x[0].shape, dtype=x[0].dtype)
                          for x in self._exec_group.aux_arrays]
            self._aux_params = dict(zip(self._aux_names, aux_arrays))

        if shared_module is not None and shared_module.optimizer_initialized:
            self.borrow_optimizer(shared_module)

    def _reset_bind(self):
        self.binded = False
        self._exec_group = None
        self._data_shapes = None
        self._label_shapes = None

    def reshape(self, data_shapes, label_shapes=None):
        assert self.binded
        self._data_shapes, self._label_shapes = _parse_data_desc(
            self.data_names, self.label_names, data_shapes, label_shapes)
        self._exec_group.reshape(self._data_shapes, self._label_shapes)

    # ----------------------------------------------------------- optimizer
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        """Set up the update machinery: resolve the kvstore (and whether
        updates run on it), build the Optimizer with reference-parity
        gradient scaling, and seed the store with the initial weights.
        Semantics of reference module.py:432-510.
        """
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring...")
            return

        store, on_store = _create_kvstore(kvstore, len(self._context),
                                          self._arg_params)
        # gradients are averaged over the GLOBAL batch: every worker of a
        # dist_sync job contributes its own local batch to the sum
        effective_batch = self._exec_group.batch_size
        if store and store.type.startswith("dist") and "_sync" in store.type:
            effective_batch *= store.num_workers

        if isinstance(optimizer, str):
            # updater callbacks receive integer indices; in the local
            # multi-device layout each device replica of a param gets its
            # own slot (index i*ndev+k), all mapping to one name so
            # lr_mult/wd_mult resolve identically on every replica
            names = self._exec_group.param_names
            ndev = 1 if on_store else len(self._context)
            slot2name = {i * ndev + k: n
                         for i, n in enumerate(names) for k in range(ndev)}
            kw = dict(optimizer_params)
            kw.setdefault("rescale_grad", 1.0 / effective_batch)
            optimizer = opt_mod.create(optimizer, sym=self.symbol,
                                       param_idx2name=slot2name, **kw)
        else:
            assert isinstance(optimizer, opt_mod.Optimizer)
            if optimizer.rescale_grad != 1.0 / effective_batch:
                self.logger.warning(
                    "Optimizer created manually outside Module but "
                    "rescale_grad is not normalized to 1.0/batch_size/"
                    "num_workers (%s vs. %s). Is this intended?",
                    optimizer.rescale_grad, 1.0 / effective_batch)

        self._optimizer = optimizer
        self._kvstore = store
        self._update_on_kvstore = on_store
        # either the store applies updates (set_optimizer) or a local
        # updater closure does — never both
        if store:
            _initialize_kvstore(kvstore=store,
                                param_arrays=self._exec_group.param_arrays,
                                arg_params=self._arg_params,
                                param_names=self._param_names,
                                update_on_kvstore=on_store)
        if on_store:
            self._updater = None
            store.set_optimizer(optimizer)
        else:
            self._updater = opt_mod.get_updater(optimizer)
        self.optimizer_initialized = True

        # Module.load(load_optimizer_states=True) defers the state file
        # until the optimizer exists — consume it now
        if self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

    def borrow_optimizer(self, shared_module):
        assert shared_module.optimizer_initialized
        self._optimizer = shared_module._optimizer
        self._kvstore = shared_module._kvstore
        self._update_on_kvstore = shared_module._update_on_kvstore
        self._updater = shared_module._updater
        self.optimizer_initialized = True

    # ------------------------------------------------------------- compute
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        from .. import telemetry
        with telemetry.span("module.forward", category="module"):
            self._exec_group.forward(data_batch, is_train)

    def forward_backward(self, data_batch):
        """Fused train step (reference runs forward and backward as
        separate engine pushes; here one XLA program shares the forward
        between primal and vjp).  An MXNetError here — including an
        executor-annotated RESOURCE_EXHAUSTED — dumps the flight
        recorder's black box when MXNET_TPU_FLIGHT_DIR is set."""
        assert self.binded and self.params_initialized
        from .. import telemetry
        from ..telemetry import flight as _flight
        with telemetry.span("module.forward_backward", category="module"), \
                _flight.crash_guard("module.forward_backward"):
            self._exec_group.forward_backward(data_batch)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        from .. import telemetry
        with telemetry.span("module.backward", category="module"):
            self._exec_group.backward(out_grads=out_grads)

    def update(self):
        """Apply gradients (reference module.py:561-581)."""
        assert self.binded and self.params_initialized and \
            self.optimizer_initialized

        from .. import telemetry
        self._params_dirty = True
        with telemetry.span("module.update", category="module"):
            if self._update_on_kvstore:
                _update_params_on_kvstore(self._exec_group.param_arrays,
                                          self._exec_group.grad_arrays,
                                          self._kvstore)
            else:
                _update_params(self._exec_group.param_arrays,
                               self._exec_group.grad_arrays,
                               updater=self._updater,
                               num_device=len(self._context),
                               kvstore=self._kvstore)

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._exec_group.get_outputs(
            merge_multi_context=merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized and \
            self.inputs_need_grad
        return self._exec_group.get_input_grads(
            merge_multi_context=merge_multi_context)

    def update_metric(self, eval_metric, labels):
        self._exec_group.update_metric(eval_metric, labels)

    def _sync_params_from_devices(self):
        """Average device copies back into _arg_params (reference
        module.py _sync_params_from_devices)."""
        self._exec_group.get_params(self._arg_params, self._aux_params)
        self._params_dirty = False

    # ---------------------------------------------------------- opt states
    def save_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname)
        else:
            with open(fname, "wb") as fout:
                fout.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
        else:
            with open(fname, "rb") as f:
                self._updater.set_states(f.read())

    def install_monitor(self, mon):
        assert self.binded
        self._exec_group.install_monitor(mon)


def _parse_data_desc(data_names, label_names, data_shapes, label_shapes):
    """Normalize shapes into DataDesc lists (reference module/__init__)."""
    from .. import io as io_mod
    data_shapes = [x if isinstance(x, io_mod.DataDesc)
                   else io_mod.DataDesc(*x) for x in data_shapes]
    _check_names_match(data_names, data_shapes, "data", True)
    if label_shapes is not None and len(label_shapes) > 0:
        label_shapes = [x if isinstance(x, io_mod.DataDesc)
                        else io_mod.DataDesc(*x) for x in label_shapes]
        _check_names_match(label_names, label_shapes, "label", False)
    else:
        label_shapes = None
    return data_shapes, label_shapes


def _check_names_match(data_names, data_shapes, name, throw):
    actual = [x[0] for x in data_shapes]
    if sorted(data_names) != sorted(actual):
        msg = "Data provided by %s_shapes don't match names specified by " \
              "%s_names (%s vs. %s)" % (name, name, str(data_shapes),
                                        str(data_names))
        if throw:
            raise ValueError(msg)
        logging.warning(msg)
