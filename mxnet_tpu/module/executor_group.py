"""Data-parallel executor group.

Reference: ``python/mxnet/module/executor_group.py`` (679 L) — the
data-parallel core: slice the batch across devices (`decide_slices`:218),
bind one executor per device (`_bind_ith_exec`:565) with shared param
arrays, scatter inputs / gather outputs (`_load_data`/
`_merge_multi_context`:16-81).  TPU note: single-process multi-device; for
pjit-fused data parallelism over a Mesh see :mod:`mxnet_tpu.parallel` —
this class keeps the reference's per-device executor semantics (and works
on the CPU-device-impersonation test trick, SURVEY §4.2).
"""
from __future__ import annotations

import logging

import numpy as np

from ..base import MXNetError
from .. import context as ctx_mod
from .. import io as io_mod
from .. import ndarray as nd
from ..ndarray import NDArray

__all__ = ["DataParallelExecutorGroup"]


def _load_general(data, targets):
    """Load a list of batch arrays into per-device slices of targets
    (None target = the symbol does not consume this entry)."""
    for d_src, d_targets in zip(data, targets):
        if d_targets is None:
            continue
        if isinstance(d_targets, NDArray):
            d_targets[:] = d_src
        else:
            src = d_src.asnumpy() if isinstance(d_src, NDArray) else \
                np.asarray(d_src)
            for slice_idx, d_dst in d_targets:
                d_dst[:] = src[slice_idx.start:slice_idx.stop]


def _load_data(batch, targets):
    _load_general(batch.data, targets)


def _load_label(batch, targets):
    _load_general(batch.label, targets)


def _merge_multi_context(outputs, major_axis):
    """Concatenate per-device outputs along the batch axis
    (reference executor_group.py:55-81)."""
    rets = []
    for tensors, axis in zip(outputs, major_axis):
        if axis >= 0 and len(tensors) > 1:
            arrs = [t.asnumpy() for t in tensors]
            rets.append(nd.array(np.concatenate(arrs, axis=axis)))
        else:
            rets.append(tensors[0])
    return rets


class DataParallelExecutorGroup:
    def __init__(self, symbol, contexts, workload, data_shapes,
                 label_shapes, param_names, for_training, inputs_need_grad,
                 shared_group=None, logger=logging, fixed_param_names=None,
                 grad_req="write", state_names=None):
        self.param_names = param_names
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()

        self.symbol = symbol
        self.contexts = contexts
        self.workload = workload or [1] * len(contexts)

        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad

        self.logger = logger
        self.fixed_param_names = fixed_param_names or []
        self.state_names = state_names or []

        if not for_training:
            grad_req = "null"

        data_names = [x.name for x in data_shapes]
        if isinstance(grad_req, str):
            self.grad_req = {}
            for k in self.arg_names:
                if k in self.param_names:
                    self.grad_req[k] = "null" \
                        if k in self.fixed_param_names else grad_req
                elif k in data_names:
                    self.grad_req[k] = grad_req if inputs_need_grad else "null"
                else:
                    self.grad_req[k] = "null"
        elif isinstance(grad_req, (list, tuple)):
            self.grad_req = dict(zip(self.arg_names, grad_req))
        elif isinstance(grad_req, dict):
            self.grad_req = {k: "null" for k in self.arg_names}
            self.grad_req.update(grad_req)
        else:
            raise ValueError("invalid grad_req")

        if shared_group is not None:
            self.shared_data_arrays = shared_group.shared_data_arrays
        else:
            self.shared_data_arrays = [{} for _ in contexts]

        self.output_names = symbol.list_outputs()
        self.output_layouts = [
            io_mod.DataDesc.get_batch_axis("NCHW") for _ in self.output_names]

        self.batch_size = None
        self.slices = None
        self.execs = []
        self._default_execs = None
        self.data_shapes = None
        self.label_shapes = None
        self.data_layouts = None
        self.label_layouts = None
        self.param_arrays = None
        self.grad_arrays = None
        self.aux_arrays = None
        self.input_grad_arrays = None

        self.bind_exec(data_shapes, label_shapes, shared_group)

    def decide_slices(self, data_shapes):
        """Workload-weighted batch slicing (reference :218-243)."""
        assert len(data_shapes) > 0
        major_axis = [io_mod.DataDesc.get_batch_axis(getattr(d, "layout",
                                                             "NCHW"))
                      for d in data_shapes]
        for (name, shape), axis in zip(data_shapes, major_axis):
            if axis == -1:
                continue
            batch_size = shape[axis]
            if self.batch_size is not None:
                assert batch_size == self.batch_size, \
                    ("all data must have the same batch size: "
                     + ("batch_size = %d, but " % self.batch_size)
                     + ("%s has shape %s" % (name, shape)))
            else:
                self.batch_size = batch_size
                total_workload = sum(self.workload)
                self.slices = []
                start = 0
                for k, w in enumerate(self.workload):
                    if k == len(self.workload) - 1:
                        end = batch_size
                    else:
                        end = start + int(
                            round(batch_size * w / total_workload))
                    self.slices.append(slice(start, end))
                    start = end
        return major_axis

    def _collect_arrays(self):
        """Gather param/grad/aux array lists over devices (reference
        executor_group.py bind_exec tail)."""
        self.param_arrays = [[exe.arg_dict[name] for exe in self.execs]
                             for name in self.param_names]
        if self.for_training:
            self.grad_arrays = [[exe.grad_dict.get(name) for exe in self.execs]
                                for name in self.param_names]
        else:
            self.grad_arrays = None
        data_names = [x[0] for x in self.data_shapes]
        if self.inputs_need_grad:
            self.input_grad_arrays = [
                [exe.grad_dict[name] for exe in self.execs]
                for name in data_names if name in self.execs[0].grad_dict]
        self.aux_arrays = [[exe.aux_dict[name] for exe in self.execs]
                           for name in self.aux_names]

    def bind_exec(self, data_shapes, label_shapes, shared_group=None,
                  reshape=False):
        self.batch_size = None
        self.data_layouts = self.decide_slices(data_shapes)
        if label_shapes is not None:
            self.label_layouts = self.decide_slices(label_shapes)

        self.execs = []
        for i in range(len(self.contexts)):
            self.execs.append(
                self._bind_ith_exec(i, data_shapes, label_shapes,
                                    shared_group))
        self.data_shapes = data_shapes
        self.label_shapes = label_shapes
        self._collect_arrays()

    def reshape(self, data_shapes, label_shapes):
        if data_shapes == self.data_shapes and \
                label_shapes == self.label_shapes:
            return
        self.bind_exec(data_shapes, label_shapes, reshape=True)

    def set_params(self, arg_params, aux_params):
        for exec_ in self.execs:
            exec_.copy_params_from(arg_params, aux_params,
                                   allow_extra_params=True)

    def get_params(self, arg_params, aux_params):
        """Average params over devices into the given dicts
        (reference executor_group.py get_params)."""
        for name, block in zip(self.param_names, self.param_arrays):
            weight = sum(w.asnumpy() for w in block) / len(block)
            arg_params[name][:] = weight.astype(arg_params[name].dtype)
        for name, block in zip(self.aux_names, self.aux_arrays):
            weight = sum(w.asnumpy() for w in block) / len(block)
            aux_params[name][:] = weight.astype(aux_params[name].dtype)

    def forward(self, data_batch, is_train=None):
        _load_data(data_batch, self.data_arrays)
        if is_train is None:
            is_train = self.for_training
        if self.label_shapes is not None and data_batch.label:
            _load_label(data_batch, self.label_arrays)
        for exec_ in self.execs:
            exec_.forward(is_train=is_train)

    def forward_backward(self, data_batch):
        """Fused per-device train step (one XLA program per device)."""
        assert self.for_training
        _load_data(data_batch, self.data_arrays)
        if self.label_shapes is not None and data_batch.label:
            _load_label(data_batch, self.label_arrays)
        for exec_ in self.execs:
            exec_.forward_backward()

    def backward(self, out_grads=None):
        assert self.for_training, "re-bind with for_training=True"
        for i, exec_ in enumerate(self.execs):
            out_grads_slice = []
            if out_grads is not None:
                for grad, axis in zip(out_grads, self.output_layouts):
                    if axis >= 0 and len(self.execs) > 1:
                        og = grad.asnumpy()[self.slices[i]]
                        out_grads_slice.append(nd.array(og))
                    else:
                        out_grads_slice.append(grad)
                exec_.backward(out_grads=out_grads_slice)
            else:
                exec_.backward()

    def get_outputs(self, merge_multi_context=True):
        outputs = [[exe.outputs[i] for exe in self.execs]
                   for i in range(len(self.execs[0].outputs))]
        if merge_multi_context:
            return _merge_multi_context(outputs, self.output_layouts)
        return outputs

    def get_input_grads(self, merge_multi_context=True):
        assert self.inputs_need_grad
        data_names = [x[0] for x in self.data_shapes]
        grads = [[exe.grad_dict[name] for exe in self.execs]
                 for name in data_names]
        if merge_multi_context:
            return _merge_multi_context(grads, self.data_layouts)
        return grads

    def update_metric(self, eval_metric, labels):
        """Per-device metric update with sliced labels
        (reference executor_group.py:530-563)."""
        for current_exec, islice in zip(self.execs, self.slices):
            labels_slice = []
            for label, axis in zip(labels, self.label_layouts or
                                   [0] * len(labels)):
                if axis == 0:
                    if len(self.execs) > 1:
                        lab = label.asnumpy()[islice]
                        labels_slice.append(nd.array(lab))
                    else:
                        labels_slice.append(label)
                else:
                    labels_slice.append(label)
            eval_metric.update(labels_slice, current_exec.outputs)

    def _sliced_shape(self, shapes, i, major_axis):
        """Shape of the i-th executor's slice."""
        sliced_shapes = []
        for desc, axis in zip(shapes, major_axis):
            shape = list(desc.shape)
            if axis >= 0:
                shape[axis] = self.slices[i].stop - self.slices[i].start
            sliced_shapes.append(
                io_mod.DataDesc(desc.name, tuple(shape),
                                getattr(desc, "dtype", np.float32)))
        return sliced_shapes

    def _bind_ith_exec(self, i, data_shapes, label_shapes, shared_group):
        """Bind executor i, sharing params across executors
        (reference _bind_ith_exec:565-660)."""
        shared_exec = None if shared_group is None else shared_group.execs[i]
        context = self.contexts[i]
        shared_data_arrays = self.shared_data_arrays[i]

        data_shapes_i = self._sliced_shape(data_shapes, i, self.data_layouts)
        if label_shapes is not None:
            label_shapes_i = self._sliced_shape(label_shapes, i,
                                                self.label_layouts)
        else:
            label_shapes_i = []

        input_shapes = dict(data_shapes_i)
        input_shapes.update(dict(label_shapes_i))
        arg_shapes, _, aux_shapes = self.symbol.infer_shape(**input_shapes)
        assert arg_shapes is not None, "shape inference failed"

        input_types = {x.name: getattr(x, "dtype", np.float32)
                       for x in data_shapes_i + label_shapes_i}
        arg_types, _, aux_types = self.symbol.infer_type(**input_types)

        arg_arrays = []
        grad_arrays = {} if self.for_training else None

        def _get_or_reshape(name, shared_data_arrays, arg_shape, arg_type,
                            context, logger):
            # the reference reuses a bigger pooled buffer via reshape
            # (executor_group.py _get_or_reshape); XLA owns memory here, so
            # shape mismatch just allocates per-shape
            arg_arr = shared_data_arrays.get(name)
            if arg_arr is None or tuple(arg_arr.shape) != tuple(arg_shape):
                arg_arr = nd.zeros(arg_shape, ctx=context, dtype=arg_type)
                shared_data_arrays[name] = arg_arr
            return arg_arr

        for j, name in enumerate(self.arg_names):
            if name in self.param_names:  # model parameters
                if shared_exec is None:
                    arg_arr = nd.zeros(arg_shapes[j], ctx=context,
                                       dtype=arg_types[j])
                    if self.grad_req[name] != "null":
                        grad_arr = nd.zeros(arg_shapes[j], ctx=context,
                                            dtype=arg_types[j])
                        grad_arrays[name] = grad_arr
                else:
                    arg_arr = shared_exec.arg_dict[name]
                    assert tuple(arg_arr.shape) == tuple(arg_shapes[j])
                    if self.grad_req[name] != "null":
                        grad_arrays[name] = shared_exec.grad_dict[name]
            else:  # data, label, or states
                arg_arr = _get_or_reshape(name, shared_data_arrays,
                                          arg_shapes[j], arg_types[j],
                                          context, self.logger)
                if self.grad_req[name] != "null":
                    grad_arrays[name] = _get_or_reshape(
                        "grad of " + name, shared_data_arrays,
                        arg_shapes[j], arg_types[j], context, self.logger)
            arg_arrays.append(arg_arr)

        if shared_exec is None:
            aux_arrays = [nd.zeros(s, ctx=context, dtype=t)
                          for s, t in zip(aux_shapes, aux_types)]
        else:
            aux_arrays = shared_exec.aux_arrays[:]

        executor = self.symbol.bind(ctx=context, args=arg_arrays,
                                    args_grad=grad_arrays,
                                    aux_states=aux_arrays,
                                    grad_req=self.grad_req,
                                    shared_exec=shared_exec)
        return executor

    @property
    def data_arrays(self):
        data_names = [x[0] for x in self.data_shapes]
        return [[(self.slices[i], e.arg_dict[name])
                 for i, e in enumerate(self.execs)]
                for name in data_names]

    @property
    def label_arrays(self):
        # tolerate labels the bound symbol does not consume (reference
        # executor_group filters label_names against the arguments — an
        # inference symbol scored with a labeled iterator has none).
        # None placeholders keep positional alignment with batch.label so
        # a partially-consumed label list still pairs by name.
        return [None if x[0] not in self.execs[0].arg_dict else
                [(self.slices[i], e.arg_dict[x[0]])
                 for i, e in enumerate(self.execs)]
                for x in self.label_shapes]

    def install_monitor(self, mon):
        for exe in self.execs:
            mon.install(exe)
