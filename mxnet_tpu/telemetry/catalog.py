"""The declared metric catalog: every metric the framework emits.

Reference analogue: the profiler's fixed per-device stat tables
(``src/engine/profiler.h:32-58``) — the set of observable quantities is
part of the framework contract, not ad-hoc.  Each entry is
``name -> (kind, label names, help)``; the registry refuses to create a
metric that is not declared here (a typo'd name fails at the emit site,
not silently in a dashboard), and ``tools/ci_check.py`` cross-checks
this table against the hand-written catalog in
``docs/api/telemetry.md`` in both directions — the same drift-guard
pattern that caught the unregistered ``squeeze`` op in the op registry.

Naming follows Prometheus conventions: ``_total`` counters,
``_seconds``/``_bytes`` units, gauges unsuffixed.
"""
from __future__ import annotations

__all__ = ["CATALOG", "COUNTER", "GAUGE", "HISTOGRAM", "selfcheck"]

COUNTER = "counter"
GAUGE = "gauge"
HISTOGRAM = "histogram"

# seconds-scale latency buckets (histogram default): 0.5 ms .. 10 s
TIME_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

# name -> (kind, labelnames tuple, help)
CATALOG = {
    # ------------------------------------------------- training steps
    "mxtpu_step_total": (COUNTER, (), "training steps completed"),
    "mxtpu_samples_total": (COUNTER, (),
                            "samples consumed by training steps"),
    "mxtpu_step_seconds": (HISTOGRAM, (),
                           "host wall time per training step"),
    "mxtpu_span_seconds": (HISTOGRAM, ("span",),
                           "wall time per traced span (executor/module/"
                           "trainer/io phases)"),
    # ------------------------------------------------- XLA compilation
    "mxtpu_compile_total": (COUNTER, (),
                            "XLA backend compiles observed in this "
                            "process (jax.monitoring)"),
    "mxtpu_compile_seconds_total": (COUNTER, (),
                                    "total XLA backend compile time"),
    # ------------------------------------------------------------- IO
    "mxtpu_io_records_total": (COUNTER, ("source",),
                               "records read (source=recordio|native)"),
    "mxtpu_io_bad_records_total": (COUNTER, ("source",),
                                   "corrupt/truncated records skipped "
                                   "under MXNET_TPU_BAD_RECORD_QUOTA"),
    "mxtpu_io_resyncs_total": (COUNTER, ("source",),
                               "magic-resync scans after a corrupt "
                               "record"),
    "mxtpu_io_skipped_bytes_total": (COUNTER, ("source",),
                                     "bytes skipped while resyncing "
                                     "past corrupt records"),
    "mxtpu_io_prefetch_depth": (GAUGE, ("iter",),
                                "staged batches currently queued "
                                "(iter=host|device); last-observed "
                                "value set by the ioview occupancy "
                                "tracker under its own lock"),
    "mxtpu_io_prefetch_stall_seconds_total": (
        COUNTER, ("iter",),
        "time the consumer blocked waiting on the prefetcher"),
    "mxtpu_io_prefetch_starved_seconds_total": (
        COUNTER, ("iter",),
        "time prefetch producer threads idled waiting for the "
        "consumer to drain the queue (consumer-bound: the device, "
        "not the pipeline, bounds throughput)"),
    # ------------------------------- input-pipeline view (ioview)
    "mxtpu_io_stage_seconds": (HISTOGRAM, ("stage",),
                               "wall time per unit of work in each "
                               "input-pipeline stage (stage=read|"
                               "decode|augment|batch|host_prefetch|"
                               "device_stage)"),
    "mxtpu_io_stage_items_total": (COUNTER, ("stage",),
                                   "items processed per input-pipeline "
                                   "stage (records/images for the "
                                   "leaf stages, batches for the "
                                   "prefetch/staging stages)"),
    "mxtpu_io_bytes_total": (COUNTER, ("stage",),
                             "bytes flowing through each input-"
                             "pipeline stage"),
    "mxtpu_io_queue_occupancy": (HISTOGRAM, ("iter",),
                                 "time-weighted prefetch-queue "
                                 "occupancy: weighted observations "
                                 "where bucket counts are SECONDS "
                                 "spent at each staged-batch depth "
                                 "(sum/count = time-weighted mean "
                                 "depth)"),
    "mxtpu_io_bottleneck_total": (COUNTER, ("stage",),
                                  "per-window bottleneck verdicts from "
                                  "the ioview classifier (stage=<the "
                                  "slowest pipeline stage> when "
                                  "producer-bound, consumer when the "
                                  "training loop binds, balanced "
                                  "otherwise)"),
    "mxtpu_data_resume_total": (COUNTER, (),
                                "durable data-iterator restores from a "
                                "checkpoint manifest data_state entry "
                                "(io_resume.restore_iterator — mid-"
                                "epoch resume landed at the exact next "
                                "sample)"),
    "mxtpu_data_remap_samples": (GAUGE, (),
                                 "globally-consumed samples carried "
                                 "through the last elastic cursor "
                                 "remap (io_resume.remap_state: the "
                                 "permutation prefix re-cut for the "
                                 "new world size)"),
    "mxtpu_backpressure_adjust_total": (COUNTER, ("knob", "direction"),
                                        "runtime pipeline-knob moves by "
                                        "the backpressure controller "
                                        "(io_resume."
                                        "BackpressureController: "
                                        "direction=raise|lower per "
                                        "registered knob)"),
    # -------------------------------------------------------- kvstore
    "mxtpu_kvstore_push_bytes_total": (COUNTER, ("store",),
                                       "gradient bytes pushed "
                                       "(store=local|device|dist_sync|"
                                       "dist_async)"),
    "mxtpu_kvstore_pull_bytes_total": (COUNTER, ("store",),
                                       "weight bytes pulled"),
    "mxtpu_kvstore_pending_async": (GAUGE, (),
                                    "dist_async push/pull RPCs "
                                    "currently in flight"),
    # ------------------------- communication overlap (parallel.overlap)
    "mxtpu_overlap_buckets_total": (COUNTER, ("phase",),
                                    "gradient buckets launched by the "
                                    "overlap layer (phase=backward — "
                                    "the launch overlapped gradient "
                                    "production; drain — it waited "
                                    "for the optimizer boundary)"),
    "mxtpu_overlap_bucket_bytes": (HISTOGRAM, (),
                                   "payload bytes per launched "
                                   "gradient bucket "
                                   "(MXNET_TPU_BUCKET_BYTES sets the "
                                   "fill target)"),
    "mxtpu_overlap_drain_seconds": (HISTOGRAM, (),
                                    "wall time of the optimizer-"
                                    "boundary bucket drain (launch "
                                    "remainder + wait out every "
                                    "in-flight allreduce)"),
    "mxtpu_overlap_inflight_buckets": (GAUGE, (),
                                       "gradient buckets launched and "
                                       "not yet drained"),
    # ----------------------------------------------------- resilience
    "mxtpu_retry_total": (COUNTER, ("site",),
                          "retry attempts scheduled by "
                          "resilience.retry_call"),
    "mxtpu_fault_injected_total": (COUNTER, ("site",),
                                   "armed fault_point seams that fired"),
    "mxtpu_watchdog_restarts": (GAUGE, (),
                                "restart attempt this process is "
                                "running under (MXNET_TPU_RESTART_COUNT "
                                "from tools/launch.py)"),
    # -------------------------------------------------------- monitor
    "mxtpu_monitor_stat": (GAUGE, ("tensor",),
                           "latest Monitor stat value per matched "
                           "tensor"),
    # ------------------------------------------------ memory / HBM
    "mxtpu_memory_plan_bytes": (GAUGE, ("program", "category"),
                                "static XLA memory plan of a compiled "
                                "program (category=argument|output|temp|"
                                "alias|generated_code|total)"),
    "mxtpu_program_flops": (GAUGE, ("program",),
                            "XLA cost-analysis FLOPs per execution of "
                            "a compiled program"),
    "mxtpu_program_bytes_accessed": (GAUGE, ("program",),
                                     "XLA cost-analysis bytes accessed "
                                     "per execution (HBM traffic)"),
    "mxtpu_hbm_bytes_in_use": (GAUGE, ("device",),
                               "live device memory in use "
                               "(device.memory_stats, sampled at step "
                               "boundaries)"),
    "mxtpu_hbm_peak_bytes": (GAUGE, ("device",),
                             "peak device memory in use since process "
                             "start (device.memory_stats)"),
    "mxtpu_oom_total": (COUNTER, ("program",),
                        "RESOURCE_EXHAUSTED errors annotated with the "
                        "memory plan and live-bytes snapshot"),
    "mxtpu_predicted_peak_bytes": (GAUGE, ("program", "category"),
                                   "bind-time static liveness peak-HBM "
                                   "prediction (analysis.memlive; "
                                   "category=params|activations|"
                                   "residuals|optimizer|workspace|"
                                   "total)"),
    "mxtpu_remat_candidate_bytes": (GAUGE, ("program",),
                                    "residual bytes freeable at the "
                                    "predicted peak by the ranked "
                                    "MXG019 remat candidates"),
    "mxtpu_memlive_drift_ratio": (GAUGE, ("program",),
                                  "(static predicted peak - XLA "
                                  "memory_analysis total) / total for "
                                  "the last MXG018 comparison "
                                  "(MXNET_TPU_MEMLIVE_TOL bounds it)"),
    # ------------------------------------------------ flight recorder
    "mxtpu_flight_events_total": (COUNTER, ("kind",),
                                  "structured events recorded into the "
                                  "flight-recorder ring"),
    "mxtpu_flight_dumps_total": (COUNTER, ("reason",),
                                 "flight-recorder black-box dumps "
                                 "written (MXNET_TPU_FLIGHT_DIR)"),
    # ------------------------------- block fusion (analysis.fusion)
    "mxtpu_fusion_plans_total": (COUNTER, (),
                                 "block-fusion plans computed (one per "
                                 "trace with the pass enabled)"),
    "mxtpu_fusion_blocks_total": (COUNTER, ("kind",),
                                  "fused blocks emitted by the "
                                  "block-granularity fusion plan "
                                  "(kind=conv_bn_act|conv_bn|bn_act|"
                                  "fc_act)"),
    "mxtpu_fusion_relayouts_eliminated_total": (
        COUNTER, (),
        "region-boundary relayouts eliminated by the fusion layout "
        "plan (in-block interior edges + same-layout block "
        "adjacencies)"),
    "mxtpu_fusion_fallback_total": (COUNTER, ("reason",),
                                    "candidate chains the fusion pass "
                                    "left unfused, by reason"),
    # --------------------------------------- cost database (costdb)
    "mxtpu_block_mfu": (GAUGE, ("block",),
                        "latest derived model-FLOPs-utilization per "
                        "fused block / Pallas kernel (costdb roofline "
                        "attribution)"),
    "mxtpu_costdb_records_total": (COUNTER, ("kind",),
                                   "aggregate records created in the "
                                   "op/block cost database "
                                   "(kind=program|block|kernel|"
                                   "collective)"),
    # ----------------------------------------- autotuner (autotune)
    "mxtpu_tune_cache_hit_total": (COUNTER, ("op",),
                                   "trace-time tuning-cache lookups "
                                   "answered by a tuned entry "
                                   "(mxnet_tpu.autotune; the dispatch "
                                   "uses the measured-best block "
                                   "config)"),
    "mxtpu_tune_cache_miss_total": (COUNTER, ("op",),
                                    "tuning-cache lookups that fell "
                                    "back to the built-in heuristic "
                                    "(or triggered an inline search "
                                    "under MXNET_TPU_AUTOTUNE="
                                    "search)"),
    # ------------------------------- plan search (analysis.plansearch)
    "mxtpu_plan_cache_hit_total": (COUNTER, (),
                                   "bind-time graph_plan tuning-cache "
                                   "lookups answered by a committed "
                                   "plan entry (analysis.plansearch; "
                                   "the traces activate the searched "
                                   "decision vector)"),
    "mxtpu_plan_cache_miss_total": (COUNTER, (),
                                    "bind-time graph_plan lookups that "
                                    "fell back to the greedy fusion "
                                    "plan (untuned graph/mesh/layout)"),
    # ------------------------- static verification (mxnet_tpu.analysis)
    "mxtpu_verify_findings_total": (COUNTER, ("rule",),
                                    "verifier diagnostics reported, by "
                                    "rule id (MXG001-021; every "
                                    "Report.add increments — bind-time "
                                    "strict checks, CLI runs and "
                                    "ci_check sweeps all count)"),
    # ---------------------------- elastic training (parallel.reshard)
    "mxtpu_reshard_total": (COUNTER, ("kind",),
                            "mesh reshapes performed (kind=load — a "
                            "checkpoint restored onto a different mesh "
                            "shape; offline — tools/reshard.py "
                            "conversion; kvstore — DistKVStore state "
                            "migration)"),
    "mxtpu_reshard_params_total": (COUNTER, (),
                                   "named arrays restaged across mesh "
                                   "reshapes (params + aux; optimizer "
                                   "slots ride their param's plan "
                                   "entry)"),
    "mxtpu_reshard_bytes_total": (COUNTER, (),
                                  "bytes restaged across mesh "
                                  "reshapes"),
    "mxtpu_reshard_seconds": (HISTOGRAM, (),
                              "wall time per mesh reshape (plan + "
                              "per-param scatter onto the target "
                              "mesh)"),
    "mxtpu_elastic_resizes_total": (COUNTER, ("direction",),
                                    "world-size changes observed "
                                    "across a resume "
                                    "(direction=join|leave)"),
    # --------------------------- training-health numerics (numerics)
    "mxtpu_tensor_norm": (GAUGE, ("tensor", "kind"),
                          "latest sampled l2 norm per named tensor "
                          "(kind=param|grad|block|node; sampled every "
                          "MXNET_TPU_NUMERICS_EVERY steps inside the "
                          "jitted step)"),
    "mxtpu_grad_global_norm": (GAUGE, (),
                               "latest sampled global gradient l2 "
                               "norm (the grad_spike EWMA input)"),
    "mxtpu_nonfinite_total": (COUNTER, ("tensor",),
                              "non-finite (NaN/Inf) values detected "
                              "per watched tensor (grad/param/block "
                              "stats, monitored node outputs, and "
                              "metric/<name> update values)"),
    "mxtpu_numerics_anomalies_total": (COUNTER, ("rule",),
                                       "numerics anomaly rules fired "
                                       "(rule=nonfinite|grad_spike|"
                                       "dead_grad); each firing also "
                                       "leaves a numerics_anomaly "
                                       "flight event"),
    # ------------------------------------ cross-rank view (distview)
    "mxtpu_step_segment_seconds": (HISTOGRAM, ("segment",),
                                   "per-step host wall time split into "
                                   "segment=compute|input_wait|"
                                   "collective_wait (straggler "
                                   "attribution)"),
    "mxtpu_collective_wait_seconds": (HISTOGRAM, (),
                                      "time this rank stalled at a "
                                      "pre-collective timestamp barrier "
                                      "waiting for its slowest peer"),
    "mxtpu_rank_step_skew_seconds": (GAUGE, (),
                                     "arrival-time spread (max-min) "
                                     "across ranks at the last "
                                     "timestamp barrier — the "
                                     "straggler's lead"),
    "mxtpu_capture_total": (COUNTER, ("trigger",),
                            "on-demand live capture windows started "
                            "(trigger=signal|http|api)"),
    # ------------------------------------- serving tier (mxnet_tpu.serving)
    "mxtpu_serve_requests_total": (COUNTER, ("outcome",),
                                   "predict requests finished "
                                   "(outcome=ok|shed|error)"),
    "mxtpu_serve_shed_total": (COUNTER, ("reason",),
                               "requests refused by the load shedder "
                               "(reason=queue_full — the bounded queue "
                               "was at depth; deadline — the remaining "
                               "deadline could not cover the estimated "
                               "rung wall)"),
    "mxtpu_serve_rung_dispatch_total": (COUNTER, ("rung",),
                                        "coalesced batches dispatched "
                                        "per ladder rung (rung=batch "
                                        "size)"),
    "mxtpu_serve_request_seconds": (HISTOGRAM, ("segment",),
                                    "per-request serving latency split "
                                    "(segment=queue|pad|dispatch|"
                                    "total)"),
    "mxtpu_serve_rung_occupancy": (HISTOGRAM, ("rung",),
                                   "real-request rows divided by rung "
                                   "batch size per dispatched batch "
                                   "(1.0 = the rung left with no pad "
                                   "rows)"),
    "mxtpu_serve_queue_depth": (GAUGE, (),
                                "predict requests currently queued in "
                                "the batcher"),
    # ----------------------------------- SLO engine / alerting (slo)
    "mxtpu_alert_transitions_total": (COUNTER, ("rule", "to"),
                                      "alert state-machine transitions "
                                      "per SLO rule (to=pending|firing|"
                                      "cleared|resolved)"),
    "mxtpu_alert_state": (GAUGE, ("rule",),
                          "current alert state per SLO rule "
                          "(0=inactive 1=pending 2=firing)"),
    "mxtpu_alerts_firing": (GAUGE, ("severity",),
                            "SLO rules currently firing, by severity "
                            "(severity=warn|critical)"),
    "mxtpu_slo_burn_rate": (GAUGE, ("rule", "window"),
                            "latest error-budget burn rate per "
                            "burn_rate rule and window (window=fast|"
                            "slow; 1.0 = budget consumed exactly at "
                            "the objective's allowance)"),
    "mxtpu_health_status": (GAUGE, (),
                            "this rank's health verdict (0=healthy "
                            "1=degraded 2=critical)"),
    # ------------------------------ distributed tracing (telemetry.tracing)
    "mxtpu_traces_total": (COUNTER, ("status",),
                           "finished traces by final status "
                           "(status=ok|error|shed)"),
    "mxtpu_traces_kept_total": (COUNTER, ("reason",),
                                "traces retained by tail-sampling "
                                "(reason=error|shed|slow|sampled)"),
}

# rung-occupancy fractions (histogram buckets): fill ratios up to full
OCCUPANCY_BUCKETS = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)


def selfcheck():
    """Validate the catalog itself; returns a list of problem strings
    (empty = clean).  Checked: prometheus-legal metric and label names,
    counter ``_total``/unit suffixes, no reserved label names."""
    import re
    problems = []
    name_re = re.compile(r"^[a-z_][a-z0-9_]*$")
    for name, (kind, labels, help_) in sorted(CATALOG.items()):
        if not name_re.match(name):
            problems.append("metric %r: illegal prometheus name" % name)
        if not name.startswith("mxtpu_"):
            problems.append("metric %r: missing mxtpu_ namespace" % name)
        if kind not in (COUNTER, GAUGE, HISTOGRAM):
            problems.append("metric %r: unknown kind %r" % (name, kind))
        if kind == COUNTER and not name.endswith("_total"):
            problems.append("metric %r: counters end in _total" % name)
        if kind != COUNTER and name.endswith("_total"):
            problems.append("metric %r: _total reserved for counters"
                            % name)
        if not isinstance(labels, tuple):
            problems.append("metric %r: labelnames must be a tuple"
                            % name)
            continue
        for lbl in labels:
            if not name_re.match(lbl) or lbl.startswith("__"):
                problems.append("metric %r: illegal label %r"
                                % (name, lbl))
            if lbl in ("le", "quantile"):
                problems.append("metric %r: label %r is reserved by "
                                "histograms/summaries" % (name, lbl))
        if not help_:
            problems.append("metric %r: empty help string" % name)
    return problems
