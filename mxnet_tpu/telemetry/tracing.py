"""Distributed tracing: trace context, span records, tail-sampled export.

Dapper-style causal tracing for both planes (serving requests and
training steps), answering the question aggregates cannot: why was
THIS request / THIS step slow?

* **trace context** — W3C-traceparent-style identity
  (``trace_id``/``span_id``/``parent_id``).  The active context is
  thread-local (:func:`current`/:func:`attach`/:func:`detach`) AND
  explicitly attachable: a scheduler thread that times work on behalf
  of another thread's request records spans against that request's
  context directly (:func:`record_span`), no ambient state needed.
  ``serving/server.py`` accepts and returns ``traceparent`` headers;
  :func:`parse_traceparent` validates the ``00-<32hex>-<16hex>-<flags>``
  form.
* **span upgrade** — every :class:`~mxnet_tpu.telemetry.spans.span`
  entered while a trace is active records its interval into that trace
  as a child span, so the existing instrumentation (executor fwd,
  trainer phases, io stages) becomes trace depth for free.
* **tail-sampled retention** — finished traces land in a bounded ring
  (``MXNET_TPU_TRACE_RING``).  Error/shed traces are ALWAYS kept, the
  slowest ``1 - MXNET_TPU_TRACE_SLOW_PCT`` fraction of recent roots is
  ALWAYS kept, and the rest is sampled at ``MXNET_TPU_TRACE_SAMPLE``
  (deterministic on the trace id, so every rank of a fleet makes the
  same call).  ``MXNET_TPU_TRACE_SAMPLE=0`` disables tracing entirely:
  :func:`start_trace` returns the shared :data:`NULL_TRACE` and the
  request path pays one thread-local read, nothing else.
* **export** — kept traces append one self-describing JSON line
  (schema ``mxtpu-trace/1``) to ``MXNET_TPU_TRACE_DIR/
  trace.rank<N>.jsonl``; ``tools/launch.py`` merges the per-rank files
  at job end (:func:`merge_trace_dir`) so a fleet-wide trace is one
  record; ``tools/trace_top.py`` ranks, reconstructs, and attributes.
* **exemplars** — the latency histograms remember the trace id of a
  recent observation per bucket (``observe(..., exemplar=tid)`` in the
  registry); :func:`exemplar_for` resolves a metric's slowest-bucket
  exemplar so ``/metrics``, the SLO engine's firing alerts, and
  ``serve_top`` can name an actual slow trace, not just a quantile.

Module-level imports are stdlib-only and the reader half (parse /
merge / critical path) never touches the framework — ``launch.py`` and
``tools/trace_top.py`` load this file by path, exactly like
``distview.py``.

See ``docs/api/telemetry.md`` (tracing section) for the schema and the
propagation contract.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque

__all__ = [
    "TRACE_SCHEMA", "TraceContext", "Trace", "NULL_TRACE",
    "sample_rate", "enabled", "ring_capacity", "trace_dir", "slow_pct",
    "new_trace_id", "new_span_id", "parse_traceparent",
    "current", "attach", "detach",
    "start_trace", "record_span", "set_trace_status",
    "annotate", "take_annotations",
    "traces", "get_trace", "reset", "exemplar_for",
    "read_traces", "merge_traces", "merge_trace_dir",
    "critical_path", "dominant_segment",
]

log = logging.getLogger(__name__)

#: the per-trace JSONL export schema tag (one line per kept trace)
TRACE_SCHEMA = "mxtpu-trace/1"

_tls = threading.local()
_lock = threading.Lock()
_active = {}                # trace_id -> in-flight trace doc
_ring = deque()             # kept finished traces (bounded in _finish)
_durs = deque(maxlen=512)   # recent root durations (slow-tail threshold)
_counters = {}              # (metric, label value) -> bound child cache
_warned_write = [False]


# ------------------------------------------------------------- env knobs

def sample_rate():
    """Head/tail sample rate for ordinary (ok, not-slow) traces
    (``MXNET_TPU_TRACE_SAMPLE``, default 1.0, clamped to [0, 1]).
    0 disables tracing entirely."""
    try:
        v = float(os.environ.get("MXNET_TPU_TRACE_SAMPLE", "1"))
    except ValueError:
        return 1.0
    return min(1.0, max(0.0, v))


def enabled():
    """Tracing master switch — ``sample_rate() > 0``."""
    return sample_rate() > 0.0


def ring_capacity():
    """Kept-trace ring capacity (``MXNET_TPU_TRACE_RING``, default
    256, floor 8)."""
    try:
        n = int(os.environ.get("MXNET_TPU_TRACE_RING", "256"))
    except ValueError:
        n = 256
    return max(8, n)


def trace_dir():
    """JSONL export directory (``MXNET_TPU_TRACE_DIR``), or None when
    export is off (the in-process ring still fills)."""
    return os.environ.get("MXNET_TPU_TRACE_DIR") or None


def slow_pct():
    """Slow-tail retention percentile (``MXNET_TPU_TRACE_SLOW_PCT``,
    default 0.95): root durations at or above this percentile of the
    recent window are always kept."""
    try:
        v = float(os.environ.get("MXNET_TPU_TRACE_SLOW_PCT", "0.95"))
    except ValueError:
        return 0.95
    return min(0.999, max(0.5, v))


def _rank():
    try:
        return int(os.environ.get("MXNET_TPU_PROCESS_ID", "0") or 0)
    except ValueError:
        return 0


# ------------------------------------------------------------ identities

def new_trace_id():
    """A fresh 32-hex-char (128-bit) trace id."""
    return os.urandom(16).hex()


def new_span_id():
    """A fresh 16-hex-char (64-bit) span id."""
    return os.urandom(8).hex()


def parse_traceparent(header):
    """``(trace_id, parent_span_id)`` from a W3C ``traceparent`` header
    (``00-<32hex>-<16hex>-<flags>``), or None when malformed — a bad
    inbound header starts a fresh trace instead of poisoning the
    export."""
    if not header or not isinstance(header, str):
        return None
    parts = header.strip().lower().split("-")
    if len(parts) < 4:
        return None
    version, tid, sid = parts[0], parts[1], parts[2]
    if len(version) != 2 or len(tid) != 32 or len(sid) != 16:
        return None
    try:
        int(version, 16), int(tid, 16), int(sid, 16)
    except ValueError:
        return None
    if tid == "0" * 32 or sid == "0" * 16:
        return None
    return tid, sid


class TraceContext:
    """One span's identity inside a trace.  Immutable; ``child()``
    derives the context a nested span runs under."""

    __slots__ = ("trace_id", "span_id", "parent_id")

    def __init__(self, trace_id, span_id, parent_id=None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id

    def child(self):
        return TraceContext(self.trace_id, new_span_id(), self.span_id)

    def to_traceparent(self):
        return "00-%s-%s-01" % (self.trace_id, self.span_id)

    def __repr__(self):
        return "TraceContext(%s/%s<-%s)" % (self.trace_id, self.span_id,
                                            self.parent_id)


# ------------------------------------------------------ thread-local ctx

def current():
    """The calling thread's active :class:`TraceContext`, or None."""
    return getattr(_tls, "ctx", None)


def attach(ctx):
    """Make ``ctx`` the calling thread's active context; returns the
    previous one (pass it back to :func:`detach`)."""
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    return prev


def detach(prev):
    """Restore the context :func:`attach` displaced."""
    _tls.ctx = prev


# ------------------------------------------------------- span annotations

def annotate(**attrs):
    """Attach attributes to the span the CURRENT dispatch is being
    timed under (the ladder's rung/pad/slice detail).  The attrs park
    on a thread-local slot; the owner of the span collects them with
    :func:`take_annotations` when it records the span.  No-op without
    an active context."""
    if getattr(_tls, "ctx", None) is None:
        return
    d = getattr(_tls, "pending", None)
    if d is None:
        d = _tls.pending = {}
    d.update(attrs)


def take_annotations():
    """Drain and return the calling thread's pending span attributes."""
    d = getattr(_tls, "pending", None)
    if not d:
        return {}
    _tls.pending = {}
    return d


# ------------------------------------------------------------ the handle

class _NullTrace:
    """The shared disabled-trace handle: every method is a no-op and
    ``trace_id``/``ctx`` are None.  Returned by :func:`start_trace`
    when tracing is off so the request path allocates nothing."""

    __slots__ = ()
    ctx = None
    trace_id = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def annotate(self, **attrs):
        pass

    def set_status(self, status, **attrs):
        pass


NULL_TRACE = _NullTrace()


class Trace:
    """A root span + trace lifetime, used as a context manager.  On
    exit the trace is finalized: tail-sampling decides retention, kept
    traces land in the ring and (``MXNET_TPU_TRACE_DIR``) the per-rank
    JSONL export."""

    __slots__ = ("ctx", "name", "_attrs", "_prev", "_t0", "_p0")

    def __init__(self, name, ctx, attrs=None):
        self.name = name
        self.ctx = ctx
        self._attrs = dict(attrs) if attrs else {}
        self._prev = None
        self._t0 = 0.0
        self._p0 = 0.0

    @property
    def trace_id(self):
        return self.ctx.trace_id

    def __enter__(self):
        self._prev = attach(self.ctx)
        self._t0 = time.time()
        self._p0 = time.perf_counter()
        doc = {"trace_id": self.ctx.trace_id, "root": self.name,
               "rank": _rank(), "ts": round(self._t0, 6),
               "status": "ok", "attrs": self._attrs, "spans": []}
        with _lock:
            _active[self.ctx.trace_id] = doc
        return self

    def annotate(self, **attrs):
        """Merge attributes onto the trace document."""
        with _lock:
            doc = _active.get(self.ctx.trace_id)
            if doc is not None:
                doc["attrs"].update(attrs)

    def set_status(self, status, **attrs):
        """Mark the trace's final status (``shed`` / ``error``); later
        exception-driven marking never downgrades it."""
        set_trace_status(self.ctx, status, **attrs)

    def __exit__(self, etype, exc, tb):
        dur = time.perf_counter() - self._p0
        detach(self._prev)
        with _lock:
            doc = _active.pop(self.ctx.trace_id, None)
        if doc is None:
            return False
        if etype is not None and doc["status"] == "ok":
            doc["status"] = "error"
            doc["attrs"].setdefault("error", str(exc)[:200])
        doc["dur_s"] = round(dur, 6)
        doc["spans"].insert(0, {
            "span_id": self.ctx.span_id,
            "parent_id": self.ctx.parent_id,
            "name": self.name, "ts": doc["ts"],
            "dur_s": doc["dur_s"]})
        _finish(doc)
        return False


def start_trace(name, traceparent=None, attrs=None):
    """Begin a trace rooted at ``name``; use as a context manager.
    ``traceparent`` (a W3C header value) continues an inbound trace —
    the root span becomes a child of the remote parent under the SAME
    trace id.  Returns :data:`NULL_TRACE` when tracing is disabled."""
    if sample_rate() <= 0.0:
        return NULL_TRACE
    parent = parse_traceparent(traceparent) if traceparent else None
    if parent is not None:
        ctx = TraceContext(parent[0], new_span_id(), parent[1])
    else:
        ctx = TraceContext(new_trace_id(), new_span_id(), None)
    return Trace(name, ctx, attrs=attrs)


def record_span(ctx, name, ts, dur_s, attrs=None, links=None,
                status=None, span_id=None):
    """Record one finished span as a child of ``ctx`` (any thread may
    call — this is the explicit-attach path the batch scheduler uses).
    ``ts`` is epoch seconds, ``dur_s`` wall seconds.  ``links`` is a
    list of ``{"trace_id", "span_id"}`` references (batch fan-in: one
    dispatch, many parents).  Pass ``span_id`` to pin the id (the same
    dispatch span recorded into N member traces keeps ONE id).
    Returns the span id, or None when the trace is not active."""
    if ctx is None:
        return None
    rec = {"span_id": span_id or new_span_id(),
           "parent_id": ctx.span_id, "name": name,
           "ts": round(ts, 6), "dur_s": round(dur_s, 6)}
    if attrs:
        rec["attrs"] = dict(attrs)
    if links:
        rec["links"] = list(links)
    if status:
        rec["status"] = status
    with _lock:
        doc = _active.get(ctx.trace_id)
        if doc is None:
            return None
        doc["spans"].append(rec)
    return rec["span_id"]


def set_trace_status(ctx, status, **attrs):
    """Mark an in-flight trace's final status by context (``shed``
    with its reason, ``error``); merges ``attrs`` into the trace."""
    if ctx is None:
        return
    with _lock:
        doc = _active.get(ctx.trace_id)
        if doc is not None:
            doc["status"] = str(status)
            doc["attrs"].update(attrs)


# ------------------------------------------------- finalize / tail-sample

def _hash_unit(trace_id):
    """Deterministic [0, 1) from the trace id — every rank samples the
    same traces."""
    try:
        return int(trace_id[:13], 16) / float(16 ** 13)
    except (ValueError, TypeError):
        return 0.0


def _slow_threshold():
    """Duration at the slow percentile of the recent-roots window, or
    None until 20 roots have finished (early traces fall through to
    the sample gate)."""
    with _lock:
        durs = sorted(_durs)
    if len(durs) < 20:
        return None
    i = min(len(durs) - 1, int(slow_pct() * len(durs)))
    return durs[i]


def _count(name, label, value):
    try:
        from mxnet_tpu.telemetry.registry import counter
    except ImportError:       # loaded by path (supervisor/tools half)
        return
    key = (name, value)
    c = _counters.get(key)
    if c is None:
        c = _counters[key] = counter(name).labels(**{label: value})
    c.inc()


def _finish(doc):
    status = doc["status"]
    dur = doc["dur_s"]
    thresh = _slow_threshold()
    with _lock:
        _durs.append(dur)
    if status != "ok":
        keep, why = True, status          # error / shed: always kept
    elif thresh is not None and dur >= thresh:
        keep, why = True, "slow"          # the slow tail: always kept
    else:
        keep, why = _hash_unit(doc["trace_id"]) < sample_rate(), \
            "sampled"
    _count("mxtpu_traces_total", "status", status)
    if not keep:
        return
    doc["keep"] = why
    _count("mxtpu_traces_kept_total", "reason", why)
    cap = ring_capacity()
    with _lock:
        _ring.append(doc)
        while len(_ring) > cap:
            _ring.popleft()
    d = trace_dir()
    if d:
        _export(doc, d)


def _export(doc, directory):
    path = os.path.join(directory, "trace.rank%d.jsonl" % _rank())
    line = json.dumps(dict(doc, schema=TRACE_SCHEMA), sort_keys=True,
                      default=repr)
    try:
        os.makedirs(directory, exist_ok=True)
        with _lock:
            with open(path, "a") as f:
                f.write(line + "\n")
    except OSError as e:
        if not _warned_write[0]:
            _warned_write[0] = True
            log.warning("tracing: cannot append trace to %s: %s",
                        path, e)


# --------------------------------------------------------- ring access

def traces():
    """Kept traces, oldest first (copies of the ring)."""
    with _lock:
        return [dict(t) for t in _ring]


def get_trace(trace_id):
    """One kept trace by id, or None."""
    with _lock:
        for t in reversed(_ring):
            if t["trace_id"] == trace_id:
                return dict(t)
    return None


def reset():
    """Drop in-flight and kept traces, the duration window, and the
    calling thread's context (``telemetry.reset()`` calls this)."""
    with _lock:
        _active.clear()
        _ring.clear()
        _durs.clear()
    _tls.ctx = None
    _tls.pending = {}


# ----------------------------------------------------------- exemplars

def exemplar_for(metric, labels=None):
    """The trace id remembered by the highest (slowest) populated
    bucket of a histogram whose labels contain ``labels`` — the
    exemplar healthd alerts and serve_top name next to p99.  None when
    the metric has no exemplars (or the registry is unavailable —
    by-path loads)."""
    try:
        from mxnet_tpu.telemetry.registry import REGISTRY
    except ImportError:
        return None
    m = REGISTRY.get(metric)
    if m is None or getattr(m, "kind", None) != "histogram":
        return None
    want = {k: str(v) for k, v in (labels or {}).items()}
    best = None
    for key, s in m.samples().items():
        kv = dict(key)
        if any(kv.get(k) != v for k, v in want.items()):
            continue
        for i, rec in (s.get("exemplars") or {}).items():
            if best is None or i > best[0] or \
                    (i == best[0] and rec[2] > best[2]):
                best = (i, rec[0], rec[2])
    return best[1] if best else None


# ======================================================================
# Reader / merge half — stdlib only; launch.py and tools/trace_top.py
# load this module by file path and must never touch the framework.
# ======================================================================

def read_trace_lines(path):
    """Parse one ``mxtpu-trace/1`` JSONL file -> list of trace docs.
    Raises ValueError on a wrong-schema line (trace files are
    machine-written; silent tolerance would hide producer bugs)."""
    out = []
    with open(path) as f:
        for ln, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            doc = json.loads(line)
            if doc.get("schema") != TRACE_SCHEMA:
                raise ValueError(
                    "%s:%d: schema %r != %s"
                    % (path, ln, doc.get("schema"), TRACE_SCHEMA))
            out.append(doc)
    return out


def read_traces(path):
    """Traces from a file, or from every ``trace*.jsonl`` of a
    directory MERGED by trace id (a fleet-wide trace becomes one
    doc)."""
    if os.path.isdir(path):
        docs = []
        for name in sorted(os.listdir(path)):
            if name.startswith("trace") and name.endswith(".jsonl") \
                    and name != "trace.merged.jsonl":
                docs.extend(read_trace_lines(os.path.join(path, name)))
        return merge_traces(docs)
    return merge_traces(read_trace_lines(path))


_STATUS_RANK = {"ok": 0, "shed": 1, "error": 2}


def merge_traces(docs):
    """Group per-rank trace docs by trace id: spans concatenate, the
    root comes from the doc that owns the root span (no parent), the
    status escalates (error > shed > ok), and ``ranks`` lists every
    contributor.  Order: first appearance."""
    merged, order = {}, []
    for doc in docs:
        tid = doc.get("trace_id")
        cur = merged.get(tid)
        if cur is None:
            cur = dict(doc)
            cur["ranks"] = [doc.get("rank", 0)]
            merged[tid] = cur
            order.append(tid)
            continue
        had_root = any(s.get("parent_id") is None
                       for s in cur.get("spans", ()))
        seen = {s.get("span_id") for s in cur.get("spans", ())}
        cur["spans"] = list(cur.get("spans", ())) + [
            s for s in doc.get("spans", ())
            if s.get("span_id") not in seen]
        if doc.get("rank", 0) not in cur["ranks"]:
            cur["ranks"].append(doc.get("rank", 0))
        if _STATUS_RANK.get(doc.get("status"), 0) > \
                _STATUS_RANK.get(cur.get("status"), 0):
            cur["status"] = doc.get("status")
        cur["dur_s"] = max(cur.get("dur_s", 0.0),
                           doc.get("dur_s", 0.0))
        # the doc holding the parentless root span names the trace
        if not had_root and any(s.get("parent_id") is None
                                for s in doc.get("spans", ())):
            cur["root"] = doc.get("root")
            cur["rank"] = doc.get("rank", 0)
            cur["ts"] = doc.get("ts")
    return [merged[t] for t in order]


def merge_trace_dir(directory, out_path=None):
    """Merge every per-rank trace file of ``directory`` into
    ``trace.merged.jsonl`` (one line per fleet-wide trace); returns
    the written path, or None when there was nothing to merge."""
    docs = read_traces(directory)
    if not docs:
        return None
    out_path = out_path or os.path.join(directory,
                                        "trace.merged.jsonl")
    tmp = "%s.tmp.%d" % (out_path, os.getpid())
    with open(tmp, "w") as f:
        for doc in docs:
            f.write(json.dumps(dict(doc, schema=TRACE_SCHEMA),
                               sort_keys=True, default=repr) + "\n")
    os.replace(tmp, out_path)
    return out_path


# ------------------------------------------------------- critical path

def critical_path(doc):
    """Per-span-name EXCLUSIVE seconds for one trace: each span's wall
    minus its direct children's wall (clamped at 0), so concurrent
    instrumentation depth never double-counts.  The aggregate
    ``trace_top`` ranks."""
    spans = doc.get("spans") or []
    child_wall = {}
    for s in spans:
        p = s.get("parent_id")
        if p is not None:
            child_wall[p] = child_wall.get(p, 0.0) \
                + float(s.get("dur_s") or 0.0)
    out = {}
    for s in spans:
        excl = max(0.0, float(s.get("dur_s") or 0.0)
                   - child_wall.get(s.get("span_id"), 0.0))
        out[s.get("name") or "?"] = out.get(s.get("name") or "?", 0.0) \
            + excl
    return out


def dominant_segment(doc):
    """``(name, exclusive_s)`` of the segment the trace's wall lives
    in, or (None, 0.0) for an empty trace."""
    cp = critical_path(doc)
    if not cp:
        return None, 0.0
    name = max(cp, key=cp.get)
    return name, cp[name]
