"""Training-health numerics: jit-safe tensor statistics, NaN/Inf
provenance, and a determinism/divergence ledger.

The observability stack so far measures *time* (spans, distview,
costdb) and *space* (memory plans, HBM gauges); this module watches the
*values*.  Reference analogue: ``python/mxnet/monitor.py``'s per-node
stat callbacks — but computed INSIDE the jitted step as a small extra
output (a handful of scalars per named tensor), so there is no
host-sync-per-node MXL002 hazard on the training hot path.

Three layers:

* **in-graph stats** (:func:`tensor_stats`, :func:`step_stats`) — per
  named param/grad (and per fused-block output when block fusion is
  active, via the :func:`note_block` trace hook in
  ``analysis.fusion.apply_block``): l2 norm, mean/max absolute value,
  non-finite count, zero fraction, and a bit-level value digest, plus a
  global gradient norm.  Computed as traced reductions in the SAME
  compiled program as the step; sampled every
  ``MXNET_TPU_NUMERICS_EVERY`` steps (0 = off).  Unsampled steps
  dispatch the unmodified step program — the stats variant is a
  separate compile.
* **anomaly rules** (:func:`process_step`) — ``nonfinite`` (any
  non-finite value in a watched tensor), ``grad_spike`` (global grad
  norm above ``MXNET_TPU_NUMERICS_SPIKE`` x its running EWMA), and
  ``dead_grad`` (a gradient whose zero fraction reaches
  ``MXNET_TPU_NUMERICS_DEAD``).  Every firing emits a
  ``numerics_anomaly`` flight event and bumps
  ``mxtpu_numerics_anomalies_total{rule}``; under
  ``MXNET_TPU_NUMERICS_STRICT`` the flight ring is dumped and a
  descriptive :class:`~mxnet_tpu.base.MXNetError` is raised naming the
  step, the tensors, and — for non-finite values — the first producing
  node found by eager re-execution (NaN/Inf *provenance*, the
  executor's ``_forward_monitored`` path at node granularity).
* **divergence ledger** — one JSON line per sampled step (schema
  ``mxtpu-numerics/1``) appended to ``MXNET_TPU_NUMERICS_LEDGER``:
  the per-tensor stats + digests and the global grad norm.  The
  compact pair (``grad_norm``, ``digest``) also rides the step's
  telemetry JSONL record, flows through ``distview.RunAggregator``
  into the ``mxtpu-run/1`` timeline, and surfaces as per-rank columns
  in ``tools/run_top.py``.  ``tools/numdiff.py`` compares two ledgers
  (fused vs unfused, pre- vs post-reshard resume, rank vs rank, run vs
  run) and names the first diverging step and tensor with magnitude.

Metrics: ``mxtpu_tensor_norm{tensor,kind}``,
``mxtpu_grad_global_norm``, ``mxtpu_nonfinite_total{tensor}``,
``mxtpu_numerics_anomalies_total{rule}``.  See
``docs/api/telemetry.md`` for the full contract.

Import discipline (the distview pattern): module-level imports are
stdlib-only and in-package imports are deferred into the functions
that need them, so ``tools/numdiff.py`` — a supervisor-side reader —
can load this file by path without dragging jax into the process.
The ledger reader half (:func:`read_ledger`, :func:`compare_ledgers`)
therefore raises plain :class:`ValueError`.
"""
from __future__ import annotations

import contextlib
import json
import logging
import os
import threading

__all__ = [
    "SCHEMA", "every", "enabled", "sampled", "strict", "ledger_path",
    "tensor_stats", "value_digest", "step_stats", "block_stats",
    "note_block", "process_step", "note_monitored", "read_ledger",
    "compare_ledgers", "summary", "reset",
]

#: ledger record schema tag (one JSON object per sampled step)
SCHEMA = "mxtpu-numerics/1"

#: anomaly rule names (the ``rule`` label values)
RULES = ("nonfinite", "grad_spike", "dead_grad")

_log = logging.getLogger(__name__)

_lock = threading.Lock()
# running EWMA of the global grad norm per program (the grad_spike
# baseline) and the process-level roll-up summary() reports
_state = {
    "ewma": {},          # program -> (ewma value, samples folded in)
    "sampled": 0,        # sampled steps processed
    "last_grad_norm": None,
    "last_step": None,
}
_ledger = {"path": None, "fh": None}

# trace-time fused-block stat sink (see block_stats/note_block);
# thread-local because jit traces run on the calling thread
_TLS = threading.local()


# ----------------------------------------------------------- env knobs

def every():
    """Sampling cadence (``MXNET_TPU_NUMERICS_EVERY``): compute the
    in-graph stats every Nth step (step 1 is always sampled when
    enabled); 0 (default) disables numerics entirely."""
    try:
        n = int(os.environ.get("MXNET_TPU_NUMERICS_EVERY", "0"))
    except ValueError:
        return 0
    return max(0, n)


def enabled():
    """True when numerics sampling is on (``every() > 0``)."""
    return every() > 0


def sampled(step):
    """True when 1-based step number ``step`` is a sampled step."""
    n = every()
    return n > 0 and (int(step) - 1) % n == 0


def strict():
    """``MXNET_TPU_NUMERICS_STRICT``: anomalies raise a descriptive
    MXNetError (after dumping the flight ring) instead of warning."""
    return os.environ.get("MXNET_TPU_NUMERICS_STRICT", "0") == "1"


def spike_factor():
    """``MXNET_TPU_NUMERICS_SPIKE``: grad_spike fires when the global
    grad norm exceeds this factor times its running EWMA (default 10);
    ``<= 0`` disables the rule (the repo-wide '0 = off' convention —
    strict runs can keep NaN detection with the spike alarm off)."""
    try:
        f = float(os.environ.get("MXNET_TPU_NUMERICS_SPIKE", "10"))
    except ValueError:
        return 10.0
    return max(f, 0.0)


def dead_threshold():
    """``MXNET_TPU_NUMERICS_DEAD``: dead_grad fires when a gradient's
    zero fraction reaches this value (default 1.0 — only an entirely
    zero gradient); ``<= 0`` disables the rule (the repo-wide '0 =
    off' env convention — a 0 threshold would fire on every grad)."""
    try:
        f = float(os.environ.get("MXNET_TPU_NUMERICS_DEAD", "1.0"))
    except ValueError:
        return 1.0
    return min(f, 1.0)


def ledger_path():
    """Ledger destination (``MXNET_TPU_NUMERICS_LEDGER``), or None when
    the ledger is off.  One file per rank: a multi-process launch must
    assign distinct paths per worker (the same contract as
    ``MXNET_TPU_TELEMETRY_JSONL`` under ``tools/launch.py``)."""
    return os.environ.get("MXNET_TPU_NUMERICS_LEDGER") or None


# ------------------------------------------------------ in-graph stats

def tensor_stats(x, digest=False):
    """The per-tensor stat bundle as traced scalar reductions (safe
    inside jit — this IS how the stats ride the compiled step).

    Returns ``{"l2", "mean_abs", "max_abs", "nonfinite", "zero_frac"}``
    (+ ``"digest"`` when requested): the l2/mean/max are computed over
    the FINITE values (a single NaN must not erase the magnitude
    signal), ``nonfinite`` counts NaN/Inf entries, ``zero_frac`` is the
    exact-zero fraction, and ``digest`` is the wrapping uint32 sum of
    the float32 bit patterns — equal values give equal digests, so two
    ledgers can be compared for bit-cleanliness without shipping the
    tensors."""
    import jax
    import jax.numpy as jnp

    xf = jnp.asarray(x).astype(jnp.float32)
    if xf.size == 0:
        z = jnp.float32(0)
        out = {"l2": z, "mean_abs": z, "max_abs": z,
               "nonfinite": jnp.int32(0), "zero_frac": z}
        if digest:
            out["digest"] = jnp.uint32(0)
        return out
    finite = jnp.isfinite(xf)
    xz = jnp.where(finite, xf, jnp.float32(0))
    ab = jnp.abs(xz)
    out = {
        "l2": jnp.sqrt(jnp.sum(xz * xz)),
        "mean_abs": jnp.mean(ab),
        "max_abs": jnp.max(ab),
        "nonfinite": jnp.sum(~finite).astype(jnp.int32),
        "zero_frac": jnp.mean((xf == 0).astype(jnp.float32)),
    }
    if digest:
        out["digest"] = value_digest(xf)
    return out


def value_digest(x):
    """Wrapping uint32 sum of the float32 bit patterns of ``x`` — a
    cheap in-graph value digest: order-independent, deterministic, and
    bit-sensitive (any changed value almost surely changes it)."""
    import jax
    import jax.numpy as jnp
    xf = jnp.asarray(x).astype(jnp.float32)
    bits = jax.lax.bitcast_convert_type(xf, jnp.uint32)
    return jnp.sum(bits, dtype=jnp.uint32)


def step_stats(params, grads, blocks=None, loss=None):
    """The full sampled-step stat tree, traced inside the step program:
    per-param and per-grad :func:`tensor_stats` (+digests), the merged
    fused-block output stats (``blocks``: a :func:`block_stats` sink),
    the global gradient l2 norm, and the loss.  Every leaf is a scalar,
    so the extra device->host traffic per sampled step is a few dozen
    numbers regardless of model size."""
    import jax.numpy as jnp

    tensors = {}
    sq = jnp.float32(0)
    for name in sorted(params):
        tensors["param/%s" % name] = tensor_stats(params[name],
                                                  digest=True)
    for name in sorted(grads):
        st = tensor_stats(grads[name], digest=True)
        tensors["grad/%s" % name] = st
        sq = sq + st["l2"] * st["l2"]
    for name, st in sorted((blocks or {}).items()):
        tensors[name] = st
    out = {"tensors": tensors, "grad_norm": jnp.sqrt(sq)}
    if loss is not None:
        out["loss"] = jnp.asarray(loss).astype(jnp.float32)
    return out


@contextlib.contextmanager
def block_stats(active=True):
    """Trace-time collection window for fused-block output stats.  The
    trainer wraps its forward/vjp trace in this context on the stats
    variant only; ``analysis.fusion.apply_block`` feeds it through
    :func:`note_block`.  Yields the sink dict (``None`` when
    inactive)."""
    if not active:
        yield None
        return
    prev = getattr(_TLS, "blocks", None)
    _TLS.blocks = {}
    try:
        yield _TLS.blocks
    finally:
        _TLS.blocks = prev


def note_block(name, out):
    """Record one fused-block output into the active collection window
    (no-op — zero added jaxpr equations — outside a
    :func:`block_stats` context).  Never raises: the trace being fused
    must not pay for observability."""
    sink = getattr(_TLS, "blocks", None)
    if sink is None:
        return
    try:
        sink["block/%s" % name] = tensor_stats(out)
    except MemoryError:  # pragma: no cover - never mask resource exhaustion
        raise
    except Exception:  # mxlint: allow-broad-except(trace-time observability; a stat failure must not fail the trace that is being fused)
        pass


# ----------------------------------------------------- host-side pump

def _rank():
    from . import distview
    return distview.rank()


def _round(v):
    return round(float(v), 9)


def _host_payload(stats, step, program):
    """Fetch the stats tree (ONE device sync for the whole bundle) and
    shape it into the ledger record."""
    import jax
    host = jax.device_get(stats)
    tensors = {}
    total_digest = 0
    for name, st in sorted((host.get("tensors") or {}).items()):
        rec = {
            "l2": _round(st["l2"]),
            "mean_abs": _round(st["mean_abs"]),
            "max_abs": _round(st["max_abs"]),
            "nonfinite": int(st["nonfinite"]),
            "zero_frac": _round(st["zero_frac"]),
        }
        if "digest" in st:
            rec["digest"] = int(st["digest"])
            total_digest = (total_digest + rec["digest"]) & 0xFFFFFFFF
        tensors[name] = rec
    payload = {
        "schema": SCHEMA,
        "step": int(step),
        "rank": _rank(),
        "program": str(program),
        "grad_norm": _round(host["grad_norm"])
        if "grad_norm" in host else None,
        "digest": total_digest,
        "tensors": tensors,
    }
    if "loss" in host:
        payload["loss"] = _round(host["loss"])
    return payload


def _publish_gauges(payload):
    from .registry import counter, gauge
    norm_g = gauge("mxtpu_tensor_norm")
    for name, st in payload["tensors"].items():
        kind = name.split("/", 1)[0]
        norm_g.labels(tensor=name.split("/", 1)[-1],
                      kind=kind).set(st["l2"])
        if st.get("nonfinite"):
            counter("mxtpu_nonfinite_total").labels(
                tensor=name).inc(st["nonfinite"])
    if payload.get("grad_norm") is not None:
        gauge("mxtpu_grad_global_norm").set(payload["grad_norm"])


def _check_rules(payload, scope=None):
    """Evaluate the anomaly rules against one payload; returns the list
    of fired anomalies (dicts with at least ``rule``).  ``scope`` keys
    the grad_spike EWMA baseline (defaults to the program name —
    callers owning multiple step streams pass a per-instance token so
    one model's baseline cannot false-trip another's)."""
    import math
    anomalies = []
    bad = [n for n, st in sorted(payload["tensors"].items())
           if st.get("nonfinite")]
    gn = payload.get("grad_norm")
    if gn is not None and not math.isfinite(gn):
        if "grad_norm" not in bad:
            bad.append("grad_norm")
    if bad:
        anomalies.append({
            "rule": "nonfinite", "tensors": bad[:16],
            "total": sum(payload["tensors"].get(n, {}).get("nonfinite", 0)
                         for n in bad)})
    factor = spike_factor()
    if gn is not None and math.isfinite(gn) and factor > 0:
        key = scope if scope is not None else payload["program"]
        with _lock:
            ew = _state["ewma"].get(key)
            if ew is not None and ew[0] > 0 and gn > factor * ew[0]:
                anomalies.append({"rule": "grad_spike", "grad_norm": gn,
                                  "ewma": _round(ew[0]),
                                  "factor": factor})
                # the baseline is NOT updated with the spike: repeated
                # explosions keep firing instead of normalizing the alarm
            elif ew is None:
                _state["ewma"][key] = (gn, 1)
            else:
                _state["ewma"][key] = (0.9 * ew[0] + 0.1 * gn,
                                       ew[1] + 1)
    thresh = dead_threshold()
    dead = [] if thresh <= 0 else \
        [n for n, st in sorted(payload["tensors"].items())
         if n.startswith("grad/") and st.get("nonfinite", 0) == 0
         and st.get("zero_frac", 0.0) >= thresh]
    if dead:
        anomalies.append({"rule": "dead_grad", "tensors": dead[:16],
                          "threshold": thresh})
    return anomalies


def json_safe(obj):
    """Recursively map non-finite floats to None so a payload always
    serializes as STRICT JSON (`json.dumps(allow_nan=True)` would emit
    a bare ``NaN`` token jq and non-Python consumers reject — the
    ledger contract is one valid JSON object per line)."""
    import math
    if isinstance(obj, float) and not math.isfinite(obj):
        return None
    if isinstance(obj, dict):
        return {k: json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [json_safe(v) for v in obj]
    return obj


def _ledger_handle():
    path = ledger_path()
    if path != _ledger["path"]:
        if _ledger["fh"] is not None:
            try:
                _ledger["fh"].close()
            except OSError:
                pass
        fh = None
        if path:
            try:
                fh = open(path, "a")
            except OSError as e:
                _log.warning(
                    "MXNET_TPU_NUMERICS_LEDGER=%r cannot be opened "
                    "(%s); ledger disabled for this run", path, e)
        _ledger["fh"] = fh
        _ledger["path"] = path
    return _ledger["fh"]


def _write_ledger(payload):
    with _lock:
        fh = _ledger_handle()
        if fh is None:
            return False
        try:
            fh.write(json.dumps(json_safe(payload), sort_keys=True,
                                allow_nan=False) + "\n")
            fh.flush()
        except (OSError, ValueError):
            return False
        return True


def _raise_strict(payload, anomalies, provenance):
    """Dump the flight ring, then raise the descriptive error.  The
    exception is tagged so outer ``flight.crash_guard`` levels pass it
    through instead of dumping a second black box."""
    from ..base import MXNetError
    from . import flight
    rules = [a["rule"] for a in anomalies]
    names = sorted({n for a in anomalies for n in a.get("tensors", ())})
    dump_path = flight.dump("numerics")
    msg = ("numerics anomaly at step %d (%s): rule(s) %s fired on %s"
           % (payload["step"], payload["program"], "/".join(rules),
              names[:8] or ["<global>"]))
    if payload.get("grad_norm") is not None:
        msg += "; global grad norm %g" % payload["grad_norm"]
    if provenance:
        msg += ("; first non-finite producing node: %r (%s non-finite "
                "value(s) in the eager replay)"
                % (provenance.get("node"),
                   provenance.get("nonfinite", "?")))
    if dump_path:
        msg += "; flight dump: %s" % dump_path
    msg += (" — MXNET_TPU_NUMERICS_STRICT=1 stops the run on the first "
            "anomaly; see docs/api/telemetry.md")
    err = MXNetError(msg)
    err._mxtpu_flight_dumped = True
    raise err


def process_step(stats, step, program="trainer.step",
                 provenance_fn=None, scope=None):
    """Publish one sampled step's stat tree: fetch it (one sync),
    update the gauges/counters, append the ledger record, and run the
    anomaly rules.  ``provenance_fn``: zero-arg callable invoked only
    when non-finite values were detected; it should replay the step
    eagerly and return ``{"node": name, "nonfinite": count}`` for the
    first producing node (or None).  ``scope``: per-caller token keying
    the grad_spike EWMA (the trainer passes an instance-unique one so
    two models in one process keep separate baselines).  Returns the
    ledger payload (with ``"anomalies"`` attached when any rule
    fired); raises MXNetError in strict mode after dumping the flight
    ring."""
    try:
        payload = _host_payload(stats, step, program)
    except MemoryError:  # pragma: no cover - never mask resource exhaustion
        raise
    except Exception as e:  # mxlint: allow-broad-except(observability: a stats fetch/shape failure must not kill the training step it observes)
        _log.warning("numerics: cannot fetch step stats: %s", e)
        return None
    with _lock:
        _state["sampled"] += 1
        _state["last_grad_norm"] = payload.get("grad_norm")
        _state["last_step"] = payload["step"]
    _publish_gauges(payload)
    anomalies = _check_rules(payload, scope=scope)
    _write_ledger(payload)
    if not anomalies:
        return payload
    payload["anomalies"] = anomalies
    provenance = None
    if provenance_fn is not None and \
            any(a["rule"] == "nonfinite" for a in anomalies):
        try:
            provenance = provenance_fn()
        except MemoryError:  # pragma: no cover - never mask resource exhaustion
            raise
        except Exception as e:  # mxlint: allow-broad-except(the eager provenance replay is best-effort forensics on a run that is already anomalous; its failure must not mask the anomaly)
            _log.warning("numerics: provenance replay failed: %s", e)
    if provenance:
        payload["provenance"] = provenance
    from .registry import counter
    from . import flight
    anom_counter = counter("mxtpu_numerics_anomalies_total")
    for a in anomalies:
        anom_counter.labels(rule=a["rule"]).inc()
        ev = {"rule": a["rule"], "step": payload["step"],
              "program": payload["program"]}
        if a.get("tensors"):
            ev["tensors"] = a["tensors"]
        if payload.get("grad_norm") is not None:
            ev["grad_norm"] = payload["grad_norm"]
        if a["rule"] == "grad_spike":
            ev["ewma"] = a.get("ewma")
        if provenance and a["rule"] == "nonfinite":
            ev["provenance"] = provenance
        flight.record("numerics_anomaly", **ev)
    if strict():
        _raise_strict(payload, anomalies, provenance)
    _log.warning(
        "numerics anomaly at step %d (%s): %s (MXNET_TPU_NUMERICS_"
        "STRICT=1 would stop the run)", payload["step"],
        payload["program"],
        "; ".join("%s on %s" % (a["rule"], a.get("tensors", ["<global>"]))
                  for a in anomalies))
    return payload


def note_monitored(stats_by_name, program="executor.forward",
                   step=None):
    """Anomaly pass over a jit-safe monitored forward's per-node stat
    bundles (``{node name: tensor_stats dict of host scalars}``): count
    non-finite values per node, and — since per-node stats ARE the
    provenance — name the first non-finite producing node directly in
    the ``numerics_anomaly`` event.  Strict mode raises like
    :func:`process_step`."""
    from .registry import counter, gauge
    from . import flight
    bad = []
    norm_g = gauge("mxtpu_tensor_norm")
    for name in sorted(stats_by_name):
        st = stats_by_name[name]
        if st.get("l2") is not None:
            norm_g.labels(tensor=name, kind="node").set(st["l2"])
        n = int(st.get("nonfinite", 0))
        if n:
            counter("mxtpu_nonfinite_total").labels(
                tensor="node/%s" % name).inc(n)
            bad.append((name, n))
    if not bad:
        return None
    first = {"node": bad[0][0], "nonfinite": bad[0][1]}
    counter("mxtpu_numerics_anomalies_total").labels(
        rule="nonfinite").inc()
    ev = {"rule": "nonfinite", "program": program,
          "tensors": [n for n, _c in bad[:16]], "provenance": first}
    if step is not None:
        ev["step"] = int(step)
    flight.record("numerics_anomaly", **ev)
    if strict():
        payload = {"step": int(step or 0), "program": program,
                   "grad_norm": None}
        _raise_strict(payload,
                      [{"rule": "nonfinite",
                        "tensors": [n for n, _c in bad[:16]]}], first)
    return first


# ------------------------------------------------------- ledger reader

def read_ledger(path):
    """Parse a numerics ledger: returns the list of ``mxtpu-numerics/1``
    records (ascending step order preserved).  Accepts a pure ledger
    file or a telemetry JSONL stream carrying ledger records inline
    (a ``"numerics"`` sub-object per step record).  Raises ValueError
    when the file is unreadable or contains no record with the
    schema — a wrong-schema file must be rejected, not silently
    compared as empty."""
    try:
        with open(path) as f:
            raw = f.read()
    except OSError as e:
        raise ValueError("cannot read numerics ledger %r: %s"
                         % (path, e))
    records = []
    saw_line = False
    for line in raw.split("\n"):
        line = line.strip()
        if not line:
            continue
        saw_line = True
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if not isinstance(rec, dict):
            continue
        if rec.get("schema") == SCHEMA:
            records.append(rec)
        elif isinstance(rec.get("numerics"), dict) and \
                rec["numerics"].get("schema") == SCHEMA:
            records.append(rec["numerics"])
    if not records:
        raise ValueError(
            "%r is not an %s ledger (%s)"
            % (path, SCHEMA,
               "no parseable lines" if not saw_line
               else "no record carries the schema"))
    for rec in records:
        if not isinstance(rec.get("step"), int) or \
                not isinstance(rec.get("tensors"), dict):
            raise ValueError(
                "numerics ledger %r: malformed record (needs an int "
                "'step' and a 'tensors' object): %r"
                % (path, {k: rec.get(k) for k in ("step", "tensors")}))
    return records


def compare_ledgers(recs_a, recs_b, rtol=1e-4, atol=1e-9):
    """Compare two ledgers (record lists from :func:`read_ledger`).

    Walks the common steps in ascending order; per step, the common
    tensor names (tensors present in only one ledger — e.g. ``block/*``
    entries a fused run adds — are counted, not compared).  Returns a
    dict::

        {"steps_compared", "tensors_compared", "only_a", "only_b",
         "bit_clean": bool,
         "first_bit_divergence": {"step", "tensor"} | None,
         "divergence": {"step", "tensor", "stat", "a", "b",
                        "rel"} | None}

    ``divergence`` is the first (step, tensor) whose l2/mean_abs/
    grad_norm differs beyond ``rtol`` (relative, floored by ``atol``)
    — the bisection answer; ``first_bit_divergence`` is the first
    digest mismatch even when within tolerance (fused-vs-unfused runs
    are rarely bit-identical but must stay within rtol)."""
    a_by = {r["step"]: r for r in recs_a}
    b_by = {r["step"]: r for r in recs_b}
    common = sorted(set(a_by) & set(b_by))
    out = {"steps_compared": len(common), "tensors_compared": 0,
           "only_a": 0, "only_b": 0, "bit_clean": True,
           "first_bit_divergence": None, "divergence": None}

    def rel(x, y):
        d = abs(x - y)
        m = max(abs(x), abs(y), atol)
        return d / m

    for step in common:
        ra, rb = a_by[step], b_by[step]
        ta, tb = ra["tensors"], rb["tensors"]
        names = sorted(set(ta) & set(tb))
        out["only_a"] += len(set(ta) - set(tb))
        out["only_b"] += len(set(tb) - set(ta))
        for name in names:
            out["tensors_compared"] += 1
            sa, sb = ta[name], tb[name]
            da, db = sa.get("digest"), sb.get("digest")
            if da is not None and db is not None and da != db \
                    and out["bit_clean"]:
                out["bit_clean"] = False
                out["first_bit_divergence"] = {"step": step,
                                               "tensor": name}
            # non-finite counts compare EXACTLY, never under rtol: the
            # l2/mean stats are finite-masked, so NaNs appearing in one
            # run and not the other — the worst drift a lowering can
            # have — would otherwise be invisible within tolerance
            na, nb = sa.get("nonfinite"), sb.get("nonfinite")
            if isinstance(na, int) and isinstance(nb, int) \
                    and na != nb and out["divergence"] is None:
                out["divergence"] = {"step": step, "tensor": name,
                                     "stat": "nonfinite", "a": na,
                                     "b": nb,
                                     "rel": round(rel(na, nb), 6)}
            for stat in ("l2", "mean_abs", "max_abs"):
                va, vb = sa.get(stat), sb.get(stat)
                if not isinstance(va, (int, float)) or \
                        not isinstance(vb, (int, float)):
                    continue
                r = rel(va, vb)
                if r > rtol and out["divergence"] is None:
                    out["divergence"] = {"step": step, "tensor": name,
                                         "stat": stat, "a": va, "b": vb,
                                         "rel": round(r, 6)}
            # zero_frac compares ABSOLUTELY (it lives in [0,1]): a
            # relative test would flag a legitimate borderline element
            # flipping zero/nonzero between lowerings (0 vs 1/N is
            # rel=1), while a flush-to-zero corruption still jumps it
            za, zb = sa.get("zero_frac"), sb.get("zero_frac")
            if isinstance(za, (int, float)) and \
                    isinstance(zb, (int, float)) \
                    and abs(za - zb) > rtol \
                    and out["divergence"] is None:
                out["divergence"] = {"step": step, "tensor": name,
                                     "stat": "zero_frac", "a": za,
                                     "b": zb,
                                     "rel": round(abs(za - zb), 6)}
        # the global grad norm is checked AFTER the named tensors so a
        # localizable divergence is reported by name, not by the
        # aggregate that merely reflects it
        gna, gnb = ra.get("grad_norm"), rb.get("grad_norm")
        if isinstance(gna, (int, float)) and \
                isinstance(gnb, (int, float)):
            r = rel(gna, gnb)
            if r > rtol and out["divergence"] is None:
                out["divergence"] = {"step": step, "tensor": "grad_norm",
                                     "stat": "grad_norm", "a": gna,
                                     "b": gnb, "rel": round(r, 6)}
        if out["divergence"] is not None:
            break
    return out


# ------------------------------------------------------------ roll-up

def summary():
    """Process-level numerics roll-up for ``bench.py`` /
    ``report()`` embedding: the sampling cadence, sampled-step and
    per-rule anomaly counts, and the last observed global grad norm."""
    from .registry import counter
    anom = {}
    m = counter("mxtpu_numerics_anomalies_total")
    for key, val in m.samples().items():
        anom[dict(key).get("rule", "?")] = int(val)
    with _lock:
        return {
            "every": every(),
            "strict": strict(),
            "sampled_steps": _state["sampled"],
            "anomalies": anom,
            "last_step": _state["last_step"],
            "last_grad_norm": json_safe(_state["last_grad_norm"]),
            "ledger": ledger_path(),
        }


def reset():
    """Clear the EWMA baselines, the roll-up counters, and the ledger
    handle (the env var is re-read on the next sampled step).
    ``telemetry.reset()`` calls this."""
    with _lock:
        _state["ewma"].clear()
        _state["sampled"] = 0
        _state["last_grad_norm"] = None
        _state["last_step"] = None
        if _ledger["fh"] is not None:
            try:
                _ledger["fh"].close()
            except OSError:
                pass
        _ledger["fh"] = None
        _ledger["path"] = None
