"""Cross-rank run observability: straggler attribution, run-timeline
aggregation, and on-demand live capture.

Everything before this module is strictly per-rank: the JSONL step-log,
the Prometheus endpoint, the Chrome trace, and the flight recorder each
describe ONE process.  The question that dominates multi-host TPU
operations — *which rank is slow, and is it compute, input, or the
collective?* — needs a cross-rank layer, because one straggler stalls
every ``psum`` ("A Learned Performance Model for TPUs", arXiv:2008.01040
treats exactly this per-op/collective attribution as ground truth; here
it is measured, not predicted).  Three pieces:

* **straggler attribution** (worker half): each training step is split
  into ``compute`` / ``input_wait`` / ``collective_wait`` segments
  (:func:`record_step_segments` → ``mxtpu_step_segment_seconds``), and a
  lightweight pre-collective *timestamp barrier*
  (:func:`pre_collective_barrier`) measures — not infers — how long each
  rank waits for its slowest peer (``mxtpu_collective_wait_seconds``)
  and the arrival spread across ranks
  (``mxtpu_rank_step_skew_seconds``);
* **fleet aggregation** (supervisor half): :class:`RunAggregator` tails
  every rank's JSONL step-log (``tools/launch.py`` gives each local
  worker its own ``<base>.rank<N>`` stream) and merges them into ONE
  run-level timeline — schema ``mxtpu-run/1`` — with per-step p50/max
  across ranks, the worst-rank id, skew history, and restart/fault
  events; ``tools/run_top.py`` renders it live and as a postmortem;
* **on-demand live capture** (worker half): a SIGUSR1 handler
  (:func:`install_capture_handler`) and the ``/debug/capture`` endpoint
  capture a bounded ``jax.profiler`` trace window plus a flight-recorder
  snapshot on a RUNNING rank without restarting it;
  ``tools/launch.py --capture`` broadcasts the signal fleet-wide and
  ``tools/xprof_top.py --trace`` feeds the result into the per-op
  attribution flow.

Import discipline: module-level imports are stdlib-only and in-package
imports are deferred into the worker-half functions, so the supervisor
(``tools/launch.py``), ``tools/run_top.py``, and ``tools/flight_read.py``
can load this file by path (``importlib``) without dragging jax — or the
framework — into a process that only aggregates text streams.
"""
from __future__ import annotations

import json
import logging
import os
import signal as _signal
import threading
import time

__all__ = [
    "RUN_SCHEMA", "rank", "world", "skew_every",
    "record_step_segments", "pre_collective_barrier",
    "capture_dir", "capture_seconds", "capture_now", "capture_status",
    "install_capture_handler",
    "rank_jsonl_path", "split_jsonl", "RunAggregator",
    "read_run_timeline", "summarize_run",
]

#: run-timeline schema tag (first line of the ``<base>.run`` JSONL)
RUN_SCHEMA = "mxtpu-run/1"

#: step segment names, in display order
SEGMENTS = ("compute", "input_wait", "collective_wait")


def rank():
    """This process's rank in the launch.py job (0 outside one)."""
    try:
        return int(os.environ.get("MXNET_TPU_PROCESS_ID", "0") or 0)
    except ValueError:
        return 0


def world():
    """Number of processes in the launch.py job (1 outside one)."""
    try:
        return int(os.environ.get("MXNET_TPU_NUM_PROCESSES", "1") or 1)
    except ValueError:
        return 1


# --------------------------------------------------- straggler attribution

def skew_every():
    """Measure the pre-collective timestamp barrier every N collectives
    (``MXNET_TPU_SKEW_EVERY``; 0 disables).  The default samples every
    8th collective: the barrier's allgather returns host values, so each
    measured step gives up async-dispatch run-ahead — a fleet-wide host
    sync that must not be the every-step default; ``1`` opts into
    per-step measurement when hunting a straggler."""
    try:
        return max(0, int(os.environ.get("MXNET_TPU_SKEW_EVERY", "8")))
    except ValueError:
        return 8


def record_step_segments(total_s, input_s=0.0, collective_s=0.0,
                         count=1):
    """Split one step's host wall time into the three segments and
    record them into ``mxtpu_step_segment_seconds{segment=...}``.

    ``compute`` is the remainder (``total - input - collective``,
    floored at 0): on an async backend it covers dispatch *and* the
    device wait, which is exactly the per-rank quantity the aggregator
    compares across the fleet.  ``count`` > 1 (a ``run_steps`` scan
    chain) observes the per-step average ``count`` times — exactly how
    ``step_end`` feeds ``mxtpu_step_seconds`` — so the two histograms'
    sums/counts stay comparable and a chain rank is not under-weighted
    against single-step ranks.  Returns the (un-averaged) segments dict
    for the JSONL record."""
    input_s = max(0.0, float(input_s))
    collective_s = max(0.0, float(collective_s))
    compute_s = max(0.0, float(total_s) - input_s - collective_s)
    seg = {"compute": compute_s, "input_wait": input_s,
           "collective_wait": collective_s}
    from mxnet_tpu.telemetry.registry import histogram
    h = histogram("mxtpu_step_segment_seconds")
    count = max(1, int(count))
    scale = 1.0 / count
    for name, val in seg.items():
        child = h.labels(segment=name)
        for _ in range(count):
            child.observe(val * scale)
    return {k: round(v, 6) for k, v in seg.items()}


_skew_state = {"calls": 0}


def pre_collective_barrier(site="trainer.step"):
    """Timestamp barrier immediately before a cross-process collective.

    Every rank allgathers its arrival wall-clock timestamp; the call
    itself is the barrier, so each rank's *measured local wait* is how
    long it stalled for its slowest peer — the time GSPMD's ``psum``
    would otherwise hide inside the XLA program.  Records:

    * ``mxtpu_collective_wait_seconds`` — this rank's wait (≈0 on the
      straggler, ≈skew on the fastest rank: collective wait is paid by
      the FAST ranks);
    * ``mxtpu_rank_step_skew_seconds`` — the arrival spread
      (max − min timestamp) across ranks, i.e. the straggler's lead.

    Timestamps are wall clock, so cross-HOST skew inherits NTP error
    (~ms); the *wait* is measured locally and is exact everywhere.
    Returns ``{"wait_s", "skew_s", "slowest_rank", "rank"}``, or None
    when disabled (``MXNET_TPU_SKEW_EVERY=0``), off-interval, or
    single-process.  Never raises: a failed barrier degrades to
    unmeasured skew, not a dead training loop."""
    every = skew_every()
    if every == 0:
        return None
    try:
        import jax
        if jax.process_count() <= 1:
            return None
        _skew_state["calls"] += 1
        if (_skew_state["calls"] - 1) % every:
            return None
        import numpy as np
        from jax.experimental import multihost_utils

        if not _skew_state.get("warm"):
            # the first allgather compiles its XLA program: measuring
            # it would record seconds of "collective wait" that are
            # really compile time — burn one untimed round first.
            # warm flips BEFORE the attempt: if the warm-up raises on
            # this rank only, retrying it next interval would have this
            # rank issue one more allgather than its peers — a count
            # desync that hangs the fleet, far worse than one polluted
            # measurement
            _skew_state["warm"] = True
            multihost_utils.process_allgather(
                np.asarray([0.0], np.float64))
        t_arrive = time.time()
        p0 = time.perf_counter()
        ts = np.asarray(multihost_utils.process_allgather(
            np.asarray([t_arrive], np.float64))).reshape(-1)
        wait_s = time.perf_counter() - p0
        skew_s = float(ts.max() - ts.min())
        slowest = int(ts.argmax())
        my_rank = int(jax.process_index())
    except Exception as e:  # mxlint: allow-broad-except(the skew probe is optional instrumentation wrapped around the hot loop — any backend/collective failure here must degrade to "skew unmeasured", never kill the step it observes)
        logging.getLogger(__name__).warning(
            "distview: pre-collective timestamp barrier failed at %s "
            "(%s); skew unmeasured for this step", site, e)
        return None
    from mxnet_tpu.telemetry.registry import gauge, histogram
    histogram("mxtpu_collective_wait_seconds").observe(wait_s)
    gauge("mxtpu_rank_step_skew_seconds").set(skew_s)
    from mxnet_tpu.telemetry import flight
    flight.record("skew", site=site, wait_s=round(wait_s, 6),
                  skew_s=round(skew_s, 6), slowest_rank=slowest)
    return {"wait_s": wait_s, "skew_s": skew_s,
            "slowest_rank": slowest, "rank": my_rank}


# ------------------------------------------------- on-demand live capture

def capture_dir():
    """Destination for on-demand capture windows
    (``MXNET_TPU_CAPTURE_DIR``), or None when capture is off."""
    return os.environ.get("MXNET_TPU_CAPTURE_DIR") or None


def capture_seconds():
    """Bounded trace-window length (``MXNET_TPU_CAPTURE_SECONDS``,
    default 3)."""
    try:
        return max(0.1, float(os.environ.get("MXNET_TPU_CAPTURE_SECONDS",
                                             "3")))
    except ValueError:
        return 3.0


_capture_lock = threading.Lock()
_capture = {"active": False, "installed": False, "last": None}


def capture_status():
    """{"active": bool, "last": dict or None} for the /debug endpoint."""
    with _capture_lock:
        return {"active": _capture["active"], "last": _capture["last"]}


def capture_now(trigger="api", seconds=None, directory=None):
    """Capture a bounded ``jax.profiler`` trace window plus a flight
    snapshot on THIS running rank, without restarting or pausing it.

    The capture runs on a background (non-daemon — see the comment at
    the thread spawn) thread: the signal/HTTP caller returns
    immediately and training continues while xprof samples the device;
    a process that exits mid-window lingers until the capture finishes
    writing.
    Files land under ``<dir>/rank<N>/`` (``MXNET_TPU_CAPTURE_DIR``, or
    ``.profiles/capture``): the trace plus a
    ``flight-*-capture.json`` ring snapshot, which is what
    ``tools/xprof_top.py --trace`` and ``tools/flight_read.py`` consume.
    One capture at a time; a concurrent trigger is reported and dropped.
    Returns ``{"started": bool, "dir": path, ...}``."""
    directory = directory or capture_dir() or os.path.join(".profiles",
                                                           "capture")
    window = capture_seconds() if seconds is None else \
        max(0.1, float(seconds))
    out = os.path.join(directory, "rank%d" % rank())
    # non-blocking: the SIGUSR1 handler runs this on the MAIN thread,
    # possibly between bytecodes of a capture_now/capture_status call
    # that already holds the (non-reentrant) lock — blocking here would
    # deadlock the training thread; a contended trigger is just dropped
    if not _capture_lock.acquire(blocking=False):
        return {"started": False, "dir": out,
                "reason": "capture state busy"}
    try:
        if _capture["active"]:
            return {"started": False, "dir": out,
                    "reason": "capture already in progress"}
        _capture["active"] = True
    finally:
        _capture_lock.release()

    def _run():
        info = {"trigger": trigger, "dir": out, "seconds": window,
                "ts": round(time.time(), 6), "trace": False,
                "flight": None}
        try:
            os.makedirs(out, exist_ok=True)
            from mxnet_tpu.telemetry import flight
            from mxnet_tpu.telemetry.registry import counter
            counter("mxtpu_capture_total").labels(trigger=trigger).inc()
            flight.record("capture", trigger=trigger, seconds=window,
                          dir=out)
            # the ring snapshot first: even if the profiler cannot trace
            # this backend, the capture still yields the black box
            info["flight"] = flight.dump("capture", directory=out)
            import jax
            jax.profiler.start_trace(out)
            try:
                time.sleep(window)
            finally:
                jax.profiler.stop_trace()
            info["trace"] = True
        except Exception as e:  # mxlint: allow-broad-except(on-demand capture piggybacks on a live training process — a profiler/backend failure must log and drop the window, never take the run down with it)
            info["error"] = str(e)
            logging.getLogger(__name__).warning(
                "distview: on-demand capture failed (%s); training "
                "continues", e)
        finally:
            with _capture_lock:
                _capture["active"] = False
                _capture["last"] = info

    # NON-daemon on purpose: jax.profiler's first trace lazily imports
    # its (heavy) xplane tooling, and a daemon thread killed mid-import
    # at interpreter shutdown segfaults the worker — which the launch.py
    # watchdog would read as a dead rank.  A non-daemon thread means a
    # process that exits right after a capture finishes writing it.
    threading.Thread(target=_run, daemon=False,
                     name="mxtpu-capture").start()
    return {"started": True, "dir": out, "seconds": window}


def install_capture_handler(signum=None):
    """Install the SIGUSR1 on-demand capture handler on this process.

    Installed automatically at ``mxnet_tpu.telemetry`` import when
    ``MXNET_TPU_CAPTURE_DIR`` is set (main thread only — signal
    handlers cannot be registered elsewhere); idempotent.  The handler
    only sets the capture off: the window itself runs on a background
    thread, so an in-flight jitted step is never interrupted.
    ``tools/launch.py`` relays SIGUSR1 to every worker, and
    ``tools/launch.py --capture`` triggers that relay on a running job.
    Returns True when the handler is (already) installed."""
    if signum is None:
        signum = _signal.SIGUSR1
    if _capture["installed"]:
        return True
    if threading.current_thread() is not threading.main_thread():
        return False

    def handler(sig, frame):
        capture_now(trigger="signal")

    try:
        _signal.signal(signum, handler)
    except (ValueError, OSError):   # non-main thread race / exotic os
        return False
    _capture["installed"] = True
    return True


# ---------------------------------------------------- fleet aggregation
# Everything below is stdlib-only: tools/launch.py loads this module by
# file path and must never import jax (the supervisor stays light).

def split_jsonl(buf):
    """Tolerantly parse a chunk of a JSONL stream that may end
    mid-append: returns ``(records, partial)`` where ``records`` are
    the parsed dict lines (malformed/non-dict lines skipped) and
    ``partial`` is the unterminated tail to carry into the next chunk.
    The shared core of every live tailer (:meth:`RunAggregator.poll`,
    ``tools/run_top.py --follow``)."""
    lines = buf.split("\n")
    partial = lines.pop()
    records = []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict):
            records.append(rec)
    return records, partial


def rank_jsonl_path(base, r):
    """Per-rank step-log path the launcher assigns to worker ``r``
    (``<base>.rank<N>``); the supervisor keeps ``<base>`` for its own
    events and writes the merged timeline to ``<base>.run``."""
    return "%s.rank%d" % (base, int(r))


class RunAggregator:
    """Merge per-rank JSONL step-logs into one run-level timeline.

    The supervisor polls :meth:`poll` (cheap: incremental reads from
    the last byte offset per rank); whenever a step has been reported
    by every rank — or falls ``window`` steps behind the newest, which
    means some rank died or skipped it — ONE timeline record is
    appended to ``out_path``:

    ``{"kind": "step", "step": N, "n_ranks": k, "p50_s", "max_s",
    "min_s", "worst_rank", "skew_s", "grad_skew", "digest_mismatch",
    "ranks": {rank: {"t_s", "segments", "skew_s", "grad_norm",
    "digest"}}}`` (the numeric fields appear on steps the
    training-health numerics layer sampled — telemetry.numerics)

    plus ``run_begin`` (the schema header), passthrough ``event``
    records (worker start/death, watchdog restarts, flight dumps), and
    a final ``run_end``.  All records are plain JSON lines so the
    timeline can itself be tailed live (``tools/run_top.py --follow``).
    """

    def __init__(self, base_path, num_ranks, out_path=None, window=64):
        self.base = base_path
        self.n = max(1, int(num_ranks))
        self.out_path = out_path or base_path + ".run"
        self.window = max(1, int(window))
        # tail each rank stream from its CURRENT end: workers append
        # ('a' mode), so a rerun over the same base must not ingest the
        # previous job's records — whose repeated step numbers would
        # then shadow the new run's steps as duplicates
        self._offsets = {}        # rank -> (byte offset, partial line)
        for r in range(self.n):
            try:
                self._offsets[r] = (
                    os.path.getsize(rank_jsonl_path(base_path, r)), "")
            except OSError:
                pass              # not created yet: start at 0
        self._all_ranks = self.n  # every rank ever launched (elastic
                                  # shrinks self.n; streams stay tailed)
        self._pending = {}        # (attempt, step) -> {rank: record}
        self._emitted = set()     # (attempt, step) already written
        self._floor = -1          # steps <= this were pruned from
                                  # _emitted (still emitted; see feed)
        self._attempt = 0         # current watchdog attempt
        self._max_step = 0        # newest step seen in this attempt
        self._steps_written = 0
        self._seen_dumps = set()
        self._lock = threading.Lock()
        self._closed = False
        #: optional fleet-scope SLO evaluator (telemetry.slo.
        #: FleetHealth): launch.py attaches one; every emitted step is
        #: judged and alert transitions land in the timeline as
        #: ``event: alert`` records
        self.health = None
        try:
            # fresh timeline per job: a reused base must not leave the
            # old run's records above this run's run_begin header
            open(self.out_path, "w").close()
        except OSError:
            pass
        self._write({"schema": RUN_SCHEMA, "kind": "run_begin",
                     "ts": round(time.time(), 6), "num_ranks": self.n,
                     "base": os.path.basename(base_path)})

    def begin_attempt(self, attempt):
        """Start watchdog attempt N: flush the previous attempt's
        partial steps (its telemetry step counters restart from the
        resumed checkpoint, so step numbers repeat across attempts)."""
        attempt = int(attempt)
        with self._lock:
            if attempt == self._attempt:
                return
            self._emit_ready(final=True)
            self._attempt = attempt
            self._max_step = 0
            self._floor = -1

    def set_num_ranks(self, n):
        """Elastic resize (tools/launch.py --elastic): subsequent
        attempts expect ``n`` ranks per step, so a shrunk fleet's steps
        complete immediately instead of waiting out the partial-step
        window for ranks that left.  Departed ranks' streams stay
        tailed (``_all_ranks`` never shrinks) so their final buffered
        lines still land in the timeline."""
        with self._lock:
            self.n = max(1, int(n))
            self._all_ranks = max(self._all_ranks, self.n)

    # ------------------------------------------------------------ output
    def _write(self, rec):
        try:
            with open(self.out_path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        except OSError as e:
            logging.getLogger(__name__).warning(
                "distview: cannot append run timeline %r: %s",
                self.out_path, e)

    def note_event(self, record):
        """Pass a supervisor event (worker_start/worker_death/
        watchdog_restart/...) through into the timeline."""
        rec = {"kind": "event", "ts": round(time.time(), 6)}
        rec.update(record)
        with self._lock:
            self._write(rec)

    # ------------------------------------------------------------- input
    def feed(self, r, rec):
        """Ingest one parsed JSONL record from rank ``r``.  Step records
        aggregate; worker EVENT records (``telemetry.jsonl_event`` —
        reshard / rank_join / rank_leave and the data-plane
        data_resume / data_remap / backpressure_adjust breadcrumbs)
        pass through into the timeline with the rank attached;
        anything else is ignored."""
        step = rec.get("step")
        if not isinstance(step, (int, float)):
            if isinstance(rec.get("event"), str):
                ev = dict(rec)
                ev.setdefault("rank", int(r))
                ev["kind"] = "event"
                with self._lock:
                    self._write(ev)
            return
        step = int(step)
        compact = {"t_s": rec.get("step_time_s"),
                   "ts": rec.get("ts")}
        if rec.get("segments"):
            compact["segments"] = rec["segments"]
        if rec.get("skew_s") is not None:
            compact["skew_s"] = rec["skew_s"]
        if rec.get("slowest_rank") is not None:
            compact["slowest_rank"] = rec["slowest_rank"]
        if rec.get("count"):
            compact["count"] = rec["count"]
        # data-plane observability (telemetry.ioview): the per-stage
        # breakdown + iterator position ride the step record so
        # run_top/io_top can name the slow STAGE on the slow RANK when
        # input_wait dominates
        if isinstance(rec.get("io"), dict):
            compact["io"] = rec["io"]
        # training-health numerics (telemetry.numerics): the sampled
        # step's global grad norm + state digest ride the step record
        # so cross-rank numeric skew is visible next to the time skew
        if isinstance(rec.get("grad_norm"), (int, float)):
            compact["grad_norm"] = rec["grad_norm"]
        if isinstance(rec.get("digest"), int):
            compact["digest"] = rec["digest"]
        with self._lock:
            key = (self._attempt, step)
            # _floor covers keys pruned from _emitted: a rank lagging
            # far behind the window must not re-open a step that was
            # already flushed partial
            if key in self._emitted or step <= self._floor:
                return
            self._pending.setdefault(key, {})[int(r)] = compact
            self._max_step = max(self._max_step, step)
            self._emit_ready()

    def _emit_ready(self, final=False):
        """Emit (under self._lock) every pending step that is complete,
        or — when ``final`` or older than the window — partial."""
        for key in sorted(self._pending):
            attempt, step = key
            ranks = self._pending[key]
            complete = len(ranks) >= self.n
            stale = (final or attempt < self._attempt
                     or step <= self._max_step - self.window)
            if not complete and not stale:
                continue
            del self._pending[key]
            self._emitted.add(key)
            # bound the dedup set (a multi-day supervisor would grow it
            # forever): keys at or below _floor move into the scalar
            # floor check in feed(), so a rank lagging past the pruned
            # region still cannot re-open those steps
            if len(self._emitted) > 8 * self.window:
                self._floor = max(self._floor,
                                  self._max_step - 4 * self.window)
                self._emitted = {k for k in self._emitted
                                 if k[0] == self._attempt
                                 and k[1] > self._floor}
            self._steps_written += 1
            times = {r: v.get("t_s") for r, v in ranks.items()
                     if isinstance(v.get("t_s"), (int, float))}
            rec = {"kind": "step", "step": step, "attempt": attempt,
                   "ts": round(max((v.get("ts") or 0)
                                   for v in ranks.values()), 6),
                   "n_ranks": len(ranks),
                   "ranks": {str(r): ranks[r] for r in sorted(ranks)}}
            if times:
                vals = sorted(times.values())
                rec["p50_s"] = round(vals[(len(vals) - 1) // 2], 6)
                rec["min_s"] = round(vals[0], 6)
                rec["max_s"] = round(vals[-1], 6)
                rec["worst_rank"] = max(times, key=times.get)
            skews = [v.get("skew_s") for v in ranks.values()
                     if isinstance(v.get("skew_s"), (int, float))]
            if skews:
                rec["skew_s"] = round(max(skews), 6)
            gnorms = [v.get("grad_norm") for v in ranks.values()
                      if isinstance(v.get("grad_norm"), (int, float))]
            if len(gnorms) >= 2:
                # cross-rank grad-norm spread: nonzero means the ranks
                # are not stepping the same numbers — the divergence
                # signal tools/numdiff.py then localizes per tensor
                rec["grad_skew"] = round(max(gnorms) - min(gnorms), 9)
            digests = {v.get("digest") for v in ranks.values()
                       if isinstance(v.get("digest"), int)}
            if len(digests) > 1:
                rec["digest_mismatch"] = True
            self._write(rec)
            if self.health is not None:
                # self._lock is already held: write alert transitions
                # directly (note_event would deadlock re-taking it)
                try:
                    for ev in self.health.observe_step(rec):
                        self._write(ev)
                except Exception:  # mxlint: allow-broad-except(a fleet-rule bug must not stop the timeline merge it annotates)
                    logging.getLogger(__name__).warning(
                        "distview: fleet SLO evaluation failed on "
                        "step %s", step, exc_info=True)

    # -------------------------------------------------------------- poll
    def poll(self):
        """Incrementally read every rank's stream (and any new flight
        dumps) and emit newly-complete steps.  Returns the number of
        records ingested this call."""
        fed = 0
        for r in range(self._all_ranks):
            path = rank_jsonl_path(self.base, r)
            off, partial = self._offsets.get(r, (0, ""))
            try:
                with open(path) as f:
                    f.seek(off)
                    chunk = f.read()
                    off = f.tell()
            except OSError:
                continue
            records, partial = split_jsonl(partial + chunk)
            self._offsets[r] = (off, partial)
            for rec in records:
                self.feed(r, rec)
                fed += 1
        self._poll_flight_dumps()
        return fed

    def _poll_flight_dumps(self):
        """Surface new black-box dumps (MXNET_TPU_FLIGHT_DIR) as
        timeline events — a rank that dies between supervisor heartbeats
        still leaves its breadcrumb in step order."""
        d = os.environ.get("MXNET_TPU_FLIGHT_DIR")
        if not d:
            return
        try:
            names = sorted(f for f in os.listdir(d)
                           if f.startswith("flight-")
                           and f.endswith(".json"))
        except OSError:
            return
        for name in names:
            if name in self._seen_dumps:
                continue
            self._seen_dumps.add(name)
            self.note_event({"event": "flight_dump",
                             "path": os.path.join(d, name)})

    def close(self):
        """Final flush: emit partially-reported steps and the
        ``run_end`` trailer.  Idempotent."""
        if self._closed:
            return
        self._closed = True
        self.poll()
        with self._lock:
            self._emit_ready(final=True)
            if self.health is not None:
                try:
                    self._write({"kind": "event",
                                 "event": "fleet_health",
                                 "ts": round(time.time(), 6),
                                 **self.health.verdict()})
                except Exception:  # mxlint: allow-broad-except(the closing verdict is best-effort; run_end must still be written)
                    pass
            self._write({"kind": "run_end", "ts": round(time.time(), 6),
                         "steps": self._steps_written})


# --------------------------------------------------- timeline reading

def read_run_timeline(path):
    """Parse + validate an ``mxtpu-run/1`` timeline (JSONL).  Returns
    the record list; raises ValueError naming the problem (unreadable
    file, malformed line, wrong/missing schema header, malformed step
    records) — ``tools/flight_read.py`` and ``tools/run_top.py`` both
    route through this."""
    recs = []
    try:
        with open(path) as f:
            raw = f.read()
    except OSError as e:
        raise ValueError("cannot read run timeline %r: %s" % (path, e))
    lines = raw.split("\n")
    # a LIVE timeline may end mid-append: a final line with no newline
    # is an in-progress record, not corruption — ignore it (--follow's
    # partial-line carry does the same); mid-file garbage still raises
    tail_partial = lines.pop() if lines and not raw.endswith("\n") \
        else None
    for i, line in enumerate(lines, 1):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError as e:
            raise ValueError("run timeline %r line %d: %s"
                             % (path, i, e))
        if not isinstance(rec, dict):
            raise ValueError("run timeline %r line %d: not a "
                             "JSON object" % (path, i))
        recs.append(rec)
    if tail_partial and tail_partial.strip():
        try:
            rec = json.loads(tail_partial)
            if isinstance(rec, dict):
                recs.append(rec)
        except ValueError:
            pass                # still being written
    if not recs:
        raise ValueError("run timeline %r is empty" % path)
    head = recs[0]
    if head.get("schema") != RUN_SCHEMA or head.get("kind") != "run_begin":
        raise ValueError(
            "run timeline %r: first record must be the %r run_begin "
            "header (got %r)" % (path, RUN_SCHEMA,
                                 {k: head.get(k)
                                  for k in ("schema", "kind")}))
    for i, rec in enumerate(recs, 1):
        kind = rec.get("kind")
        if kind not in ("run_begin", "run_end", "step", "event"):
            raise ValueError("run timeline %r record %d: unknown kind %r"
                             % (path, i, kind))
        if kind == "step":
            if not isinstance(rec.get("step"), int) or \
                    not isinstance(rec.get("ranks"), dict):
                raise ValueError(
                    "run timeline %r record %d: step records need an "
                    "int 'step' and a 'ranks' object" % (path, i))
    return recs


def summarize_run(records):
    """Postmortem roll-up of a timeline: step counts, cross-rank
    step-time stats, the straggler (most-frequent worst rank), peak
    skew, per-rank segment totals, the numerics columns (per-rank last
    grad norm/digest, peak cross-rank grad-norm skew, digest-mismatch
    step count), and the event list.  Input is
    :func:`read_run_timeline` output; the result is plain JSON-able —
    ``tools/run_top.py --summarize`` prints it."""
    steps = [r for r in records if r.get("kind") == "step"]
    events = [r for r in records if r.get("kind") == "event"]
    head = records[0]
    # fleet SLO alerts (telemetry.slo.FleetHealth transitions written
    # into the timeline) + the closing fleet_health verdict
    alerts = [e for e in events if e.get("event") == "alert"]
    firing_now = {}
    for a in alerts:
        if a.get("to") == "firing":
            firing_now[a.get("rule")] = a
        elif a.get("to") == "resolved":
            firing_now.pop(a.get("rule"), None)
    fleet_health = None
    for e in events:
        if e.get("event") == "fleet_health":
            fleet_health = {k: e.get(k)
                            for k in ("status", "firing", "rules")}
    if fleet_health is None and alerts:
        fleet_health = {
            "status": "critical" if any(
                a.get("severity") == "critical"
                for a in firing_now.values())
            else ("degraded" if firing_now else "healthy"),
            "firing": sorted(firing_now),
        }
    worst = {}
    seg_totals = {}
    rank_times = {}
    skew_max = 0.0
    skew_last = None
    grad_skew_max = None
    digest_mismatch_steps = 0
    rank_numerics = {}
    io_stages = {}      # rank -> {stage: seconds}
    io_position = {}    # rank -> last reported position
    for s in steps:
        w = s.get("worst_rank")
        if w is not None:
            worst[str(w)] = worst.get(str(w), 0) + 1
        if isinstance(s.get("skew_s"), (int, float)):
            skew_max = max(skew_max, s["skew_s"])
            skew_last = s["skew_s"]
        if isinstance(s.get("grad_skew"), (int, float)):
            grad_skew_max = max(grad_skew_max or 0.0, s["grad_skew"])
        if s.get("digest_mismatch"):
            digest_mismatch_steps += 1
        for r, v in (s.get("ranks") or {}).items():
            if isinstance(v.get("grad_norm"), (int, float)):
                rn = rank_numerics.setdefault(r, {})
                rn["grad_norm_last"] = v["grad_norm"]
                rn["grad_norm_steps"] = rn.get("grad_norm_steps", 0) + 1
            if isinstance(v.get("digest"), int):
                rank_numerics.setdefault(r, {})["digest_last"] = \
                    v["digest"]
            if isinstance(v.get("t_s"), (int, float)):
                # a run_steps chain reports the per-step AVERAGE with a
                # count; carry the count so totals match the segment
                # totals (which are whole-chain wall time)
                n = v.get("count") if isinstance(v.get("count"), int) \
                    else 1
                rank_times.setdefault(r, []).append((v["t_s"], max(1, n)))
            for name, val in (v.get("segments") or {}).items():
                if isinstance(val, (int, float)):
                    st = seg_totals.setdefault(r, {})
                    st[name] = st.get(name, 0.0) + val
            io = v.get("io")
            if isinstance(io, dict):
                tot = io_stages.setdefault(r, {})
                for stage, sv in (io.get("stages") or {}).items():
                    if isinstance(sv, dict) and \
                            isinstance(sv.get("s"), (int, float)):
                        tot[stage] = tot.get(stage, 0.0) + sv["s"]
                if isinstance(io.get("position"), dict):
                    io_position[r] = io["position"]
    per_rank = {}
    for r, ts in sorted(rank_times.items()):
        ts = sorted(ts)
        per_rank[r] = {
            "steps": sum(n for _t, n in ts),
            "p50_s": round(ts[(len(ts) - 1) // 2][0], 6),
            "max_s": round(ts[-1][0], 6),
            "total_s": round(sum(t * n for t, n in ts), 6),
        }
        if r in seg_totals:
            per_rank[r]["segments_s"] = {
                k: round(v, 6) for k, v in sorted(seg_totals[r].items())}
    for r, rn in rank_numerics.items():
        per_rank.setdefault(r, {}).update(rn)
    for r, tot in io_stages.items():
        per_rank.setdefault(r, {})["io_stages_s"] = {
            k: round(v, 6) for k, v in sorted(tot.items())}
    for r, pos in io_position.items():
        per_rank.setdefault(r, {})["data_position"] = pos
    straggler = max(worst, key=worst.get) if worst else None
    # the cross-rank io-bottleneck verdict: when the straggler's steps
    # are dominated by input_wait (the data plane, not compute, makes
    # it slow) and it reported an io stage breakdown, NAME the slowest
    # stage on that rank — the answer run_top surfaces when PR 5's
    # segments say "input"
    io_bottleneck = None
    if straggler is not None:
        seg = seg_totals.get(straggler, {})
        input_s = seg.get("input_wait", 0.0)
        stages = io_stages.get(straggler)
        if stages and input_s > 0 and input_s >= seg.get("compute", 0.0):
            slow_stage = max(stages, key=stages.get)
            io_bottleneck = {"rank": int(straggler),
                             "stage": slow_stage,
                             "stage_s": round(stages[slow_stage], 6),
                             "input_wait_s": round(input_s, 6)}
    return {
        "schema": head.get("schema"),
        "num_ranks": head.get("num_ranks"),
        "steps": len(steps),
        "complete_steps": sum(1 for s in steps
                              if s.get("n_ranks") == head.get("num_ranks")),
        "straggler": None if straggler is None else int(straggler),
        "worst_rank_counts": {k: worst[k] for k in sorted(worst)},
        "skew_max_s": round(skew_max, 6),
        "skew_last_s": skew_last,
        "grad_skew_max": grad_skew_max,
        "digest_mismatch_steps": digest_mismatch_steps,
        "io_bottleneck": io_bottleneck,
        "health": fleet_health,
        "alerts": [{k: a.get(k) for k in ("ts", "step", "rule", "to",
                                          "severity", "value", "bound")
                    if a.get(k) is not None} for a in alerts],
        "per_rank": per_rank,
        "events": [{k: e.get(k) for k in ("ts", "event", "rank", "pid",
                                          "attempt", "exit_code", "path",
                                          "telemetry_port", "rule",
                                          "to", "severity", "status")
                    if e.get(k) is not None} for e in events],
        "ended": any(r.get("kind") == "run_end" for r in records),
    }
