"""Span tracer: wall-time scopes feeding metrics AND the Chrome trace.

``telemetry.span("fwd")`` is a context manager and a decorator.  Every
span records its wall time into the ``mxtpu_span_seconds`` histogram
(labeled by span name — the per-phase breakdown ``report()`` prints)
and into the per-step accumulator the JSONL step-log drains; when the
profiler is running (``profiler_set_state('run')``) the same interval
is appended to the Chrome trace via :func:`mxnet_tpu.profiler.
record_event`, so telemetry spans and the reference-parity operator
events land in ONE trace file.

Spans nest freely (executor.forward inside module.forward inside a fit
step); each level is recorded independently, and the trace event
carries the thread id so concurrent prefetcher/consumer spans render on
separate trace rows.
"""
from __future__ import annotations

import functools
import threading
import time

from .. import profiler
from . import tracing
from .registry import histogram

__all__ = ["span", "drain_step_spans", "step_span_totals"]

_SPAN_HIST = None          # created lazily (after catalog import settles)
_step_lock = threading.Lock()
_step_spans = {}           # name -> [total_seconds, count] since last step


def _hist():
    global _SPAN_HIST
    if _SPAN_HIST is None:
        _SPAN_HIST = histogram("mxtpu_span_seconds")
    return _SPAN_HIST


class span:
    """Time a scope::

        with telemetry.span("fwd"):
            ...

    or decorate a function::

        @telemetry.span("data.fetch")
        def next_batch(): ...

    One instance may be shared (the decorator form re-enters it from
    many threads): enter state lives on a per-instance thread-local
    stack, not on the instance itself.
    """

    def __init__(self, name, category="span"):
        self.name = name
        self.category = category
        self._tls = threading.local()

    def __enter__(self):
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        # active trace? this span becomes a child span of it; the cost
        # without a trace is ONE thread-local read (tracing.current)
        ctx = tracing.current()
        tr = None
        if ctx is not None:
            tracing.attach(ctx.child())
            tr = (ctx, time.time())
        stack.append((time.perf_counter(), profiler.now_us(), tr))
        return self

    def __exit__(self, *exc):
        t0, start_us, tr = self._tls.stack.pop()
        dur = time.perf_counter() - t0
        if tr is not None:
            parent, ts0 = tr
            child = tracing.current()
            tracing.detach(parent)
            if child is not None:
                tracing.record_span(
                    parent, self.name, ts0, dur,
                    span_id=child.span_id,
                    status="error" if exc and exc[0] is not None
                    else None)
        _hist().labels(span=self.name).observe(dur)
        with _step_lock:
            acc = _step_spans.get(self.name)
            if acc is None:
                _step_spans[self.name] = [dur, 1]
            else:
                acc[0] += dur
                acc[1] += 1
        if profiler.is_running():
            profiler.record_event(
                self.name, start_us, dur * 1e6, category=self.category,
                tid=threading.get_ident() % (1 << 31))
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with self:
                return fn(*args, **kwargs)
        return wrapper


def drain_step_spans():
    """Spans accumulated since the last drain, as
    ``{name: {"total_s": s, "count": n}}`` — consumed by the JSONL
    step-log so each record carries that step's phase timings."""
    with _step_lock:
        out = {name: {"total_s": v[0], "count": v[1]}
               for name, v in _step_spans.items()}
        _step_spans.clear()
    return out


def step_span_totals():
    """Non-draining view of the current per-step accumulator."""
    with _step_lock:
        return {name: {"total_s": v[0], "count": v[1]}
                for name, v in _step_spans.items()}
