"""healthd: declarative SLOs, burn-rate alerting, and the health verdict.

Every prior telemetry layer is a *sensor* — the metric catalog, the
cross-rank skew barrier, the numerics anomaly rules, the ioview
bottleneck classifier, the serving latency/shed histograms.  Nothing
*judges* them: an operator must eyeball ``serve_top``/``run_top`` to
notice a p99 breach or a straggler.  This module is the judge — a
declarative rule engine in the Prometheus-alerting lineage, with the
multi-window multi-burn-rate method from the Google SRE Workbook for
error-budget SLOs:

* **rule types** — ``threshold`` (a gauge/counter-rate/ratio/rolling-
  regression reading vs a bound), ``burn_rate`` (error-budget
  consumption measured over a FAST and a SLOW window; the alert fires
  only when both burn above the factor, which is what keeps it both
  quick on a real storm and quiet on a blip), ``absence`` (liveness: a
  heartbeat counter that stopped advancing — armed only after the
  counter first moves, so an idle process never false-fires), and
  ``anomaly_passthrough`` (the numerics / io-bottleneck verdict
  counters become first-class alerts);
* **rule catalog** — :data:`RULES` is a checked catalog (same
  drift-guard pattern as ``telemetry.CATALOG``: :func:`selfcheck_rules`
  validates every entry and ``tools/ci_check.py`` cross-checks the rule
  names against ``docs/api/telemetry.md`` in both directions);
  ``MXNET_TPU_SLO_RULES`` overrides parameters, disables rules, or
  loads a JSON rule file;
* **alert state machine** — inactive → pending → firing → resolved,
  with ``for_s`` debounce on the way up and ``resolve_for_s``
  anti-flap on the way down.  Transitions emit ``alert`` flight
  events, ``mxtpu_alert_*`` catalog metrics, and feed the per-rank
  :func:`SloEngine.health` verdict (healthy/degraded/critical);
* **evaluation** — a low-overhead in-process ticker: the serving
  ``Server`` arms a background ticker thread; training loops ride
  ``telemetry.step_end`` (one clock read per step, a full evaluation
  at most every ``MXNET_TPU_SLO_TICK_S``); the supervisor evaluates
  ``scope="fleet"`` rules over the merged run timeline via
  :class:`FleetHealth` (``launch.py``'s ``RunAggregator``).

Import discipline (same contract as ``telemetry.distview``):
module-level imports are stdlib-only and in-package imports are
deferred into the engine methods, so ``tools/launch.py`` and
``tools/health_top.py`` can load this file by path (``importlib``)
without dragging jax — or the framework — into a supervisor process.

The health verdict / ``tools/health_top.py --json`` document schema is
``mxtpu-health/1``::

    {
      "schema": "mxtpu-health/1",
      "ts": <unix seconds>, "rank": <MXNET_TPU_PROCESS_ID>,
      "status": "healthy" | "degraded" | "critical",
      "firing":   [{"rule", "severity", "since_s", "value", "summary"}],
      "pending":  [... same shape ...],
      "resolved": [{"rule", "severity", "ago_s"}],   # recently cleared
      "rules": <number of enabled rank-scope rules>
    }
"""
from __future__ import annotations

import copy
import json
import logging
import os
import threading
import time

__all__ = [
    "HEALTH_SCHEMA", "RULES", "TYPES", "SEVERITIES",
    "selfcheck_rules", "load_rules",
    "SloEngine", "FleetHealth",
    "engine", "on_step", "start_ticker", "stop_ticker", "reset",
    "enabled", "tick_seconds", "fast_seconds", "slow_seconds",
    "latency_threshold_s", "health",
]

log = logging.getLogger(__name__)

#: the health verdict / health_top --json document schema tag
HEALTH_SCHEMA = "mxtpu-health/1"

TYPES = ("threshold", "burn_rate", "absence", "anomaly_passthrough")
SEVERITIES = ("warn", "critical")

#: alert states, in escalation order (gauge value = index)
STATES = ("inactive", "pending", "firing")


# ------------------------------------------------------------- env knobs

def enabled():
    """SLO evaluation switch (``MXNET_TPU_SLO``, default on)."""
    return os.environ.get("MXNET_TPU_SLO", "1").strip().lower() \
        not in ("0", "false", "off", "no")


def tick_seconds():
    """Minimum interval between evaluations (``MXNET_TPU_SLO_TICK_S``,
    default 1.0; floor 0.05 — the ticker must never busy-spin)."""
    try:
        return max(0.05, float(os.environ.get("MXNET_TPU_SLO_TICK_S",
                                              "1.0")))
    except ValueError:
        return 1.0


def fast_seconds():
    """Default FAST burn-rate window (``MXNET_TPU_SLO_FAST_S``,
    default 60)."""
    try:
        return max(1.0, float(os.environ.get("MXNET_TPU_SLO_FAST_S",
                                             "60")))
    except ValueError:
        return 60.0


def slow_seconds():
    """Default SLOW burn-rate window (``MXNET_TPU_SLO_SLOW_S``,
    default 300)."""
    try:
        return max(1.0, float(os.environ.get("MXNET_TPU_SLO_SLOW_S",
                                             "300")))
    except ValueError:
        return 300.0


def latency_threshold_s():
    """Serving latency SLO target (``MXNET_TPU_SLO_LATENCY_MS``,
    default 250 ms), in seconds."""
    try:
        ms = float(os.environ.get("MXNET_TPU_SLO_LATENCY_MS", "250"))
    except ValueError:
        ms = 250.0
    return max(0.0001, ms / 1e3)


# ----------------------------------------------------------- rule catalog
# Every rule the framework evaluates, declared like telemetry.CATALOG
# declares metrics: selfcheck_rules() validates the table, the stage-4
# drift guard cross-checks these names against docs/api/telemetry.md,
# and MXNET_TPU_SLO_RULES overrides parameters per deployment.
#
# Common fields: name, type, severity (warn|critical), scope
# (rank|fleet), summary, for_s (debounce before pending -> firing),
# resolve_for_s (condition-false hold before firing -> resolved).
# Window fields left at None inherit MXNET_TPU_SLO_FAST_S/_SLOW_S at
# load time.

RULES = [
    # ------------------------------------------------- serving tier SLOs
    dict(name="serve_p99_latency_burn", type="burn_rate",
         severity="critical", scope="rank",
         summary="serving latency error budget burning (requests over "
                 "the MXNET_TPU_SLO_LATENCY_MS target)",
         kind="latency", metric="mxtpu_serve_request_seconds",
         labels={"segment": "total"}, threshold_s=None,
         objective=0.99, factor=2.0, fast_s=None, slow_s=None,
         for_s=0.0, resolve_for_s=60.0),
    dict(name="serve_shed_burn", type="burn_rate", severity="critical",
         scope="rank",
         summary="serving shed-rate error budget burning (requests "
                 "refused by the load shedder)",
         kind="ratio",
         bad_metric="mxtpu_serve_requests_total",
         bad_labels={"outcome": "shed"},
         total_metric="mxtpu_serve_requests_total", total_labels=None,
         objective=0.99, factor=2.0, fast_s=None, slow_s=None,
         for_s=0.0, resolve_for_s=60.0),
    dict(name="serve_error_rate", type="threshold", severity="warn",
         scope="rank", mode="share",
         summary="serving dispatch error share over the fast window",
         metric="mxtpu_serve_requests_total",
         labels={"outcome": "error"},
         denom_metric="mxtpu_serve_requests_total", denom_labels=None,
         op=">", bound=0.05, window_s=None,
         for_s=0.0, resolve_for_s=30.0),
    dict(name="serve_queue_depth", type="threshold", severity="warn",
         scope="rank", mode="value",
         summary="batcher queue near capacity (Server arms the bound "
                 "at 0.9x its queue depth)",
         metric="mxtpu_serve_queue_depth", labels=None,
         op=">", bound=1e18, window_s=None,
         for_s=5.0, resolve_for_s=10.0),
    dict(name="serve_heartbeat", type="absence", severity="warn",
         scope="rank",
         summary="no rung dispatched for hold_s despite earlier "
                 "traffic (scheduler stalled or traffic stopped)",
         metric="mxtpu_serve_rung_dispatch_total", labels=None,
         hold_s=300.0, for_s=0.0, resolve_for_s=0.0),
    # ---------------------------------------------- training-run SLOs
    dict(name="train_step_time_regression", type="threshold",
         severity="warn", scope="rank", mode="regression",
         summary="fast-window mean step time regressed vs the rolling "
                 "slow-window baseline",
         metric="mxtpu_step_seconds", labels=None,
         op=">", bound=1.5, fast_s=None, slow_s=None, min_count=3,
         for_s=0.0, resolve_for_s=30.0),
    dict(name="train_collective_wait_share", type="threshold",
         severity="warn", scope="rank", mode="share",
         summary="collective wait dominates step time (a straggler "
                 "peer is stalling this rank)",
         metric="mxtpu_step_segment_seconds",
         labels={"segment": "collective_wait"},
         denom_metric="mxtpu_step_seconds", denom_labels=None,
         op=">", bound=0.5, window_s=None,
         for_s=0.0, resolve_for_s=30.0),
    dict(name="train_input_starved_share", type="threshold",
         severity="warn", scope="rank", mode="share",
         summary="input wait dominates step time (the data plane, not "
                 "compute, bounds throughput)",
         metric="mxtpu_step_segment_seconds",
         labels={"segment": "input_wait"},
         denom_metric="mxtpu_step_seconds", denom_labels=None,
         op=">", bound=0.5, window_s=None,
         for_s=0.0, resolve_for_s=30.0),
    dict(name="train_heartbeat", type="absence", severity="critical",
         scope="rank",
         summary="no training step completed for hold_s despite "
                 "earlier progress (hung collective or dead loop)",
         metric="mxtpu_step_total", labels=None,
         hold_s=120.0, for_s=0.0, resolve_for_s=0.0),
    # -------------------------------------- verdict passthrough alerts
    dict(name="numerics_anomaly", type="anomaly_passthrough",
         severity="critical", scope="rank",
         summary="training-health numerics rule fired (nonfinite / "
                 "grad_spike / dead_grad — see the numerics_anomaly "
                 "flight events)",
         metric="mxtpu_numerics_anomalies_total", labels=None,
         exclude=None, window_s=None, for_s=0.0, resolve_for_s=120.0),
    dict(name="io_bottleneck", type="anomaly_passthrough",
         severity="warn", scope="rank",
         summary="ioview classified the input pipeline producer-bound "
                 "(a pipeline stage, not the device, bounds "
                 "throughput)",
         metric="mxtpu_io_bottleneck_total", labels=None,
         exclude={"stage": ("balanced", "consumer")},
         window_s=None, for_s=0.0, resolve_for_s=60.0),
    # ------------------------------------------------- fleet-scope SLOs
    # Evaluated by FleetHealth over mxtpu-run/1 step records in the
    # launch.py supervisor, NOT by the per-rank engine.  `field` names
    # a step-record key ("ranks.<k>" fans out per rank); `quorum` is
    # "any" | "all" | a fraction of reporting values that must breach.
    dict(name="fleet_skew", type="threshold", severity="warn",
         scope="fleet", field="skew_s", op=">", bound=1.0,
         quorum="any",
         summary="cross-rank step skew above bound (a straggler is "
                 "stalling every collective)",
         for_s=0.0, resolve_for_s=30.0),
    dict(name="fleet_digest_mismatch", type="threshold",
         severity="critical", scope="fleet", field="digest_mismatch",
         op=">", bound=0.5, quorum="any",
         summary="ranks disagree on the sampled state digest (numeric "
                 "divergence — bisect with tools/numdiff.py)",
         for_s=0.0, resolve_for_s=60.0),
    dict(name="fleet_rank_missing", type="threshold",
         severity="critical", scope="fleet", field="n_ranks",
         op="<", bound=-1, quorum="any",
         summary="a step completed without every rank reporting "
                 "(launch.py arms the bound at the fleet size)",
         for_s=0.0, resolve_for_s=30.0),
]


def selfcheck_rules(rules=None):
    """Validate a rule table (default :data:`RULES`); returns a list of
    problem strings (empty = clean).  Checked: unique slug names, known
    types/severities/scopes, per-type required params, referenced
    metrics declared in ``telemetry.CATALOG``, sane numeric ranges
    (burn objective in (0, 1), positive windows)."""
    import re
    problems = []
    rules = RULES if rules is None else rules
    name_re = re.compile(r"^[a-z][a-z0-9_]*$")
    try:
        from .catalog import CATALOG
    except ImportError:          # loaded by path in a supervisor
        CATALOG = None
    seen = set()
    for r in rules:
        name = r.get("name")
        if not isinstance(name, str) or not name_re.match(name or ""):
            problems.append("rule %r: illegal name" % (name,))
            continue
        if name in seen:
            problems.append("rule %r: duplicate name" % name)
        seen.add(name)
        rtype = r.get("type")
        if rtype not in TYPES:
            problems.append("rule %r: unknown type %r" % (name, rtype))
            continue
        if r.get("severity") not in SEVERITIES:
            problems.append("rule %r: severity must be one of %s"
                            % (name, list(SEVERITIES)))
        if r.get("scope", "rank") not in ("rank", "fleet"):
            problems.append("rule %r: scope must be rank|fleet" % name)
        if not r.get("summary"):
            problems.append("rule %r: empty summary" % name)
        for k in ("for_s", "resolve_for_s"):
            if not isinstance(r.get(k), (int, float)) or r[k] < 0:
                problems.append("rule %r: %s must be a number >= 0"
                                % (name, k))
        if r.get("scope") == "fleet":
            if rtype != "threshold":
                problems.append("rule %r: fleet rules are thresholds "
                                "over step-record fields" % name)
            if not isinstance(r.get("field"), str):
                problems.append("rule %r: fleet rule needs a 'field'"
                                % name)
            q = r.get("quorum")
            if q not in ("any", "all") and \
                    not (isinstance(q, (int, float)) and 0 < q <= 1):
                problems.append("rule %r: quorum must be any|all|"
                                "fraction" % name)
            if r.get("op") not in (">", "<"):
                problems.append("rule %r: op must be > or <" % name)
            continue
        # rank-scope rules reference catalog metrics
        metrics = []
        if rtype == "burn_rate":
            if r.get("kind") == "latency":
                metrics = [r.get("metric")]
            elif r.get("kind") == "ratio":
                metrics = [r.get("bad_metric"), r.get("total_metric")]
            else:
                problems.append("rule %r: burn_rate kind must be "
                                "latency|ratio" % name)
            obj = r.get("objective")
            if not (isinstance(obj, (int, float)) and 0 < obj < 1):
                problems.append("rule %r: objective must be in (0, 1)"
                                % name)
            if not (isinstance(r.get("factor"), (int, float))
                    and r["factor"] > 0):
                problems.append("rule %r: factor must be > 0" % name)
        elif rtype == "threshold":
            metrics = [r.get("metric")]
            if r.get("mode") not in ("value", "rate", "share",
                                     "regression"):
                problems.append("rule %r: threshold mode must be "
                                "value|rate|share|regression" % name)
            if r.get("mode") == "share":
                metrics.append(r.get("denom_metric"))
            if r.get("op") not in (">", "<"):
                problems.append("rule %r: op must be > or <" % name)
            if not isinstance(r.get("bound"), (int, float)):
                problems.append("rule %r: bound must be a number"
                                % name)
        elif rtype == "absence":
            metrics = [r.get("metric")]
            if not (isinstance(r.get("hold_s"), (int, float))
                    and r["hold_s"] > 0):
                problems.append("rule %r: hold_s must be > 0" % name)
        elif rtype == "anomaly_passthrough":
            metrics = [r.get("metric")]
        for m in metrics:
            if not isinstance(m, str):
                problems.append("rule %r: missing metric reference"
                                % name)
            elif CATALOG is not None and m not in CATALOG:
                problems.append("rule %r: metric %r is not declared in "
                                "telemetry.CATALOG" % (name, m))
    return problems


# --------------------------------------------------------- rule loading

def _parse_value(tok):
    try:
        return json.loads(tok)
    except ValueError:
        return tok


def load_rules(spec=None):
    """The effective rule table: :data:`RULES` (deep-copied, window
    defaults filled from the env) with ``MXNET_TPU_SLO_RULES``
    overrides applied.  Three override grammars:

    * ``@/path/rules.json`` — a JSON list of rule objects, merged by
      ``name`` into the defaults (a full new rule is appended;
      ``{"name": x, "disable": true}`` removes one);
    * inline JSON (``[...]``) — same semantics;
    * compact ``rule.param=value;rule2.disable=1`` — parameter
      overrides only (values parse as JSON scalars, bare words as
      strings).

    A malformed spec logs ONE warning and falls back to the defaults —
    the judge must never take down the process it judges.  The merged
    table is selfchecked; offending overrides are dropped."""
    rules = copy.deepcopy(RULES)
    by_name = {r["name"]: r for r in rules}
    if spec is None:
        spec = os.environ.get("MXNET_TPU_SLO_RULES", "")
    spec = (spec or "").strip()
    try:
        if spec.startswith("@"):
            with open(spec[1:]) as f:
                overrides = json.load(f)
            rules = _merge_rules(rules, by_name, overrides)
        elif spec.startswith("[") or spec.startswith("{"):
            overrides = json.loads(spec)
            if isinstance(overrides, dict):
                overrides = overrides.get("rules", [])
            rules = _merge_rules(rules, by_name, overrides)
        elif spec:
            for entry in spec.split(";"):
                entry = entry.strip()
                if not entry:
                    continue
                lhs, _, rhs = entry.partition("=")
                rname, _, param = lhs.strip().partition(".")
                if not param or rname not in by_name:
                    raise ValueError("bad override %r (want "
                                     "rule.param=value)" % entry)
                if param == "disable":
                    if _parse_value(rhs.strip()) in (1, True, "1",
                                                     "true"):
                        by_name[rname]["disable"] = True
                else:
                    by_name[rname][param] = _parse_value(rhs.strip())
            rules = [r for r in rules if not r.get("disable")]
    except (OSError, ValueError) as e:
        log.warning("MXNET_TPU_SLO_RULES %r unusable (%s); using the "
                    "default rule catalog", spec, e)
        rules = copy.deepcopy(RULES)
    rules = _fill_defaults(rules)
    problems = selfcheck_rules(rules)
    if problems:
        bad = {p.split("'")[1] for p in problems if "'" in p}
        log.warning("slo: dropping %d invalid rule(s) after overrides: "
                    "%s", len(bad), "; ".join(sorted(problems)[:4]))
        rules = [r for r in rules if r.get("name") not in bad]
    return rules


def _merge_rules(rules, by_name, overrides):
    if not isinstance(overrides, list):
        raise ValueError("rule override document must be a JSON list")
    for ov in overrides:
        if not isinstance(ov, dict) or not ov.get("name"):
            raise ValueError("each rule override needs a 'name'")
        cur = by_name.get(ov["name"])
        if cur is None:
            rules.append(dict(ov))
            by_name[ov["name"]] = rules[-1]
        else:
            cur.update(ov)
    return [r for r in rules if not r.get("disable")]


def _fill_defaults(rules):
    fast, slow = fast_seconds(), slow_seconds()
    for r in rules:
        if r.get("fast_s") is None and "fast_s" in r:
            r["fast_s"] = fast
        if r.get("slow_s") is None and "slow_s" in r:
            r["slow_s"] = slow
        if r.get("window_s") is None and "window_s" in r:
            r["window_s"] = fast
        if r.get("threshold_s") is None and "threshold_s" in r:
            r["threshold_s"] = latency_threshold_s()
    return rules


# ------------------------------------------------------ alert machinery

class Alert:
    """One rule's alert state.  The transition logic lives in
    :meth:`advance` and is shared by the per-rank engine and the fleet
    evaluator; only the *emission* of transitions differs."""

    __slots__ = ("name", "severity", "state", "since", "last_true",
                 "fired_ts", "resolved_ts", "value", "info")

    def __init__(self, name, severity):
        self.name = name
        self.severity = severity
        self.state = "inactive"
        self.since = None         # entered current state at
        self.last_true = None     # condition last observed true at
        self.fired_ts = None
        self.resolved_ts = None
        self.value = None         # last headline reading (display)
        self.info = {}            # last evaluation detail

    def advance(self, cond, now, for_s, resolve_for_s):
        """Feed one evaluation; returns the transition names emitted
        this tick (subset of ``pending``/``firing``/``cleared``/
        ``resolved``).  ``cond=None`` (unknown — e.g. no traffic yet)
        freezes the state."""
        out = []
        if cond is None:
            return out
        if cond:
            self.last_true = now
            if self.state == "inactive":
                self.state = "pending"
                self.since = now
                out.append("pending")
            if self.state == "pending" and now - self.since >= for_s:
                self.state = "firing"
                self.since = now
                self.fired_ts = now
                out.append("firing")
        else:
            if self.state == "pending":
                self.state = "inactive"
                self.since = now
                out.append("cleared")
            elif self.state == "firing":
                ref = self.last_true if self.last_true is not None \
                    else self.since
                if now - ref >= resolve_for_s:
                    self.state = "inactive"
                    self.since = now
                    self.resolved_ts = now
                    out.append("resolved")
        return out

    def describe(self, now):
        d = {"rule": self.name, "severity": self.severity,
             "state": self.state}
        if self.state != "inactive" and self.since is not None:
            d["since_s"] = round(max(0.0, now - self.since), 3)
        if self.value is not None:
            d["value"] = self.value
        if self.info:
            d.update(self.info)
        return d


def _cmp(op, value, bound):
    return value > bound if op == ">" else value < bound


def _status_of(alerts):
    """healthy | degraded | critical from a list of Alert objects."""
    status = "healthy"
    for al in alerts:
        if al.state != "firing":
            continue
        if al.severity == "critical":
            return "critical"
        status = "degraded"
    return status


def _rank():
    try:
        return int(os.environ.get("MXNET_TPU_PROCESS_ID", "0") or 0)
    except ValueError:
        return 0


# ----------------------------------------------------- per-rank engine

class SloEngine:
    """Evaluate the rank-scope rules against the live metrics registry.

    The engine snapshots ONLY the metrics the rules reference (a few
    dict reads per tick), keeps a bounded time-indexed history per rule
    for window math, and advances each rule's :class:`Alert`.  All
    methods are thread-safe; ``tick`` is rate-limited by its callers,
    not here, and accepts an explicit ``now`` for deterministic tests.
    """

    def __init__(self, rules=None):
        self.rules = [r for r in (load_rules() if rules is None
                                  else rules)
                      if r.get("scope", "rank") == "rank"]
        self._lock = threading.Lock()
        self._alerts = {r["name"]: Alert(r["name"], r["severity"])
                        for r in self.rules}
        self._hist = {r["name"]: [] for r in self.rules}
        self._absence = {}      # rule -> {last, last_advance, armed}
        self._ticks = 0

    # ------------------------------------------------------- configure
    def configure(self, name, **params):
        """Adjust one rule's parameters at arm time (e.g. the serving
        front door sets ``serve_queue_depth.bound`` from its batcher's
        real depth).  Unknown rule names are ignored (the rule may have
        been disabled by MXNET_TPU_SLO_RULES)."""
        with self._lock:
            for r in self.rules:
                if r["name"] == name:
                    r.update(params)
                    return True
        return False

    # ------------------------------------------------------- readings
    def _metric(self, name):
        from .registry import REGISTRY
        return REGISTRY.get(name)

    @staticmethod
    def _match(key, labels, exclude=None):
        kd = dict(key)
        if labels:
            for k, v in labels.items():
                if kd.get(k) != str(v):
                    return False
        if exclude:
            for k, vals in exclude.items():
                if kd.get(k) in tuple(str(v) for v in vals):
                    return False
        return True

    def _scalar(self, name, labels=None, exclude=None):
        """Sum of matching counter/gauge samples (0.0 when absent)."""
        m = self._metric(name)
        if m is None:
            return 0.0
        total = 0.0
        for key, val in m.samples().items():
            if isinstance(val, dict):   # histogram: use its sum
                val = val.get("sum", 0.0)
            if self._match(key, labels, exclude):
                total += val
        return total

    def _hist_pair(self, name, labels=None):
        """(sum, count) over matching histogram samples."""
        m = self._metric(name)
        s = c = 0.0
        if m is None:
            return s, c
        for key, val in m.samples().items():
            if isinstance(val, dict) and self._match(key, labels):
                s += val.get("sum", 0.0)
                c += val.get("count", 0)
        return s, c

    def _latency_pair(self, name, labels, threshold_s):
        """(good, total) observation counts: good = observations whose
        histogram bucket upper bound is <= the latency target."""
        m = self._metric(name)
        good = total = 0.0
        if m is None:
            return good, total
        bounds = getattr(m, "buckets", ())
        for key, val in m.samples().items():
            if not isinstance(val, dict) or \
                    not self._match(key, labels):
                continue
            total += val.get("count", 0)
            for ub, n in zip(bounds, val.get("buckets", ())):
                if ub <= threshold_s:
                    good += n
        return good, total

    def _reading(self, r):
        """The rule's raw reading tuple for this tick (window math
        happens against the history of these)."""
        t = r["type"]
        if t == "burn_rate":
            if r["kind"] == "latency":
                good, total = self._latency_pair(
                    r["metric"], r.get("labels"), r["threshold_s"])
                return (total - good, total)
            return (self._scalar(r["bad_metric"], r.get("bad_labels")),
                    self._scalar(r["total_metric"],
                                 r.get("total_labels")))
        if t == "threshold":
            mode = r["mode"]
            if mode == "value":
                return (self._scalar(r["metric"], r.get("labels")),)
            if mode == "rate":
                return (self._scalar(r["metric"], r.get("labels")),)
            if mode == "share":
                return (self._scalar(r["metric"], r.get("labels")),
                        self._scalar(r["denom_metric"],
                                     r.get("denom_labels")))
            if mode == "regression":
                return self._hist_pair(r["metric"], r.get("labels"))
        if t in ("absence", "anomaly_passthrough"):
            return (self._scalar(r["metric"], r.get("labels"),
                                 r.get("exclude")),)
        return (0.0,)

    # ---------------------------------------------------- window math
    @staticmethod
    def _at(hist, cutoff):
        """(ts, reading) of the newest sample at or before ``cutoff``
        (the oldest sample when the engine is younger than the
        window — windows are bounded by engine age, documented in
        docs/api/telemetry.md)."""
        best = None
        for ts, reading in hist:
            if ts <= cutoff:
                best = (ts, reading)
            else:
                break
        return best if best is not None else hist[0]

    def _delta(self, name, now, window, index=None):
        hist = self._hist[name]
        t0, r0 = self._at(hist, now - window)
        t1, r1 = hist[-1]
        if index is None:
            d = tuple(b - a for a, b in zip(r0, r1))
        else:
            d = r1[index] - r0[index]
        return d, max(1e-9, t1 - t0)

    # ---------------------------------------------------- evaluation
    def _evaluate(self, r, now):
        """(condition, headline value, info dict) for one rule; the
        condition is None when the rule cannot be judged yet (no
        traffic / not armed / not enough samples)."""
        name, t = r["name"], r["type"]
        if t == "burn_rate":
            budget = 1.0 - r["objective"]
            burns = {}
            for wname, w in (("fast", r["fast_s"]),
                             ("slow", r["slow_s"])):
                (d_bad, d_total), _el = self._delta(name, now, w)
                if d_total <= 0:
                    burns[wname] = 0.0
                else:
                    burns[wname] = (d_bad / d_total) / budget
            cond = (burns["fast"] > r["factor"]
                    and burns["slow"] > r["factor"])
            info = {"burn_fast": round(burns["fast"], 3),
                    "burn_slow": round(burns["slow"], 3),
                    "factor": r["factor"]}
            if r.get("kind") == "latency":
                # name an ACTUAL slow trace next to the burning
                # quantile: the histogram's slowest-bucket exemplar
                # (rides describe() -> /alerts -> health_top, and the
                # alert flight event)
                from . import tracing
                ex = tracing.exemplar_for(r["metric"], r.get("labels"))
                if ex:
                    info["exemplar_trace"] = ex
            return cond, round(max(burns.values()), 3), info
        if t == "threshold":
            mode = r["mode"]
            if mode == "value":
                v = self._hist[name][-1][1][0]
            elif mode == "rate":
                (dv,), el = self._delta(name, now, r["window_s"])
                v = dv / el
            elif mode == "share":
                (d_num, d_den), _el = self._delta(name, now,
                                                  r["window_s"])
                if d_den <= 0:
                    return None, None, {}
                v = d_num / d_den
            else:  # regression: fast mean vs slow-window baseline mean
                (fs, fc), _ = self._delta(name, now, r["fast_s"])
                (ss, sc), _ = self._delta(name, now, r["slow_s"])
                mc = r.get("min_count", 3)
                bs, bc = ss - fs, sc - fc   # baseline = slow \ fast
                if fc < mc or bc < mc or bs <= 0:
                    return None, None, {}
                v = (fs / fc) / (bs / bc)
            cond = _cmp(r["op"], v, r["bound"])
            return cond, round(v, 6), {"bound": r["bound"]}
        if t == "absence":
            st = self._absence.get(name)
            cum = self._hist[name][-1][1][0]
            if st is None:
                st = {"last": cum, "last_advance": now,
                      "armed": cum > 0}
                self._absence[name] = st
            if cum > st["last"]:
                st["last"] = cum
                st["last_advance"] = now
                st["armed"] = True
            if not st["armed"]:
                return None, None, {}
            idle = now - st["last_advance"]
            return idle >= r["hold_s"], round(idle, 3), \
                {"hold_s": r["hold_s"]}
        if t == "anomaly_passthrough":
            (dv,), _el = self._delta(name, now, r["window_s"])
            return dv > 0, dv, {}
        return None, None, {}

    # ---------------------------------------------------------- tick
    def tick(self, now=None):
        """Run one evaluation pass: snapshot the referenced metrics,
        evaluate every rule, advance the alert machines, emit
        transition metrics + flight events, refresh the state gauges.
        Returns the number of firing rules.  Never raises — the judge
        must not kill the process it judges."""
        try:
            return self._tick(time.time() if now is None else
                              float(now))
        except Exception as e:  # mxlint: allow-broad-except(the SLO evaluator piggybacks on step_end and the serving scheduler; an engine bug must degrade to "unjudged", never to a dead training loop or replica)
            log.warning("slo: evaluation tick failed (%s: %s)",
                        type(e).__name__, e)
            return 0

    def _tick(self, now):
        with self._lock:
            self._ticks += 1
            retain = max([now - 2 * max(
                r.get("slow_s") or 0, r.get("window_s") or 0,
                r.get("fast_s") or 0, 60.0) for r in self.rules]
                or [now - 600.0])
            transitions = []
            for r in self.rules:
                name = r["name"]
                hist = self._hist[name]
                hist.append((now, self._reading(r)))
                while len(hist) > 2 and hist[1][0] < retain:
                    # keep >= 2 entries so deltas always have a base
                    hist.pop(0)
                cond, value, info = self._evaluate(r, now)
                al = self._alerts[name]
                if value is not None:
                    al.value = value
                    al.info = info
                for to in al.advance(cond, now, r["for_s"],
                                     r["resolve_for_s"]):
                    transitions.append((r, al, to))
            firing = [a for a in self._alerts.values()
                      if a.state == "firing"]
            status = _status_of(self._alerts.values())
        self._emit(transitions, firing, status)
        return len(firing)

    def _emit(self, transitions, firing, status):
        from . import flight
        from .registry import counter, gauge
        for r, al, to in transitions:
            counter("mxtpu_alert_transitions_total").labels(
                rule=al.name, to=to).inc()
            if to in ("firing", "resolved"):
                flight.record("alert", rule=al.name, to=to,
                              severity=al.severity, value=al.value,
                              summary=r.get("summary", ""),
                              **{k: v for k, v in al.info.items()
                                 if isinstance(v, (int, float, str))})
        g_state = gauge("mxtpu_alert_state")
        for name, al in self._alerts.items():
            g_state.labels(rule=name).set(STATES.index(al.state))
        g_burn = gauge("mxtpu_slo_burn_rate")
        for r in self.rules:
            info = self._alerts[r["name"]].info
            if r["type"] == "burn_rate" and "burn_fast" in info:
                g_burn.labels(rule=r["name"],
                              window="fast").set(info["burn_fast"])
                g_burn.labels(rule=r["name"],
                              window="slow").set(info["burn_slow"])
        g_firing = gauge("mxtpu_alerts_firing")
        counts = {s: 0 for s in SEVERITIES}
        for al in firing:
            counts[al.severity] += 1
        for sev, n in counts.items():
            g_firing.labels(severity=sev).set(n)
        gauge("mxtpu_health_status").set(
            {"healthy": 0, "degraded": 1, "critical": 2}[status])

    # --------------------------------------------------------- verdict
    def health(self, now=None):
        """The per-rank health verdict — the ``mxtpu-health/1``
        document (see the module docstring)."""
        now = time.time() if now is None else float(now)
        with self._lock:
            alerts = list(self._alerts.values())
            doc = {
                "schema": HEALTH_SCHEMA,
                "ts": round(now, 6),
                "rank": _rank(),
                "status": _status_of(alerts),
                "firing": [a.describe(now) for a in alerts
                           if a.state == "firing"],
                "pending": [a.describe(now) for a in alerts
                            if a.state == "pending"],
                "resolved": [
                    {"rule": a.name, "severity": a.severity,
                     "ago_s": round(now - a.resolved_ts, 3)}
                    for a in alerts
                    if a.state == "inactive"
                    and a.resolved_ts is not None
                    and now - a.resolved_ts <= 600.0],
                "rules": len(self.rules),
            }
        return doc

    def alerts(self, now=None):
        """Every rule's current alert state (the ``/alerts`` endpoint
        body under ``"alerts"``)."""
        now = time.time() if now is None else float(now)
        with self._lock:
            return [self._alerts[r["name"]].describe(now)
                    for r in self.rules]


# ------------------------------------------------------ fleet evaluator

class FleetHealth:
    """Evaluate ``scope="fleet"`` rules over the merged run timeline.

    Supervisor-side (stdlib only — never touches the registry or
    flight recorder): ``launch.py`` attaches an instance to its
    :class:`~mxnet_tpu.telemetry.distview.RunAggregator`, which calls
    :meth:`observe_step` for every emitted step record and appends the
    returned alert event records to the ``mxtpu-run/1`` timeline::

        {"kind": "event", "event": "alert", "scope": "fleet",
         "rule": ..., "to": "firing" | "resolved" | ...,
         "severity": ..., "value": ..., "step": N, "ts": ...}

    The clock is the step records' own ``ts`` — a postmortem replay
    over an old timeline reproduces the same transitions."""

    def __init__(self, rules=None, num_ranks=None):
        self.specs = [r for r in (load_rules() if rules is None
                                  else rules)
                      if r.get("scope") == "fleet"]
        if num_ranks is not None:
            for r in self.specs:
                # the "every rank reported" bound is the fleet size
                if r["name"] == "fleet_rank_missing" and \
                        r.get("bound", -1) < 0:
                    r["bound"] = int(num_ranks)
        self._alerts = {r["name"]: Alert(r["name"], r["severity"])
                        for r in self.specs}

    @staticmethod
    def _values(rec, field):
        """The field's value list for quorum math: step-level fields
        give one value; ``ranks.<key>`` fans out per reporting rank.
        Booleans read as 0/1; missing values are skipped."""
        if field.startswith("ranks."):
            key = field[len("ranks."):]
            vals = [v.get(key) for v in (rec.get("ranks") or {})
                    .values()]
        else:
            vals = [rec.get(field)]
        out = []
        for v in vals:
            if isinstance(v, bool):
                out.append(1.0 if v else 0.0)
            elif isinstance(v, (int, float)):
                out.append(float(v))
        return out

    def observe_step(self, rec):
        """Feed one ``kind="step"`` timeline record; returns the alert
        event records to append to the timeline."""
        now = rec.get("ts") or time.time()
        events = []
        for r in self.specs:
            vals = self._values(rec, r["field"])
            # an unsampled field (e.g. skew measured every Nth step)
            # freezes the alert rather than resolving it
            cond = None
            value = None
            if vals:
                breaches = [v for v in vals
                            if _cmp(r["op"], v, r["bound"])]
                q = r.get("quorum", "any")
                if q == "any":
                    cond = len(breaches) >= 1
                elif q == "all":
                    cond = len(breaches) == len(vals)
                else:
                    cond = (len(breaches) / len(vals)) >= float(q)
                value = max(vals) if r["op"] == ">" else min(vals)
            al = self._alerts[r["name"]]
            if value is not None:
                al.value = round(value, 6)
                al.info = {"bound": r["bound"]}
            for to in al.advance(cond, now, r["for_s"],
                                 r["resolve_for_s"]):
                if to in ("firing", "resolved"):
                    events.append({
                        "kind": "event", "event": "alert",
                        "scope": "fleet", "rule": r["name"],
                        "to": to, "severity": r["severity"],
                        "value": al.value, "bound": r["bound"],
                        "step": rec.get("step"),
                        "ts": round(now, 6),
                    })
        return events

    def verdict(self, now=None):
        """Fleet-level health roll-up (written into the timeline as a
        ``fleet_health`` event at close)."""
        now = time.time() if now is None else float(now)
        alerts = list(self._alerts.values())
        return {
            "status": _status_of(alerts),
            "firing": [a.describe(now) for a in alerts
                       if a.state == "firing"],
            "rules": len(self.specs),
        }


# ------------------------------------------------- process-wide singleton

_mod_lock = threading.Lock()
_state = {"engine": None, "ticker": None, "stop": None,
          "last_step_tick": 0.0}


def engine():
    """The process-wide engine (created on first use)."""
    with _mod_lock:
        if _state["engine"] is None:
            _state["engine"] = SloEngine()
        return _state["engine"]


def health(now=None):
    """The process-wide engine's health verdict (creates the engine on
    first use; returns a disabled stub when ``MXNET_TPU_SLO=0``)."""
    if not enabled():
        return {"schema": HEALTH_SCHEMA, "ts": round(time.time(), 6),
                "rank": _rank(), "status": "healthy", "firing": [],
                "pending": [], "resolved": [], "rules": 0,
                "disabled": True}
    return engine().health(now)


def on_step():
    """The training-loop hook (``telemetry.step_end`` calls this):
    one clock read per step, a full evaluation at most every
    :func:`tick_seconds`.  Inert when ``MXNET_TPU_SLO=0``."""
    if not enabled():
        return
    now = time.time()
    if now - _state["last_step_tick"] < tick_seconds():
        return
    _state["last_step_tick"] = now
    engine().tick(now)


def start_ticker(interval=None):
    """Arm the background evaluation thread (the serving front door
    calls this; training loops ride :func:`on_step` instead).  Returns
    the thread, or None when SLO evaluation is disabled.  Idempotent.
    """
    if not enabled():
        return None
    with _mod_lock:
        if _state["ticker"] is not None and \
                _state["ticker"].is_alive():
            return _state["ticker"]
        stop = threading.Event()

        def _loop():
            while not stop.wait(interval if interval is not None
                                else tick_seconds()):
                eng = _state["engine"]
                if eng is not None:
                    eng.tick()

        t = threading.Thread(target=_loop, daemon=True,
                             name="mxtpu-slo-ticker")
        _state["stop"] = stop
        _state["ticker"] = t
        # the engine must exist before the first wait expires
        if _state["engine"] is None:
            _state["engine"] = SloEngine()
        t.start()
        return t


def stop_ticker():
    """Stop the background ticker (idempotent)."""
    with _mod_lock:
        if _state["stop"] is not None:
            _state["stop"].set()
        _state["ticker"] = None
        _state["stop"] = None


def reset():
    """Drop the engine and ticker (``telemetry.reset`` calls this);
    the next use rebuilds the engine from the current env."""
    stop_ticker()
    with _mod_lock:
        _state["engine"] = None
        _state["last_step_tick"] = 0.0
