"""Thread-safe metrics registry: counters, gauges, fixed-bucket histograms.

The process-wide aggregation layer under :mod:`mxnet_tpu.telemetry`.
Design follows the reference profiler's per-device stat accumulators
(``src/engine/profiler.h:32-58``: fixed tables, lock-guarded appends)
generalized to labeled Prometheus-style instruments:

* every metric must be declared in :data:`~mxnet_tpu.telemetry.catalog.
  CATALOG` — creation of an undeclared name raises immediately;
* one registry lock guards all samples (emit cost: a dict lookup and a
  float add — far below the per-record / per-step work it measures);
* ``labels(**kv)`` returns a bound child with a pre-resolved sample key
  for hot paths (per-record IO counters cache one at module import);
* histograms use fixed upper bounds declared at creation, so rendering
  never rebalances and concurrent observes never allocate.
"""
from __future__ import annotations

import threading
import time

from ..base import MXNetError
from .catalog import CATALOG, COUNTER, GAUGE, HISTOGRAM, TIME_BUCKETS

__all__ = ["Registry", "Counter", "Gauge", "Histogram", "REGISTRY",
           "counter", "gauge", "histogram"]


class _Child:
    """A metric bound to one resolved label set — the hot-path handle."""

    __slots__ = ("_metric", "_key")

    def __init__(self, metric, key):
        self._metric = metric
        self._key = key

    def inc(self, value=1):
        self._metric._add(self._key, value)

    def dec(self, value=1):
        self._metric._add(self._key, -value)

    def set(self, value):
        self._metric._set(self._key, value)

    def observe(self, value, weight=1, exemplar=None):
        self._metric._observe(self._key, value, weight, exemplar)

    def get(self):
        return self._metric._get(self._key)


class Metric:
    """Base labeled instrument.  Label-less metrics proxy the empty-key
    child so ``counter(name).inc()`` works directly."""

    kind = None

    def __init__(self, name, labelnames=(), help="", registry=None):
        self.name = name
        self.labelnames = tuple(labelnames)
        self.help = help
        self._registry = registry
        self._samples = {}
        self._default = _Child(self, ()) if not self.labelnames else None

    # ------------------------------------------------------------ labels
    def labels(self, **kv):
        """Bound child for one label set (hot paths cache the result)."""
        if set(kv) != set(self.labelnames):
            raise MXNetError(
                "metric %r takes labels %s, got %s"
                % (self.name, sorted(self.labelnames), sorted(kv)))
        key = tuple((k, str(kv[k])) for k in self.labelnames)
        return _Child(self, key)

    def _require_default(self):
        if self._default is None:
            raise MXNetError(
                "metric %r has labels %s; call .labels(...) first"
                % (self.name, sorted(self.labelnames)))
        return self._default

    # --------------------------------------------- label-less delegation
    def inc(self, value=1):
        self._require_default().inc(value)

    def dec(self, value=1):
        self._require_default().dec(value)

    def set(self, value):
        self._require_default().set(value)

    def observe(self, value, weight=1, exemplar=None):
        self._require_default().observe(value, weight, exemplar)

    def get(self):
        return self._require_default().get()

    # -------------------------------------------------------- internals
    def _lock(self):
        return self._registry._lock

    def _add(self, key, value):
        raise MXNetError("metric %r (%s) does not support add"
                         % (self.name, self.kind))

    def _set(self, key, value):
        raise MXNetError("metric %r (%s) does not support set"
                         % (self.name, self.kind))

    def _observe(self, key, value, weight=1, exemplar=None):
        raise MXNetError("metric %r (%s) does not support observe"
                         % (self.name, self.kind))

    def _get(self, key):
        with self._lock():
            return self._samples.get(key, 0.0)

    def samples(self):
        """{label key tuple: value} snapshot (histograms: dict values;
        nested exemplar maps are copied too, so readers never race a
        concurrent observe)."""
        with self._lock():
            out = {}
            for k, v in self._samples.items():
                if isinstance(v, dict):
                    v = dict(v)
                    ex = v.get("exemplars")
                    if ex is not None:
                        v["exemplars"] = dict(ex)
                out[k] = v
            return out

    def _clear(self):
        with self._lock():
            self._samples.clear()


class Counter(Metric):
    """Monotonic count; negative increments are rejected."""

    kind = COUNTER

    def _add(self, key, value):
        if value < 0:
            raise MXNetError("counter %r cannot decrease (got %r)"
                             % (self.name, value))
        with self._lock():
            self._samples[key] = self._samples.get(key, 0.0) + value


class Gauge(Metric):
    """Point-in-time value; settable and bidirectional."""

    kind = GAUGE

    def _add(self, key, value):
        with self._lock():
            self._samples[key] = self._samples.get(key, 0.0) + value

    def _set(self, key, value):
        with self._lock():
            self._samples[key] = float(value)


class Histogram(Metric):
    """Fixed-bucket histogram: per-bucket counts + sum + count.

    Buckets are upper bounds; an implicit +Inf bucket catches the tail.
    Bucket counts are stored non-cumulative and rendered cumulative by
    the Prometheus exporter.  ``observe(value, weight=w)`` credits the
    bucket/sum/count by ``w`` instead of 1 — the time-weighted form the
    queue-occupancy sampler uses (bucket counts become seconds-at-depth,
    so sum/count is the time-weighted mean).
    """

    kind = HISTOGRAM

    def __init__(self, name, labelnames=(), help="", registry=None,
                 buckets=TIME_BUCKETS):
        super().__init__(name, labelnames, help, registry)
        b = tuple(float(x) for x in buckets)
        if list(b) != sorted(b) or len(set(b)) != len(b):
            raise MXNetError("histogram %r: buckets must be strictly "
                             "increasing, got %s" % (name, list(b)))
        self.buckets = b

    def _observe(self, key, value, weight=1, exemplar=None):
        value = float(value)
        weight = float(weight)
        if weight < 0:
            raise MXNetError("histogram %r: negative observe weight %r"
                             % (self.name, weight))
        with self._lock():
            s = self._samples.get(key)
            if s is None:
                s = {"buckets": [0] * (len(self.buckets) + 1),
                     "sum": 0.0, "count": 0}
                self._samples[key] = s
            i = 0
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    break
            else:
                i = len(self.buckets)
            s["buckets"][i] += weight
            s["sum"] += value * weight
            s["count"] += weight
            if exemplar is not None:
                # each bucket remembers ONE recent observation's trace
                # id — the histogram-exemplar hook (telemetry.tracing)
                ex = s.get("exemplars")
                if ex is None:
                    ex = s["exemplars"] = {}
                ex[i] = (str(exemplar), value, round(time.time(), 3))

    def _get(self, key):
        with self._lock():
            s = self._samples.get(key)
            return dict(s) if s else {"buckets": [], "sum": 0.0,
                                      "count": 0}


_KINDS = {COUNTER: Counter, GAUGE: Gauge, HISTOGRAM: Histogram}


class Registry:
    """Holds metrics; creation is get-or-create and catalog-checked.

    ``catalog=None`` lifts the declaration requirement — for tests and
    embedders that want a private scratch registry.
    """

    def __init__(self, catalog=CATALOG):
        self._lock = threading.RLock()
        self._metrics = {}
        self._catalog = catalog

    def _get_or_create(self, kind, name, labelnames, help, buckets=None):
        if self._catalog is not None:
            decl = self._catalog.get(name)
            if decl is None:
                raise MXNetError(
                    "metric %r is not declared in telemetry.CATALOG — "
                    "add it there (and to docs/api/telemetry.md; "
                    "tools/ci_check.py guards the two against drift)"
                    % name)
            dkind, dlabels, dhelp = decl
            if dkind != kind:
                raise MXNetError("metric %r is declared as a %s, "
                                 "requested as a %s" % (name, dkind, kind))
            labelnames = labelnames or dlabels
            if tuple(labelnames) != tuple(dlabels):
                raise MXNetError(
                    "metric %r is declared with labels %s, requested "
                    "with %s" % (name, list(dlabels), list(labelnames)))
            help = help or dhelp
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if m.kind != kind or tuple(m.labelnames) != \
                        tuple(labelnames):
                    raise MXNetError(
                        "metric %r already registered as %s%s"
                        % (name, m.kind, list(m.labelnames)))
                return m
            cls = _KINDS[kind]
            if kind == HISTOGRAM:
                m = cls(name, labelnames, help, registry=self,
                        buckets=buckets or TIME_BUCKETS)
            else:
                m = cls(name, labelnames, help, registry=self)
            self._metrics[name] = m
            return m

    def counter(self, name, labelnames=(), help=""):
        return self._get_or_create(COUNTER, name, labelnames, help)

    def gauge(self, name, labelnames=(), help=""):
        return self._get_or_create(GAUGE, name, labelnames, help)

    def histogram(self, name, labelnames=(), help="", buckets=None):
        return self._get_or_create(HISTOGRAM, name, labelnames, help,
                                   buckets=buckets)

    def metrics(self):
        with self._lock:
            return dict(self._metrics)

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    def reset(self):
        """Clear every sample but keep the metric objects, so children
        cached at module import stay valid."""
        for m in self.metrics().values():
            m._clear()

    # ------------------------------------------------------- snapshots
    def flat(self, kinds=None):
        """{'name' or 'name{l="v"}': value} for scalar metrics; the
        JSONL / report snapshot format.  Histograms are flattened to
        ``name_sum`` / ``name_count`` entries."""
        out = {}
        for name, m in sorted(self.metrics().items()):
            if kinds is not None and m.kind not in kinds:
                continue
            for key, val in sorted(m.samples().items()):
                suffix = "" if not key else \
                    "{%s}" % ",".join('%s="%s"' % kv for kv in key)
                if m.kind == HISTOGRAM:
                    out[name + "_sum" + suffix] = val["sum"]
                    out[name + "_count" + suffix] = val["count"]
                else:
                    out[name + suffix] = val
        return out


#: the process-wide default registry (module-level helpers below)
REGISTRY = Registry()


def counter(name, labelnames=(), help=""):
    """Get-or-create a catalog-declared counter on the default registry."""
    return REGISTRY.counter(name, labelnames, help)


def gauge(name, labelnames=(), help=""):
    """Get-or-create a catalog-declared gauge on the default registry."""
    return REGISTRY.gauge(name, labelnames, help)


def histogram(name, labelnames=(), help="", buckets=None):
    """Get-or-create a catalog-declared histogram on the default
    registry."""
    return REGISTRY.histogram(name, labelnames, help, buckets=buckets)
