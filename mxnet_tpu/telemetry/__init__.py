"""Telemetry: unified metrics, tracing spans, and run reports.

The process-wide observability subsystem.  The reference framework's
only runtime window is the engine profiler's Chrome-trace dump
(``src/engine/profiler.{h,cc}``, SURVEY §5.1); this package keeps that
trace (spans feed it — see :mod:`mxnet_tpu.profiler`) and adds the
aggregation layer every TPU optimization decision needs: per-step cost
attribution, compile accounting, and the scattered robustness counters
(bad-record skips, retries, prefetch stalls, kvstore traffic, watchdog
restarts) absorbed into one registry.

Three engines:

* **metrics registry** (:mod:`.registry`) — thread-safe counters,
  gauges, and fixed-bucket histograms with label support; every metric
  is declared in :data:`CATALOG` (:mod:`.catalog`), and creation of an
  undeclared name raises at the emit site;
* **span tracer** (:mod:`.spans`) — ``telemetry.span("fwd")`` context
  manager/decorator recording wall time per phase, wired through the
  executor, Module, both trainers, and the IO stack, and mirrored into
  the Chrome trace when the profiler is running;
* **distributed tracing** (:mod:`.tracing`) — W3C-traceparent trace
  context (thread-local + explicitly attachable) giving every serving
  request and training step ONE causal trace: spans entered under an
  active trace record into it, batch fan-in is expressed with span
  links (one dispatch, many parents), retention is tail-sampled
  (errors/sheds + the slow tail always kept, the rest at
  ``MXNET_TPU_TRACE_SAMPLE``), kept traces export as ``mxtpu-trace/1``
  JSONL per rank (``MXNET_TPU_TRACE_DIR``), and latency histograms
  carry per-bucket trace-id exemplars; ``tools/trace_top.py`` ranks,
  reconstructs waterfalls, and attributes the critical path;
* **exporters** (:mod:`.exporters`) — a JSONL step-log
  (``MXNET_TPU_TELEMETRY_JSONL``), Prometheus text format
  (:func:`render_prom`, served on ``MXNET_TPU_TELEMETRY_PORT``), and
  the end-of-run :func:`report` dict ``bench.py`` emits;
* **memory observability** (:mod:`.memory`) — static XLA memory plans
  per compiled program (``memory_analysis``/``cost_analysis`` gauges),
  live ``device.memory_stats()`` sampling at step boundaries, a
  pre-dispatch budget check (``MXNET_TPU_MEMORY_BUDGET``), and
  ``RESOURCE_EXHAUSTED`` annotation with plan + live-buffer forensics;
* **input-pipeline view** (:mod:`.ioview`) — per-stage accounting of
  the data plane (read/decode/augment/batch/host prefetch/device
  staging: wall, items, bytes), time-weighted prefetch-queue occupancy,
  a per-window bottleneck classifier (producer-bound naming the slow
  stage / consumer-bound / balanced), and iterator ``position()``
  tracking riding step records and checkpoint manifests;
  ``tools/io_top.py`` renders the stream;
* **flight recorder** (:mod:`.flight`) — a bounded ring of recent
  structured events dumped to a JSON black box
  (``MXNET_TPU_FLIGHT_DIR``) on MXNetError/OOM/SIGTERM/crash;
  ``tools/flight_read.py`` pretty-prints a dump;
* **cost database** (:mod:`.costdb`) — persistent op/block cost
  records (``MXNET_TPU_COSTDB``, schema ``mxtpu-costdb/1``) joining
  measured wall time, flops/bytes, and fused-block identity into
  MFU/roofline attribution; ``tools/perf_top.py`` ranks the worst
  blocks, ``tools/bench_diff.py`` guards the BENCH trajectory;
* **training-health numerics** (:mod:`.numerics`) — jit-safe in-graph
  tensor stats sampled every ``MXNET_TPU_NUMERICS_EVERY`` steps
  (param/grad/fused-block norms, non-finite counts, value digests,
  global grad norm), anomaly rules with NaN/Inf provenance and a
  strict-mode stop, and the per-step divergence ledger
  ``tools/numdiff.py`` bisects;
* **SLO engine / healthd** (:mod:`.slo`) — the judge over every sensor
  above: a declared rule catalog (threshold / multi-window burn-rate /
  absence / anomaly-passthrough, ``MXNET_TPU_SLO_RULES`` overrides)
  evaluated by an in-process ticker, an alert state machine (pending →
  firing → resolved with debounce) emitting ``mxtpu_alert_*`` metrics
  and ``alert`` flight events, the per-rank ``health()`` verdict
  behind the serving tier's deep ``/healthz``, and fleet-scope rules
  evaluated over the run timeline by ``launch.py``;
  ``tools/health_top.py`` renders live and postmortem views.

Compile events come from ``jax.monitoring`` listeners where available
(:mod:`.compile`), else a first-call-vs-steady-state heuristic.

See ``docs/api/telemetry.md`` for the full metric catalog, env knobs,
and exporter formats.
"""
from __future__ import annotations

import os as _os

from .catalog import CATALOG, selfcheck
from .registry import (REGISTRY, Registry, Counter, Gauge, Histogram,
                       counter, gauge, histogram)
from . import tracing
from .spans import span, drain_step_spans, step_span_totals
from . import flight
from . import memory
from . import distview
from . import ioview
from . import costdb
from . import numerics
from . import slo
from .exporters import (step_end, jsonl_event, render_prom, report,
                        start_http_server, jsonl_path, env_port, reset,
                        reset_steps)
from . import compile as compile_events
from .exporters import _init_env_state

__all__ = [
    "CATALOG", "selfcheck",
    "REGISTRY", "Registry", "Counter", "Gauge", "Histogram",
    "counter", "gauge", "histogram",
    "span", "drain_step_spans", "step_span_totals",
    "step_end", "jsonl_event", "render_prom", "report",
    "start_http_server", "jsonl_path", "env_port", "reset",
    "reset_steps", "compile_events",
    "flight", "memory", "distview", "ioview", "costdb", "numerics",
    "slo", "tracing",
]

# best-effort process-wide init: compile listener (jax.monitoring) and
# env-derived gauges.  Both are cheap and dependency-light; the http
# endpoint starts only when MXNET_TPU_TELEMETRY_PORT is set.
compile_events.install()
_init_env_state()
# black-box mode: an uncaught crash must leave a flight dump for the
# launch.py watchdog to collect
if flight.dump_dir():
    flight.install_excepthook()
# on-demand live capture: SIGUSR1 (relayed fleet-wide by tools/launch.py
# --capture) writes a bounded profiler window + flight snapshot
if distview.capture_dir():
    distview.install_capture_handler()
# the per-process index offset (env_port) keeps co-located multi-process
# workers from racing to bind ONE fixed port
_port = env_port()
if _port > 0:
    try:
        start_http_server(_port)
    except (OSError, OverflowError, ValueError):
        # OverflowError: out-of-range port (socket.bind raises it, not
        # OSError) — an env typo must not break `import mxnet_tpu`
        import logging as _logging
        _logging.getLogger(__name__).warning(
            "MXNET_TPU_TELEMETRY_PORT=%s: cannot bind the metrics "
            "endpoint; telemetry continues without it",
            _os.environ["MXNET_TPU_TELEMETRY_PORT"])
