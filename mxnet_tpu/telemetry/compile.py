"""XLA compile-event capture.

A Learned Performance Model for TPUs (PAPERS.md) treats compile count /
time as first-class run facts: an unexpected recompile per step is the
single most common TPU performance bug.  Two capture modes:

* **jax.monitoring** (preferred): JAX emits a
  ``/jax/core/compile/backend_compile_duration`` duration event per
  backend compile; a process-wide listener feeds
  ``mxtpu_compile_total`` / ``mxtpu_compile_seconds_total``.
* **first-call heuristic** (fallback when the listener API is absent):
  ``report()`` classifies steps whose wall time dwarfs the steady-state
  median as compile-inflated — see
  :func:`mxnet_tpu.telemetry.exporters.report`.
"""
from __future__ import annotations

from .registry import counter

__all__ = ["install", "installed"]

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_installed = None   # None = not attempted, True/False = outcome


def install():
    """Register the jax.monitoring duration listener once per process.
    Returns True when listening, False when the API is unavailable
    (report() then falls back to the step-time heuristic)."""
    global _installed
    if _installed is not None:
        return _installed
    try:
        from jax import monitoring
        register = monitoring.register_event_duration_secs_listener
    except (ImportError, AttributeError):
        _installed = False
        return False
    c_total = counter("mxtpu_compile_total")
    c_secs = counter("mxtpu_compile_seconds_total")

    def _on_duration(name, dur, **kwargs):
        if name == _COMPILE_EVENT:
            c_total.inc()
            c_secs.inc(float(dur))
            from . import flight
            flight.record("compile", duration_s=round(float(dur), 6))

    try:
        register(_on_duration)
    except TypeError:
        # listener signature changed under us: degrade to the heuristic
        _installed = False
        return False
    _installed = True
    return True


def installed():
    """True when the jax.monitoring listener is active."""
    return bool(_installed)
