"""Input-pipeline observability: per-stage accounting, time-weighted
queue occupancy, bottleneck attribution, and iterator position.

PR 5's step segments say *when* the data plane is the straggler
(``input_wait`` dominates the step); this module says *why*.  The input
pipeline is modeled as a staged dataflow::

    read -> decode -> augment -> batch -> host_prefetch -> device_stage

and every stage accounts wall time, item count, and bytes at its emit
site (``recordio.py`` / ``io_native.py`` readers, ``image.py`` decode +
augmenters, ``io.py`` prefetchers, the trainer's host->device staging)
into the ``mxtpu_io_stage_*`` catalog metrics.  Three more pieces:

* **time-weighted queue occupancy** (:func:`queue_tracker`) — the
  prefetch queues used to export ``set(qsize())`` from BOTH the
  producer and the consumer thread, so the depth gauge flapped with
  scheduling; the tracker owns an internal depth counter, accumulates
  *seconds spent at each depth* into the weighted
  ``mxtpu_io_queue_occupancy`` histogram, and sets the legacy
  ``mxtpu_io_prefetch_depth`` gauge as a consistent last-observed value
  under its own lock;
* **bottleneck classification** (:func:`classify`) — per window
  (``MXNET_TPU_IOVIEW_WINDOW`` seconds), consumer-stall time (the
  training loop blocked on the pipeline) is weighed against
  producer-starved time (prefetch threads idle waiting for the
  consumer): stall-dominant windows are *producer-bound* and name the
  slowest work stage, starved-dominant windows are *consumer-bound*
  (the device binds, the pipeline is healthy), the rest are *balanced*.
  Each verdict bumps ``mxtpu_io_bottleneck_total{stage}`` and leaves an
  ``io_bottleneck`` flight event;
* **iterator position** (:func:`track` / :func:`current_position`) — a
  ``position()`` API threaded through the DataIter chain (epoch, shard
  id, record offset, resync count); the tracked iterator's position
  rides each sampled step's JSONL record and is written into
  checkpoint-manifest meta as advisory ``data_position`` (the
  observability half of mid-epoch resume; restore comes later).

Per-step surface: :func:`step_record` (called by
``telemetry.exporters.step_end`` every ``MXNET_TPU_IOVIEW_EVERY``
steps) returns the ``io`` block of the JSONL step record — per-stage
deltas, stall/starved deltas, occupancy snapshot, the latest verdict,
and the iterator position.  ``tools/io_top.py`` renders the resulting
stream (live or postmortem, ``--json`` schema ``mxtpu-iotop/1``), and
the launch.py run aggregator carries the block into the ``mxtpu-run/1``
timeline so ``run_top --summarize`` can name the slow *stage* on the
slow *rank*.

Import discipline (same as :mod:`.distview`): module-level imports are
stdlib-only and in-package imports are deferred into the worker-half
functions, so ``tools/io_top.py`` can load this file by path without
dragging jax into a reader process.
"""
from __future__ import annotations

import os
import threading
import time
import weakref

__all__ = [
    "STAGES", "IOTOP_SCHEMA", "DEPTH_BUCKETS",
    "ioview_every", "window_seconds",
    "account", "note_stall", "note_starved", "queue_tracker",
    "OccupancyTracker", "track", "current_position",
    "classify", "step_record", "snapshot", "summary", "reset",
    "summarize_io",
]

#: pipeline stages, in dataflow order (work stages the classifier ranks)
STAGES = ("read", "decode", "augment", "batch", "host_prefetch",
          "device_stage")

#: io_top --json schema tag
IOTOP_SCHEMA = "mxtpu-iotop/1"

#: queue-depth upper bounds for the time-weighted occupancy histogram
DEPTH_BUCKETS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0)

# indirection so tests can drive the clock deterministically
_now = time.perf_counter

# per-thread accumulated stage seconds: lets a wrapper stage (the
# host_prefetch producer) account its wall EXCLUSIVE of the inner
# stages that ran on the same thread — otherwise the wrapper is >= the
# sum of its children by construction and always "wins" the slowest-
# stage verdict
_tls = threading.local()


def thread_accounted():
    """Stage seconds accounted on THIS thread so far (monotonic;
    subtract two readings to get the inner-stage time of a region)."""
    return getattr(_tls, "accounted", 0.0)


def ioview_every():
    """Attach the ``io`` block to every Nth step's JSONL record
    (``MXNET_TPU_IOVIEW_EVERY``, default 1 = every step; 0 disables the
    per-step block — stage metrics and the classifier keep running)."""
    try:
        return max(0, int(os.environ.get("MXNET_TPU_IOVIEW_EVERY", "1")))
    except ValueError:
        return 1


def window_seconds():
    """Bottleneck-classifier window length in seconds
    (``MXNET_TPU_IOVIEW_WINDOW``, default 5)."""
    try:
        return max(0.05, float(os.environ.get("MXNET_TPU_IOVIEW_WINDOW",
                                              "5")))
    except ValueError:
        return 5.0


# ------------------------------------------------------- stage accounting

_lock = threading.Lock()
# stage -> [seconds, items, bytes] (process totals)
_stages = {}
# iter name -> seconds (process totals)
_stall = {}
_starved = {}
# snapshots consumed by step_record / classify deltas
_step_state = {"calls": 0, "t0": None, "stages": {}, "stall": {},
               "starved": {}}
_win_state = {"t0": None, "stages": {}, "stall": {}, "starved": {},
              "last": None}
# first-activity timestamp: the whole-run "window" the read-only
# classification (summary) and ingest rates are computed over
_activity = [None]
# cached bound metric children (hot path: one lock + dict math)
_metric_cache = {}


def _stage_metrics(stage):
    m = _metric_cache.get(("stage", stage))
    if m is None:
        from mxnet_tpu.telemetry.registry import counter, histogram
        m = (histogram("mxtpu_io_stage_seconds").labels(stage=stage),
             counter("mxtpu_io_stage_items_total").labels(stage=stage),
             counter("mxtpu_io_bytes_total").labels(stage=stage))
        _metric_cache[("stage", stage)] = m
    return m


def account(stage, seconds, items=0, nbytes=0):
    """Charge one unit of work (a record, an image, a batch) to a
    pipeline stage: ``seconds`` wall time, ``items`` processed,
    ``nbytes`` moved.  Hot path — called per record by the readers —
    so the cost is one lock plus three metric updates."""
    seconds = max(0.0, float(seconds))
    with _lock:
        acc = _stages.get(stage)
        if acc is None:
            acc = _stages[stage] = [0.0, 0.0, 0.0]
        acc[0] += seconds
        acc[1] += items
        acc[2] += nbytes
        if _activity[0] is None:
            _activity[0] = _now()
    _tls.accounted = thread_accounted() + seconds
    sec_h, items_c, bytes_c = _stage_metrics(stage)
    sec_h.observe(seconds)
    if items:
        items_c.inc(items)
    if nbytes:
        bytes_c.inc(nbytes)


def note_stall(iter_, seconds):
    """The consumer blocked ``seconds`` waiting on the ``iter_``
    (``host``/``device``) prefetcher — the producer-bound signal."""
    seconds = max(0.0, float(seconds))
    with _lock:
        _stall[iter_] = _stall.get(iter_, 0.0) + seconds
        if _activity[0] is None:
            _activity[0] = _now()
    c = _metric_cache.get(("stall", iter_))
    if c is None:
        from mxnet_tpu.telemetry.registry import counter
        c = counter("mxtpu_io_prefetch_stall_seconds_total").labels(
            iter=iter_)
        _metric_cache[("stall", iter_)] = c
    c.inc(seconds)


def note_starved(iter_, seconds):
    """A producer thread idled ``seconds`` waiting for the consumer to
    drain the ``iter_`` queue — the consumer-bound signal (a slow
    consumer must not be misread as a healthy pipeline).

    Intervals far beyond the classifier window (10x) are dropped: a
    producer parked across a validation pass or an inter-epoch pause is
    not pipeline backpressure, and one such gap would otherwise flip a
    whole postmortem to consumer-bound.  Genuine backpressure shows as
    a steady stream of sub-step-length intervals, which all count."""
    seconds = max(0.0, float(seconds))
    if seconds > 10.0 * window_seconds():
        return
    with _lock:
        _starved[iter_] = _starved.get(iter_, 0.0) + seconds
        if _activity[0] is None:
            _activity[0] = _now()
    c = _metric_cache.get(("starved", iter_))
    if c is None:
        from mxnet_tpu.telemetry.registry import counter
        c = counter("mxtpu_io_prefetch_starved_seconds_total").labels(
            iter=iter_)
        _metric_cache[("starved", iter_)] = c
    c.inc(seconds)


# --------------------------------------------- time-weighted occupancy

class OccupancyTracker:
    """Time-weighted queue-depth accounting for one prefetch queue.

    The producer calls :meth:`adjust(+1)` after a put, the consumer
    :meth:`adjust(-1)` after a take; the tracker owns the depth counter
    (never ``qsize()`` read from two threads), accumulates the seconds
    spent at each depth into its waterline dict AND the weighted
    ``mxtpu_io_queue_occupancy{iter}`` histogram, and sets the
    ``mxtpu_io_prefetch_depth{iter}`` gauge under its own lock — a
    consistent last-observed value instead of the old producer/consumer
    ``set(qsize())`` race."""

    def __init__(self, name):
        self.name = name
        self._lock = threading.Lock()
        self._depth = 0
        self._since = None
        self._levels = {}        # depth -> seconds
        self._hist = None
        self._gauge = None

    def _metrics(self):
        if self._hist is None:
            from mxnet_tpu.telemetry.registry import gauge, histogram
            self._hist = histogram(
                "mxtpu_io_queue_occupancy",
                buckets=DEPTH_BUCKETS).labels(iter=self.name)
            self._gauge = gauge("mxtpu_io_prefetch_depth").labels(
                iter=self.name)
        return self._hist, self._gauge

    def _settle(self, now):
        # under self._lock: credit the elapsed interval to the depth
        # the queue actually held over it
        if self._since is not None:
            dt = max(0.0, now - self._since)
            if dt:
                self._levels[self._depth] = \
                    self._levels.get(self._depth, 0.0) + dt
                hist, _gauge = self._metrics()
                hist.observe(self._depth, weight=dt)
        self._since = now

    def adjust(self, delta):
        """Transition the depth by ``delta`` (+1 put, -1 take)."""
        now = _now()
        with self._lock:
            self._settle(now)
            self._depth = max(0, self._depth + int(delta))
            _hist, gauge = self._metrics()
            gauge.set(self._depth)

    def set_depth(self, depth):
        """Force the depth (reset / composite-ready transitions)."""
        now = _now()
        with self._lock:
            self._settle(now)
            self._depth = max(0, int(depth))
            _hist, gauge = self._metrics()
            gauge.set(self._depth)

    def depth(self):
        with self._lock:
            return self._depth

    def snapshot(self):
        """{"depth", "mean", "levels"} — mean is time-weighted."""
        now = _now()
        with self._lock:
            self._settle(now)
            total = sum(self._levels.values())
            mean = (sum(d * s for d, s in self._levels.items()) / total
                    if total else float(self._depth))
            return {"depth": self._depth,
                    "mean": round(mean, 3),
                    "levels": {str(d): round(s, 6)
                               for d, s in sorted(self._levels.items())}}


_trackers = {}


def queue_tracker(name):
    """Get-or-create the process tracker for the named queue
    (``host`` = PrefetchingIter, ``device`` = DevicePrefetchIter)."""
    with _lock:
        t = _trackers.get(name)
        if t is None:
            t = _trackers[name] = OccupancyTracker(name)
        return t


# ------------------------------------------------------ iterator position

_pos_ref = [None]


def track(it):
    """Register ``it`` as the run's active data iterator: its
    ``position()`` rides every sampled step record and checkpoint
    manifest.  Held by weakref — tracking never extends the iterator's
    lifetime.  Returns ``it`` so call sites can wrap in place."""
    try:
        _pos_ref[0] = weakref.ref(it)
    except TypeError:
        _pos_ref[0] = None
    return it


def current_position():
    """The tracked iterator's ``position()`` dict (epoch, shard, record
    offset, resync count — whatever the chain reports), or None when no
    iterator is tracked or it reports nothing.  Never raises: position
    is advisory observability, not control flow."""
    ref = _pos_ref[0]
    it = ref() if ref is not None else None
    if it is None:
        return None
    fn = getattr(it, "position", None)
    if not callable(fn):
        return None
    try:
        pos = fn()
    except Exception:  # mxlint: allow-broad-except(advisory position from arbitrary user iterators must never kill the step/checkpoint that asked for it)
        return None
    return pos if isinstance(pos, dict) else None


def current_state():
    """The tracked iterator's durable ``state()`` dict
    (``mxnet_tpu.io_resume`` contract), or None when no iterator is
    tracked or it declares no durable state.  Never raises: like
    position, capture at checkpoint time is best-effort — restore-side
    validation is where strictness lives."""
    ref = _pos_ref[0]
    it = ref() if ref is not None else None
    if it is None:
        return None
    fn = getattr(it, "state", None)
    if not callable(fn):
        return None
    try:
        st = fn()
    except Exception:  # mxlint: allow-broad-except(advisory state capture from arbitrary user iterators must never kill the checkpoint save that asked for it)
        return None
    return st if isinstance(st, dict) else None


def tracked_iterator():
    """The live tracked iterator object, or None — the restore side of
    the loop (``io_resume.apply_pending``) needs the object itself, not
    just its state."""
    ref = _pos_ref[0]
    return ref() if ref is not None else None


# --------------------------------------------------- bottleneck classifier

def _totals_locked():
    return ({k: tuple(v) for k, v in _stages.items()},
            dict(_stall), dict(_starved))


def _verdict(stage_delta, stall_s, starved_s, window_s=None):
    """The classification rule, shared by the live classifier and the
    io_top aggregation: stall-dominant -> producer-bound naming the
    slowest work stage; starved-dominant -> consumer-bound; else
    balanced.  A pipeline with NO prefetcher emits neither stall nor
    starved time (the stages run inline on the consumer thread) — there
    the work-to-wall ratio decides: stages eating most of the window
    ARE the bottleneck.  Returns None when there was no pipeline
    activity at all."""
    work = sum(s for s, _i, _b in stage_delta.values())
    if work <= 0.0 and stall_s <= 0.0 and starved_s <= 0.0:
        return None
    floor = 1e-4

    def _slowest():
        return max(stage_delta.items(),
                   key=lambda kv: kv[1][0])[0] if stage_delta else "read"

    if stall_s > max(2.0 * starved_s, floor):
        return {"verdict": "producer-bound", "stage": _slowest()}
    if starved_s > max(2.0 * stall_s, floor):
        return {"verdict": "consumer-bound", "stage": "consumer"}
    if stall_s <= floor and starved_s <= floor and window_s:
        if work > 0.5 * window_s:
            return {"verdict": "producer-bound", "stage": _slowest()}
        if work < 0.25 * window_s:
            return {"verdict": "consumer-bound", "stage": "consumer"}
    return {"verdict": "balanced", "stage": "balanced"}


def classify(force=False, commit=True):
    """Run the per-window bottleneck classifier.  No-op (returning the
    last verdict) until ``MXNET_TPU_IOVIEW_WINDOW`` seconds of window
    have elapsed, unless ``force``.  A verdict bumps
    ``mxtpu_io_bottleneck_total{stage}`` and records an
    ``io_bottleneck`` flight event.

    ``commit=False`` is the READ-ONLY form (:func:`summary` uses it):
    the verdict is computed over the whole run's totals without
    rotating the live window, bumping the counter, or touching the
    flight ring — a periodic snapshot caller must not perturb the
    production classifier cadence (``force`` is implied)."""
    now = _now()
    if not commit:
        with _lock:
            stages, stall, starved = _totals_locked()
            t0 = _activity[0]
        return _verdict(stages, sum(stall.values()),
                        sum(starved.values()),
                        window_s=(now - t0) if t0 else None)
    with _lock:
        if _win_state["t0"] is None:
            # arm with an EMPTY baseline: activity accumulated before
            # the first classify belongs to the first window (a forced
            # classify on a short run must still see it)
            _win_state["t0"] = now
            _win_state["stages"], _win_state["stall"], \
                _win_state["starved"] = {}, {}, {}
            if not force:
                return _win_state["last"]
        elapsed = now - _win_state["t0"]
        if not force and elapsed < window_seconds():
            return _win_state["last"]
        prev_stages = _win_state["stages"]
        prev_stall = _win_state["stall"]
        prev_starved = _win_state["starved"]
        cur_stages, cur_stall, cur_starved = _totals_locked()
        delta = {}
        for st, (s, i, b) in cur_stages.items():
            p = prev_stages.get(st, (0.0, 0.0, 0.0))
            ds = (s - p[0], i - p[1], b - p[2])
            if any(x > 0 for x in ds):
                delta[st] = ds
        stall_d = sum(cur_stall.values()) - sum(prev_stall.values())
        starved_d = sum(cur_starved.values()) - sum(prev_starved.values())
        _win_state["t0"] = now
        _win_state["stages"], _win_state["stall"], \
            _win_state["starved"] = cur_stages, cur_stall, cur_starved
        verdict = _verdict(delta, stall_d, starved_d,
                           window_s=elapsed or None)
        if verdict is None:
            return _win_state["last"]
        verdict = dict(verdict, window_s=round(elapsed, 3),
                       stall_s=round(max(0.0, stall_d), 6),
                       starved_s=round(max(0.0, starved_d), 6))
        _win_state["last"] = verdict
    from mxnet_tpu.telemetry.registry import counter
    counter("mxtpu_io_bottleneck_total").labels(
        stage=verdict["stage"]).inc()
    from mxnet_tpu.telemetry import flight
    flight.record("io_bottleneck", **verdict)
    return verdict


# ------------------------------------------------------ per-step surface

def step_record():
    """The ``io`` block for this step's JSONL record, or None when the
    cadence (``MXNET_TPU_IOVIEW_EVERY``) skips this step or the
    pipeline saw no activity since the last emitted block.  Emitted
    fields are DELTAS since the previous block (so an aggregator can
    sum them); ``queues`` and ``position`` are absolute.  Also ticks
    the window classifier."""
    verdict = classify()
    every = ioview_every()
    if every == 0:
        return None
    with _lock:
        _step_state["calls"] += 1
        if (_step_state["calls"] - 1) % every:
            return None
        now = _now()
        t0 = _step_state["t0"]
        prev_stages = _step_state["stages"]
        prev_stall = _step_state["stall"]
        prev_starved = _step_state["starved"]
        cur_stages, cur_stall, cur_starved = _totals_locked()
        _step_state["t0"] = now
        _step_state["stages"], _step_state["stall"], \
            _step_state["starved"] = cur_stages, cur_stall, cur_starved
        stages = {}
        for st, (s, i, b) in cur_stages.items():
            p = prev_stages.get(st, (0.0, 0.0, 0.0))
            ds, di, db = s - p[0], i - p[1], b - p[2]
            if ds > 0 or di > 0 or db > 0:
                stages[st] = {"s": round(ds, 6), "items": round(di, 3),
                              "bytes": round(db, 3)}
        stall = {k: round(v - prev_stall.get(k, 0.0), 6)
                 for k, v in cur_stall.items()
                 if v - prev_stall.get(k, 0.0) > 0}
        starved = {k: round(v - prev_starved.get(k, 0.0), 6)
                   for k, v in cur_starved.items()
                   if v - prev_starved.get(k, 0.0) > 0}
        trackers = dict(_trackers)
    if not stages and not stall and not starved:
        return None
    rec = {"stages": stages}
    if stall:
        rec["stall_s"] = stall
    if starved:
        rec["starved_s"] = starved
    if trackers:
        rec["queues"] = {n: t.snapshot() for n, t in trackers.items()}
    if t0 is not None:
        rec["window_s"] = round(now - t0, 6)
    if verdict is not None:
        rec["bottleneck"] = {"verdict": verdict["verdict"],
                             "stage": verdict["stage"]}
    pos = current_position()
    if pos is not None:
        rec["position"] = pos
    return rec


def snapshot():
    """Process-lifetime totals: per-stage seconds/items/bytes,
    stall/starved seconds per prefetcher, queue occupancy."""
    with _lock:
        stages, stall, starved = _totals_locked()
        trackers = dict(_trackers)
    return {
        "stages": {st: {"s": round(s, 6), "items": i, "bytes": b}
                   for st, (s, i, b) in sorted(stages.items())},
        "stall_s": {k: round(v, 6) for k, v in sorted(stall.items())},
        "starved_s": {k: round(v, 6) for k, v in sorted(starved.items())},
        "queues": {n: t.snapshot() for n, t in sorted(trackers.items())},
    }


def summary():
    """The BENCH JSON ``io`` block: the snapshot totals plus a
    whole-run bottleneck verdict (read-only — repeated calls never
    rotate the live classifier window or emit verdict metrics/events)
    and the iterator position.  Cheap and exception-free when the run
    did no pipeline IO."""
    out = snapshot()
    out["bottleneck"] = classify(commit=False)
    pos = current_position()
    if pos is not None:
        out["position"] = pos
    return out


def reset():
    """Clear every accumulator, tracker, window, and the tracked
    iterator (``telemetry.reset`` calls this).  Cached metric children
    stay valid — the registry keeps metric objects across resets."""
    with _lock:
        _stages.clear()
        _stall.clear()
        _starved.clear()
        _trackers.clear()
        _step_state.update(calls=0, t0=None, stages={}, stall={},
                           starved={})
        _win_state.update(t0=None, stages={}, stall={}, starved={},
                          last=None)
        _activity[0] = None
    _pos_ref[0] = None


# ------------------------------------------------- aggregation (stdlib)
# Everything below is stdlib-only: tools/io_top.py loads this module by
# file path and must never import jax.

def _io_blocks(records):
    """Yield ``(rank, io_block)`` from either a per-rank JSONL step-log
    (records with "io") or an ``mxtpu-run/1`` timeline (step records
    whose per-rank payloads carry "io")."""
    for rec in records:
        if not isinstance(rec, dict):
            continue
        if rec.get("kind") == "step" and isinstance(rec.get("ranks"),
                                                    dict):
            for r, v in rec["ranks"].items():
                if isinstance(v, dict) and isinstance(v.get("io"), dict):
                    yield int(r), v["io"]
        elif isinstance(rec.get("io"), dict):
            try:
                r = int(rec.get("rank", 0) or 0)
            except (TypeError, ValueError):
                r = 0
            yield r, rec["io"]


def summarize_io(records, source=None):
    """Roll a record stream up into the ``mxtpu-iotop/1`` report:
    per-rank per-stage totals (seconds/items/bytes + throughput),
    stall/starved totals, the last queue occupancy waterlines, the last
    position, a per-rank verdict recomputed from the totals, the
    overall named bottleneck, and per-shard ingest skew.  Input records
    come from ``json.loads`` over a step-log or run timeline; raises
    ValueError when no ``io`` blocks are present."""
    ranks = {}
    for r, io in _io_blocks(records):
        agg = ranks.setdefault(r, {
            "stages": {}, "stall_s": {}, "starved_s": {},
            "queues": None, "position": None, "window_s": 0.0})
        for st, v in (io.get("stages") or {}).items():
            acc = agg["stages"].setdefault(st, [0.0, 0.0, 0.0])
            acc[0] += float(v.get("s") or 0.0)
            acc[1] += float(v.get("items") or 0.0)
            acc[2] += float(v.get("bytes") or 0.0)
        for key in ("stall_s", "starved_s"):
            for k, v in (io.get(key) or {}).items():
                agg[key][k] = agg[key].get(k, 0.0) + float(v or 0.0)
        if io.get("queues"):
            agg["queues"] = io["queues"]
        if io.get("position"):
            agg["position"] = io["position"]
        agg["window_s"] += float(io.get("window_s") or 0.0)
    if not ranks:
        raise ValueError(
            "no io blocks found — was the run recorded with "
            "MXNET_TPU_TELEMETRY_JSONL set and MXNET_TPU_IOVIEW_EVERY "
            "> 0?")
    out_ranks = {}
    overall = None
    overall_key = -1.0
    ingest = {}
    for r, agg in sorted(ranks.items()):
        stage_delta = {st: tuple(v) for st, v in agg["stages"].items()}
        stall_s = sum(agg["stall_s"].values())
        starved_s = sum(agg["starved_s"].values())
        window = agg["window_s"]
        verdict = _verdict(stage_delta, stall_s, starved_s,
                           window_s=window or None)
        items = max([v[1] for v in stage_delta.values()] or [0.0])
        rate = round(items / window, 3) if window > 0 else None
        ingest[r] = rate
        rd = {
            "stages": {st: {"s": round(s, 6), "items": i, "bytes": b,
                            "items_per_s": round(i / s, 3) if s else None}
                       for st, (s, i, b) in sorted(stage_delta.items())},
            "stall_s": {k: round(v, 6)
                        for k, v in sorted(agg["stall_s"].items())},
            "starved_s": {k: round(v, 6)
                          for k, v in sorted(agg["starved_s"].items())},
            "ingest_items_per_s": rate,
            "bottleneck": verdict,
        }
        if agg["queues"]:
            rd["queues"] = agg["queues"]
        if agg["position"]:
            rd["position"] = agg["position"]
        out_ranks[str(r)] = rd
        # the overall bottleneck: the producer-bound rank whose slow
        # stage burned the most wall; consumer-bound only when no rank
        # is pipeline-limited
        if verdict and verdict["verdict"] == "producer-bound":
            slow_s = stage_delta.get(verdict["stage"], (0.0,))[0]
            if slow_s > overall_key:
                overall_key = slow_s
                overall = dict(verdict, rank=r)
        elif overall is None and verdict is not None:
            overall = dict(verdict, rank=r)
    # skew only over ranks whose rate was actually measured — a rank
    # with no window data must not be "slowest at 0 items/s"
    measured = {r: v for r, v in ingest.items() if v}
    shard_skew = None
    if len(measured) >= 2:
        rates = list(measured.values())
        shard_skew = {
            "min_items_per_s": min(rates), "max_items_per_s": max(rates),
            "ratio": round(max(rates) / min(rates), 3) if min(rates)
            else None,
            "slowest_rank": min(measured, key=measured.get),
        }
    totals = {}
    for rd in out_ranks.values():
        for st, v in rd["stages"].items():
            acc = totals.setdefault(st, [0.0, 0.0, 0.0])
            acc[0] += v["s"]
            acc[1] += v["items"]
            acc[2] += v["bytes"]
    return {
        "schema": IOTOP_SCHEMA,
        "source": source,
        "num_ranks": len(out_ranks),
        "stages": {st: {"s": round(s, 6), "items": i, "bytes": b}
                   for st, (s, i, b) in sorted(totals.items())},
        "ranks": out_ranks,
        "bottleneck": overall,
        "shard_skew": shard_skew,
    }
