"""Exporters: JSONL step-log, Prometheus text format, end-of-run report.

Three consumers of the same registry, mirroring how TVM's autotuning
loop (PAPERS.md) is driven by measured structured run records rather
than log scraping:

* :func:`step_end` — the per-step emitter every training loop calls
  (Module.fit, ShardedTrainer.step, bench.py).  It advances the step
  counters/histograms and, when ``MXNET_TPU_TELEMETRY_JSONL`` names a
  file, appends ONE json line per step carrying the step time, that
  step's span timings, and a full counter/gauge snapshot.
* :func:`render_prom` — Prometheus text exposition of every metric,
  served by :func:`start_http_server` (``MXNET_TPU_TELEMETRY_PORT``).
* :func:`report` — the end-of-run dict (step-time percentiles,
  throughput, compile count/time, per-phase breakdown) that
  ``bench.py`` embeds in its ``BENCH_*.json`` output.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from .catalog import COUNTER, GAUGE, HISTOGRAM
from .registry import REGISTRY, counter, gauge, histogram
from . import compile as compile_mod
from . import distview as distview_mod
from . import flight
from . import ioview as ioview_mod
from . import memory as memory_mod
from . import slo as slo_mod
from . import tracing as tracing_mod
from .spans import drain_step_spans

__all__ = ["step_end", "jsonl_event", "render_prom", "report",
           "start_http_server", "jsonl_path", "env_port", "reset",
           "reset_steps"]

# retained step durations for percentiles (bounded: ~12h at 10 steps/s)
_MAX_DURS = 500_000
_lock = threading.Lock()
_step_durs = deque(maxlen=_MAX_DURS)
_jsonl = {"path": None, "fh": None}
# compile count/time already attributed by the first-call heuristic in
# windows discarded by reset_steps() (no jax.monitoring listener only)
_heur_carry = {"count": 0, "time": 0.0}
# counter snapshot at the previous step boundary (flight-event deltas)
_last_counters = {}


def jsonl_path():
    """Current step-log destination (``MXNET_TPU_TELEMETRY_JSONL``), or
    None when the step-log is off."""
    return os.environ.get("MXNET_TPU_TELEMETRY_JSONL") or None


# rank in a launch.py job (MXNET_TPU_PROCESS_ID; 0 outside one) — ONE
# parser for the JSONL records, the /debug endpoint, and flight dumps
_proc_rank = distview_mod.rank


def env_port():
    """The metrics port this process should bind
    (``MXNET_TPU_TELEMETRY_PORT``; 0 = endpoint off).  Co-located
    ranks must not race to bind one fixed port, so the LOCAL launcher
    assigns each worker ``port+rank`` in its environment (and records
    the choice in its supervisor JSONL ``worker_start`` event); the
    ssh launcher — one rank per host, no collision — passes the
    configured port through unchanged."""
    try:
        port = int(os.environ.get("MXNET_TPU_TELEMETRY_PORT", "0"))
    except ValueError:
        return 0
    return max(0, port)


def _jsonl_handle():
    """Open/rotate/close the step-log handle to match the env var (a
    test or a launcher may change it mid-process).  An unwritable path
    disables the step-log with one warning — the observability layer
    must never kill the training loop it observes."""
    path = jsonl_path()
    if path != _jsonl["path"]:
        if _jsonl["fh"] is not None:
            try:
                _jsonl["fh"].close()
            except OSError:
                pass
        fh = None
        if path:
            try:
                fh = open(path, "a")
            except OSError as e:
                import logging
                logging.getLogger(__name__).warning(
                    "MXNET_TPU_TELEMETRY_JSONL=%r cannot be opened "
                    "(%s); step-log disabled for this run", path, e)
        # record the path even on failure so the open is not retried
        # (and the warning not repeated) on every subsequent step
        _jsonl["fh"] = fh
        _jsonl["path"] = path
    return _jsonl["fh"]


def step_end(samples=None, step_time=None, extra=None, count=1):
    """Mark ``count`` training steps complete (1 for ordinary loops;
    ``ShardedTrainer.run_steps`` passes its scan length, since the
    chain IS count full optimizer updates observed once from the host).

    ``samples``: samples PER STEP (feeds throughput); ``step_time``:
    host wall seconds per step (feeds the ``mxtpu_step_seconds``
    histogram and the percentile window); ``extra``: dict merged into
    the JSONL record (trainers attach e.g. the loss).  Emits ONE JSONL
    record per call — a ``count`` > 1 record carries the whole chain's
    span timings and says so via its ``count`` field.  Cheap when the
    JSONL is off: three counter updates."""
    count = max(1, int(count))
    counter("mxtpu_step_total").inc(count)
    if samples:
        counter("mxtpu_samples_total").inc(samples * count)
    if step_time is not None:
        h = histogram("mxtpu_step_seconds")
        for _ in range(count):
            h.observe(step_time)
        with _lock:
            _step_durs.extend([float(step_time)] * count)
    spans = drain_step_spans()
    # live HBM sample at the step boundary (inert on backends without
    # memory_stats): the gauges land in the JSONL snapshot below and in
    # any later flight dump
    memory_mod.sample_live_memory()
    step_no = int(counter("mxtpu_step_total").get())
    counters = REGISTRY.flat(kinds=(COUNTER,))
    with _lock:
        deltas = {k: v - _last_counters.get(k, 0)
                  for k, v in counters.items()
                  if v != _last_counters.get(k, 0)}
        _last_counters.clear()
        _last_counters.update(counters)
    # the input-pipeline view's per-step block (telemetry.ioview):
    # per-stage deltas + stall/starved + occupancy + iterator position,
    # on the MXNET_TPU_IOVIEW_EVERY cadence.  Runs even when the JSONL
    # is off — the call also ticks the window bottleneck classifier
    io_rec = ioview_mod.step_record()
    ev = {"step": step_no, "step_time_s": step_time, "samples": samples,
          "spans": spans, "counter_deltas": deltas}
    if count > 1:
        ev["count"] = count
    if extra and extra.get("segments"):
        # straggler-attribution split (distview): worth a ring slot so
        # a postmortem black box carries the last steps' segment shape
        ev["segments"] = extra["segments"]
    flight.record("step_end", **ev)
    # the SLO judge rides the step cadence: one clock read per step, a
    # full rule evaluation at most every MXNET_TPU_SLO_TICK_S
    slo_mod.on_step()
    with _lock:
        fh = _jsonl_handle()
        if fh is None:
            return
        rec = {
            "ts": round(time.time(), 6),
            "step": step_no,
            "rank": _proc_rank(),
            "step_time_s": step_time,
            "samples": samples,
            "spans": spans,
            "counters": counters,
            "gauges": REGISTRY.flat(kinds=(GAUGE,)),
        }
        if count > 1:
            rec["count"] = count
        if io_rec is not None:
            rec["io"] = io_rec
        if extra:
            rec.update(extra)
        fh.write(json.dumps(rec) + "\n")
        fh.flush()


def jsonl_event(event, **fields):
    """Append one NON-step event record to this rank's JSONL step-log
    (no-op returning False when the step-log is off).

    The record is ``{"ts", "rank", "event": <name>, ...fields}`` — no
    ``step`` key, so per-step consumers skip it, while the launch.py
    run aggregator (``telemetry.distview.RunAggregator``) passes it
    through into the ``mxtpu-run/1`` timeline as an ``event`` record.
    Elastic training uses this for ``reshard`` / ``rank_join`` /
    ``rank_leave`` breadcrumbs; fields must be JSON-serializable."""
    with _lock:
        fh = _jsonl_handle()
        if fh is None:
            return False
        rec = {"ts": round(time.time(), 6), "rank": _proc_rank(),
               "event": str(event)}
        rec.update(fields)
        try:
            fh.write(json.dumps(rec, default=repr) + "\n")
            fh.flush()
        except (OSError, ValueError):
            return False
        return True


# ------------------------------------------------------------- prometheus

def _escape(v):
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _fmt_labels(key, extra=None):
    parts = ['%s="%s"' % (k, _escape(v)) for k, v in key]
    if extra:
        parts.extend('%s="%s"' % (k, _escape(v))
                     for k, v in extra.items())
    return "{%s}" % ",".join(parts) if parts else ""


def _fmt_num(x):
    if x == float("inf"):
        return "+Inf"
    f = float(x)
    return repr(int(f)) if f == int(f) else repr(f)


def render_prom():
    """The registry in Prometheus text exposition format (v0.0.4):
    ``# HELP`` / ``# TYPE`` headers, cumulative ``_bucket`` series with
    ``le`` labels for histograms."""
    lines = []
    for name, m in sorted(REGISTRY.metrics().items()):
        samples = m.samples()
        if not samples:
            continue
        lines.append("# HELP %s %s" % (name, _escape(m.help)))
        lines.append("# TYPE %s %s" % (name, m.kind))
        for key, val in sorted(samples.items()):
            if m.kind == HISTOGRAM:
                cum = 0
                bounds = list(m.buckets) + [float("inf")]
                exemplars = val.get("exemplars") or {}
                for i, (ub, n) in enumerate(zip(bounds,
                                                val["buckets"])):
                    cum += n
                    line = "%s_bucket%s %s" % (
                        name, _fmt_labels(key, {"le": _fmt_num(ub)}),
                        cum)
                    ex = exemplars.get(i)
                    if ex is not None:
                        # OpenMetrics exemplar suffix: the bucket names
                        # a REAL trace a reader can pull up with
                        # tools/trace_top.py --trace <id>
                        line += ' # {trace_id="%s"} %s %s' \
                            % (ex[0], _fmt_num(ex[1]), ex[2])
                    lines.append(line)
                lines.append("%s_sum%s %s"
                             % (name, _fmt_labels(key),
                                _fmt_num(val["sum"])))
                lines.append("%s_count%s %s"
                             % (name, _fmt_labels(key), val["count"]))
            else:
                lines.append("%s%s %s" % (name, _fmt_labels(key),
                                          _fmt_num(val)))
    return "\n".join(lines) + "\n"


_server = {"httpd": None, "thread": None}


def start_http_server(port=None):
    """Serve ``render_prom()`` on ``/metrics`` from a daemon thread
    (stdlib only), plus the live-debug surface: ``/debug`` (JSON rank
    status) and ``POST /debug/capture`` (trigger an on-demand bounded
    profiler window + flight snapshot — see ``telemetry.distview``;
    refused with 403 unless ``MXNET_TPU_CAPTURE_DIR`` armed capture).
    ``port=None`` reads ``MXNET_TPU_TELEMETRY_PORT``
    (:func:`env_port`); 0 binds an
    ephemeral port.  Returns the server object (its
    ``server_address[1]`` is the bound port); idempotent per process.
    """
    if _server["httpd"] is not None:
        return _server["httpd"]
    if port is None:
        port = env_port()
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _Handler(BaseHTTPRequestHandler):
        def _send(self, body, ctype, status=200):
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path in ("/", "/metrics"):
                self._send(render_prom().encode("utf-8"),
                           "text/plain; version=0.0.4; charset=utf-8")
                return
            if self.path.rstrip("/") == "/debug":
                from . import distview
                status = {
                    "rank": _proc_rank(),
                    "pid": os.getpid(),
                    "step": int(counter("mxtpu_step_total").get()),
                    "capture": distview.capture_status(),
                }
                self._send(json.dumps(status, default=repr)
                           .encode("utf-8"), "application/json")
                return
            if self.path.rstrip("/") == "/debug/capture":
                # a state change (profiler overhead + disk writes):
                # POST only, and only when the operator armed capture
                self.send_error(405, "POST /debug/capture")
                return
            self.send_error(404)

        def do_POST(self):
            if self.path.rstrip("/") != "/debug/capture":
                self.send_error(404)
                return
            from . import distview
            if not distview.capture_dir():
                self._send(json.dumps(
                    {"started": False,
                     "reason": "MXNET_TPU_CAPTURE_DIR is not set"})
                    .encode("utf-8"), "application/json", status=403)
                return
            res = distview.capture_now(trigger="http")
            self._send(json.dumps(res).encode("utf-8"),
                       "application/json")

        def log_message(self, fmt, *args):
            pass   # scrapes must not spam the training log

    httpd = ThreadingHTTPServer(("0.0.0.0", int(port)), _Handler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True,
                         name="mxtpu-telemetry-http")
    t.start()
    _server["httpd"] = httpd
    _server["thread"] = t
    return httpd


# ----------------------------------------------------------------- report

def _percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def _heuristic_compiles(durs):
    """First-call-vs-steady-state estimate: steps whose wall time dwarfs
    the median are counted as compile-inflated, the excess over the
    median as compile time.  Used only when jax.monitoring is absent."""
    if len(durs) < 2:
        return 0, 0.0
    s = sorted(durs)
    p50 = _percentile(s, 0.50)
    thresh = max(4.0 * p50, p50 + 0.05)
    hits = [d for d in durs if d > thresh]
    return len(hits), sum(d - p50 for d in hits)


def report():
    """End-of-run summary dict: step count + step-time percentiles,
    throughput (samples/sec and records/sec over summed step time),
    compile count/time, per-phase span breakdown, and the full counter
    snapshot.  ``tools/bench.py`` embeds this in its JSON output."""
    with _lock:
        durs = list(_step_durs)
    sdurs = sorted(durs)
    total_time = sum(durs)
    steps = int(counter("mxtpu_step_total").get())
    samples = counter("mxtpu_samples_total").get()
    records = sum(counter("mxtpu_io_records_total").samples().values())

    if compile_mod.installed():
        compile_count = int(counter("mxtpu_compile_total").get())
        compile_time = counter("mxtpu_compile_seconds_total").get()
        compile_source = "jax.monitoring"
    else:
        compile_count, compile_time = _heuristic_compiles(durs)
        with _lock:
            compile_count += _heur_carry["count"]
            compile_time += _heur_carry["time"]
        compile_source = "heuristic"

    phases = {}
    for key, val in histogram("mxtpu_span_seconds").samples().items():
        name = dict(key).get("span", "?")
        phases[name] = {
            "count": val["count"],
            "total_s": round(val["sum"], 6),
            "mean_s": round(val["sum"] / max(1, val["count"]), 6),
        }

    return {
        "steps": steps,
        "step_time_s": {
            "p50": round(_percentile(sdurs, 0.50), 6),
            "p90": round(_percentile(sdurs, 0.90), 6),
            "p99": round(_percentile(sdurs, 0.99), 6),
            "mean": round(total_time / len(durs), 6) if durs else 0.0,
            "min": round(sdurs[0], 6) if sdurs else 0.0,
            "max": round(sdurs[-1], 6) if sdurs else 0.0,
        },
        "throughput": {
            "samples_per_sec": round(samples / total_time, 3)
            if total_time else 0.0,
            "records_per_sec": round(records / total_time, 3)
            if total_time else 0.0,
        },
        "compile": {
            "count": compile_count,
            "total_s": round(float(compile_time), 6),
            "source": compile_source,
        },
        "phases": phases,
        "memory": {
            "plans": memory_mod.plans_dict(),
            "live": memory_mod.sample_live_memory(),
        },
        "counters": REGISTRY.flat(kinds=(COUNTER,)),
    }


def reset_steps():
    """Clear only the per-step window — step/samples counters, the
    step-time histogram/percentiles, the span histogram and per-step
    accumulator — keeping process-lifetime counters (compile, IO,
    kvstore, resilience) intact.  ``bench.py`` calls this after its
    warmup/compile steps so the reported percentiles and throughput
    cover exactly the timed loop, while compile accounting still spans
    the whole process (the heuristic fallback carries the discarded
    window's compile attribution forward)."""
    drain_step_spans()
    counter("mxtpu_step_total")._clear()
    counter("mxtpu_samples_total")._clear()
    histogram("mxtpu_step_seconds")._clear()
    histogram("mxtpu_span_seconds")._clear()
    with _lock:
        if not compile_mod.installed() and _step_durs:
            # without jax.monitoring the first-call heuristic is the
            # only compile signal, and it lives in the durations being
            # discarded — bank its estimate so report() keeps it
            c, t = _heuristic_compiles(list(_step_durs))
            _heur_carry["count"] += c
            _heur_carry["time"] += t
        _step_durs.clear()


def reset():
    """Clear every sample, the percentile window, the per-step span
    accumulator, the flight ring + memory-plan registry, and the
    step-log handle (the env var is re-read on the next step).  Metric
    objects and cached label children stay valid."""
    REGISTRY.reset()
    drain_step_spans()
    flight.clear()
    ioview_mod.reset()
    memory_mod.clear_plans()
    from . import costdb as costdb_mod
    costdb_mod.reset()
    from . import numerics as numerics_mod
    numerics_mod.reset()
    slo_mod.reset()
    tracing_mod.reset()
    with _lock:
        _step_durs.clear()
        _last_counters.clear()
        _heur_carry["count"] = 0
        _heur_carry["time"] = 0.0
        if _jsonl["fh"] is not None:
            try:
                _jsonl["fh"].close()
            except OSError:
                pass
        _jsonl["fh"] = None
        _jsonl["path"] = None
    _init_env_state()


def _init_env_state():
    """Seed env-derived gauges: the watchdog restart attempt this
    process runs under (tools/launch.py resume contract)."""
    try:
        restarts = int(os.environ.get("MXNET_TPU_RESTART_COUNT", "0"))
    except ValueError:
        restarts = 0
    gauge("mxtpu_watchdog_restarts").set(restarts)
