"""HBM accounting: static memory plans, live stats, OOM forensics.

"A Learned Performance Model for TPUs" (PAPERS.md) treats per-program
memory and FLOP/byte cost as primary observables, and XLA already
computes both on every compile — ``compiled.memory_analysis()`` and
``compiled.cost_analysis()``.  This module promotes them into the
telemetry catalog and builds the OOM story on top:

* **version-tolerant accessors** — :func:`memory_analysis_of` /
  :func:`cost_analysis_of` normalize the jax 0.4.x API drift (attribute
  objects vs dicts, list-of-dict cost tables, ``None`` backends) into
  plain dicts; ``examples/memcost/memcost.py`` and
  ``tools/profile_step.py`` use them instead of private copies;
* **memory plans** — :func:`plan_of` + :func:`register_plan` record a
  compiled program's argument/output/temp/generated-code bytes and
  FLOPs/bytes-accessed in the ``mxtpu_memory_plan_bytes`` /
  ``mxtpu_program_flops`` / ``mxtpu_program_bytes_accessed`` gauges and
  a process-wide plan registry the exporters and the flight recorder
  snapshot;
* **live stats** — :func:`sample_live_memory` reads
  ``device.memory_stats()`` (bytes_in_use / peak_bytes_in_use; absent
  on CPU) into the ``mxtpu_hbm_*`` gauges at step boundaries;
* **budget check** — :func:`check_budget` compares a plan against
  device capacity BEFORE the program is dispatched and raises a
  descriptive :class:`~mxnet_tpu.base.MXNetError` with the per-category
  breakdown and remat/batch-size advice, instead of burning a
  dispatch-then-OOM cycle;
* **OOM annotation** — :func:`annotate_oom` catches a backend
  ``RESOURCE_EXHAUSTED`` and re-raises :class:`HbmOomError` carrying
  the plan, the live-bytes snapshot, and the largest live buffers;
* **planned dispatch** — :func:`planned_executable` AOT-compiles a
  jitted function once (no double compile: callers dispatch through
  the returned executable), registering its plan and budget-checking
  it before the first execution.

Knobs: ``MXNET_TPU_MEMORY_BUDGET`` (fraction of capacity the static
plan may use, default 1.0; <=0 disables), ``MXNET_TPU_HBM_LIMIT_BYTES``
(capacity override for backends without ``memory_stats``, e.g. tests
on CPU).  See docs/api/telemetry.md.
"""
from __future__ import annotations

import os
import threading

from ..base import MXNetError
from .registry import counter, gauge
from . import flight

__all__ = [
    "HbmOomError", "MemoryPlan",
    "memory_analysis_of", "cost_analysis_of", "plan_of",
    "register_plan", "get_plan", "plans_dict", "clear_plans",
    "device_memory_stats", "device_capacity_bytes", "sample_live_memory",
    "budget_fraction", "check_budget", "planned_executable",
    "dispatch_planned",
    "is_oom_error", "annotate_oom", "largest_live_buffers",
]

#: plan byte categories, in breakdown display order
CATEGORIES = ("argument", "output", "temp", "alias", "generated_code")


class HbmOomError(MXNetError):
    """A backend ``RESOURCE_EXHAUSTED`` annotated with the static
    memory plan, the live-bytes snapshot, and the largest live buffers
    (raised by :func:`annotate_oom`; the original error is chained)."""


# ------------------------------------------------- version-tolerant accessors

def memory_analysis_of(compiled):
    """``compiled.memory_analysis()`` as a plain dict of bytes per
    category (:data:`CATEGORIES` keys), or None when the backend does
    not report one.  Tolerates the jax 0.4.x drift: attribute objects
    (``CompiledMemoryStats`` with ``*_size_in_bytes``), plain dicts,
    and ``None`` returns."""
    fn = getattr(compiled, "memory_analysis", None)
    if fn is None:
        return None
    try:
        ma = fn()
    except Exception:  # mxlint: allow-broad-except(memory_analysis availability and failure modes are backend-dependent; absence degrades to no plan, never to a crash)
        return None
    if ma is None:
        return None
    if isinstance(ma, dict):
        src = dict(ma)
    else:
        src = {c: getattr(ma, "%s_size_in_bytes" % c, None)
               for c in CATEGORIES}
    out = {}
    for c in CATEGORIES:
        v = src.get(c, src.get("%s_size_in_bytes" % c))
        if v is not None:
            out[c] = int(v)
    return out or None


def cost_analysis_of(compiled):
    """``compiled.cost_analysis()`` as a plain dict (``flops``,
    ``bytes_accessed``, ``transcendentals`` where reported), or None.
    Tolerates list-of-dict (jax <= 0.4.x), plain-dict (0.5+), and
    absent/None returns."""
    fn = getattr(compiled, "cost_analysis", None)
    if fn is None:
        return None
    try:
        ca = fn()
    except Exception:  # mxlint: allow-broad-except(cost_analysis availability and failure modes are backend-dependent; absence degrades to no plan, never to a crash)
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    out = {}
    for key, names in (("flops", ("flops",)),
                       ("bytes_accessed", ("bytes accessed",
                                           "bytes_accessed")),
                       ("transcendentals", ("transcendentals",))):
        for n in names:
            if n in ca:
                out[key] = float(ca[n])
                break
    return out or None


# ----------------------------------------------------------------- the plan

class MemoryPlan:
    """One compiled program's static footprint: bytes per category from
    ``memory_analysis()`` plus FLOPs / bytes-accessed from
    ``cost_analysis()``."""

    def __init__(self, program, memory=None, cost=None):
        self.program = program
        self.memory = dict(memory or {})
        self.cost = dict(cost or {})

    @property
    def total_bytes(self):
        """Peak HBM the program needs live at once: arguments + outputs
        + temporaries + generated code, minus aliased (donated) bytes
        counted on both sides."""
        m = self.memory
        total = sum(m.get(c, 0) for c in
                    ("argument", "output", "temp", "generated_code"))
        return max(0, total - m.get("alias", 0))

    def as_dict(self):
        d = {"program": self.program,
             "total_bytes": self.total_bytes}
        d.update({"%s_bytes" % c: self.memory[c] for c in CATEGORIES
                  if c in self.memory})
        d.update(self.cost)
        return d

    def breakdown(self):
        """Human-readable per-category byte breakdown, one line."""
        parts = ["%s=%s" % (c, _fmt_bytes(self.memory[c]))
                 for c in CATEGORIES if c in self.memory]
        parts.append("total=%s" % _fmt_bytes(self.total_bytes))
        if "flops" in self.cost:
            parts.append("flops=%.3g" % self.cost["flops"])
        return ", ".join(parts)

    def __repr__(self):
        return "MemoryPlan(%r: %s)" % (self.program, self.breakdown())


def _fmt_bytes(n):
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return ("%.1f %s" if unit != "B" else "%.0f %s") % (n, unit)
        n /= 1024.0


def plan_of(compiled, program):
    """Build a :class:`MemoryPlan` from a compiled executable, or None
    when the backend reports neither memory nor cost analysis."""
    mem = memory_analysis_of(compiled)
    cost = cost_analysis_of(compiled)
    if mem is None and cost is None:
        return None
    return MemoryPlan(program, memory=mem, cost=cost)


_plans_lock = threading.Lock()
_PLANS = {}
_STATIC = {}   # program -> analysis.memlive prediction dict


def note_static_prediction(program, info):
    """Record a bind-time static liveness prediction for ``program``
    (pushed by :func:`mxnet_tpu.analysis.memlive.record_prediction` —
    the dependency points this way so the telemetry layer never imports
    the analysis package).  The budget check and :class:`annotate_oom`
    fold it into their reports, and :func:`register_plan` publishes the
    MXG018 drift gauge once both peaks are known."""
    with _plans_lock:
        _STATIC[program] = dict(info)
        plan = _PLANS.get(program)
    if plan is not None:
        _publish_drift(program, info, plan)


def static_prediction(program):
    """The recorded static prediction for a program name, or None."""
    with _plans_lock:
        return _STATIC.get(program)


def _publish_drift(program, info, plan):
    """``mxtpu_memlive_drift_ratio{program}`` — (static - plan)/plan."""
    peak = int(info.get("peak_bytes") or 0)
    total = int(plan.total_bytes or 0)
    if total > 0:
        gauge("mxtpu_memlive_drift_ratio").labels(program=program).set(
            (peak - total) / float(total))


def register_plan(plan):
    """Record a plan in the process registry and the catalog gauges
    (``mxtpu_memory_plan_bytes{program,category}`` per category plus
    ``total``, ``mxtpu_program_flops``, ``mxtpu_program_bytes_accessed``)
    and note it in the flight ring.  Re-registering a program name
    overwrites (a rebind IS a new plan)."""
    with _plans_lock:
        _PLANS[plan.program] = plan
        static = _STATIC.get(plan.program)
    if static is not None:
        _publish_drift(plan.program, static, plan)
    g = gauge("mxtpu_memory_plan_bytes")
    for c in CATEGORIES:
        if c in plan.memory:
            g.labels(program=plan.program, category=c).set(plan.memory[c])
    g.labels(program=plan.program, category="total").set(plan.total_bytes)
    if "flops" in plan.cost:
        gauge("mxtpu_program_flops").labels(
            program=plan.program).set(plan.cost["flops"])
    if "bytes_accessed" in plan.cost:
        gauge("mxtpu_program_bytes_accessed").labels(
            program=plan.program).set(plan.cost["bytes_accessed"])
    flight.record("memory_plan", program=plan.program,
                  total_bytes=plan.total_bytes, **plan.cost)
    return plan


def get_plan(program):
    """The registered plan for a program name, or None."""
    with _plans_lock:
        return _PLANS.get(program)


def plans_dict():
    """{program: plan dict} snapshot — the report()/flight-dump block."""
    with _plans_lock:
        return {name: p.as_dict() for name, p in sorted(_PLANS.items())}


def clear_plans():
    """Forget every registered plan and static prediction
    (telemetry.reset calls this)."""
    with _plans_lock:
        _PLANS.clear()
        _STATIC.clear()


# ------------------------------------------------------------ live memory

def device_memory_stats(device=None):
    """``device.memory_stats()`` as a dict, or None when the backend
    does not report live memory (CPU, some PJRT plugins).  Default
    device: first local device."""
    try:
        if device is None:
            import jax
            devs = jax.local_devices()
            if not devs:
                return None
            device = devs[0]
        stats = getattr(device, "memory_stats", None)
        stats = stats() if callable(stats) else None
    except Exception:  # mxlint: allow-broad-except(memory_stats is backend-dependent and may raise on remote/relayed devices; live sampling degrades to None, never to a crash)
        return None
    return dict(stats) if stats else None


def device_capacity_bytes(device=None):
    """Usable device memory in bytes: ``memory_stats()['bytes_limit']``
    when the backend reports it, else the ``MXNET_TPU_HBM_LIMIT_BYTES``
    override (tests, CPU), else None (capacity unknown — the budget
    check stays inert)."""
    stats = device_memory_stats(device)
    if stats and stats.get("bytes_limit"):
        return int(stats["bytes_limit"])
    try:
        env = int(os.environ.get("MXNET_TPU_HBM_LIMIT_BYTES", "0"))
    except ValueError:
        env = 0
    return env or None


def sample_live_memory():
    """Read every local device's ``memory_stats`` into the
    ``mxtpu_hbm_bytes_in_use`` / ``mxtpu_hbm_peak_bytes`` gauges
    (label: ``platform:id``).  Returns the first device's stats dict,
    or None when no backend reports live memory.  Called at step
    boundaries by ``telemetry.step_end``; cheap when unsupported (one
    None-returning call per device)."""
    try:
        import jax
        devs = jax.local_devices()
    except Exception:  # mxlint: allow-broad-except(device enumeration can fail during interpreter teardown or before backend init; sampling degrades to None)
        return None
    first = None
    in_use = gauge("mxtpu_hbm_bytes_in_use")
    peak = gauge("mxtpu_hbm_peak_bytes")
    for d in devs:
        stats = device_memory_stats(d)
        if not stats:
            continue
        label = "%s:%d" % (getattr(d, "platform", "dev"),
                           getattr(d, "id", 0))
        if "bytes_in_use" in stats:
            in_use.labels(device=label).set(stats["bytes_in_use"])
        if "peak_bytes_in_use" in stats:
            peak.labels(device=label).set(stats["peak_bytes_in_use"])
        if first is None:
            first = stats
    return first


# ------------------------------------------------------------ budget check

def budget_fraction():
    """``MXNET_TPU_MEMORY_BUDGET``: fraction of device capacity the
    static plan may use before dispatch raises (default 1.0; a value
    <= 0 disables the check)."""
    try:
        return float(os.environ.get("MXNET_TPU_MEMORY_BUDGET", "1.0"))
    except ValueError:
        return 1.0


def check_budget(plan, capacity=None, fraction=None, device=None):
    """Raise a descriptive :class:`~mxnet_tpu.base.MXNetError` when the
    plan's total bytes exceed ``fraction * capacity`` — BEFORE the
    program is dispatched, so the failure costs no device OOM cycle.
    Inert when capacity is unknown or the check is disabled."""
    if fraction is None:
        fraction = budget_fraction()
    if fraction <= 0 or plan is None:
        return
    if capacity is None:
        capacity = device_capacity_bytes(device)
    if not capacity:
        return
    budget = int(capacity * fraction)
    if plan.total_bytes <= budget:
        return
    flight.record("budget_exceeded", program=plan.program,
                  total_bytes=plan.total_bytes, budget_bytes=budget)
    raise MXNetError(
        "memory budget check: compiled program %r needs %s of device "
        "memory but only %s is budgeted (capacity %s x "
        "MXNET_TPU_MEMORY_BUDGET=%.2f).  Plan breakdown: %s.%s  "
        "Options: reduce the per-device batch size, enable "
        "rematerialization (MXNET_BACKWARD_DO_MIRROR=1), shard more "
        "state over the mesh (tp_rules / pipeline_stages), or raise "
        "the budget fraction if the headroom is intentional."
        % (plan.program, _fmt_bytes(plan.total_bytes),
           _fmt_bytes(budget), _fmt_bytes(capacity), fraction,
           plan.breakdown(), _static_summary(plan.program)))


def _static_summary(program):
    """One sentence comparing the bind-time static prediction with the
    registered XLA plan — both peaks come from the same predictor
    (analysis.memlive), so budget failures name where the bytes go."""
    info = static_prediction(program)
    if not info:
        return ""
    parts = ["  Static liveness prediction: peak %s at %s"
             % (_fmt_bytes(info.get("peak_bytes", 0)),
                info.get("peak_node", "?"))]
    bd = info.get("breakdown") or {}
    cats = ", ".join("%s=%s" % (c, _fmt_bytes(v))
                     for c, v in bd.items() if v)
    if cats:
        parts.append(" (%s)" % cats)
    remats = info.get("remat_candidates") or ()
    if remats:
        r = remats[0]
        parts.append("; top remat candidate %s frees %s at peak"
                     % (r.get("node"),
                        _fmt_bytes(r.get("bytes_freed", 0))))
    zero = int(info.get("zero_saving_per_rank") or 0)
    if zero > 0:
        parts.append("; ZeRO-sharding replicated optimizer state "
                     "would save %s per rank" % _fmt_bytes(zero))
    return "".join(parts) + "."


# ------------------------------------------------------- planned dispatch

def planned_executable(program, fn, args):
    """AOT-compile a jitted function for ``args`` ONCE, register its
    memory plan, budget-check it, and return the executable to dispatch
    through (callers cache it — jax shares no compile cache between
    ``lower().compile()`` and ordinary jit calls, so dispatching the
    returned object is what keeps this a single compile).

    ``fn`` may already be an AOT ``Compiled`` (the trainer's
    auto_layouts path): its analyses are read directly.  Anything that
    prevents planning (no ``lower``, lowering failure, a backend
    without analyses) degrades to returning ``fn`` unchanged — the
    plan is observability, only the budget check is allowed to raise."""
    if hasattr(fn, "memory_analysis"):
        compiled = fn
    else:
        lower = getattr(fn, "lower", None)
        if lower is None:
            return fn
        try:
            compiled = lower(*args).compile()
        except MXNetError:
            raise
        except Exception as e:  # mxlint: allow-broad-except(AOT lowering is an optimization for plan capture; any backend/tracing failure falls back to the ordinary jit dispatch path)
            import logging
            logging.getLogger(__name__).debug(
                "planned_executable(%s): AOT lowering unavailable (%s: "
                "%s); dispatching via jit without a memory plan",
                program, type(e).__name__, e)
            return fn
    plan = plan_of(compiled, program)
    if plan is not None:
        register_plan(plan)
        check_budget(plan)
    return compiled


def dispatch_planned(cache, program, fn, args):
    """Dispatch ``fn(*args)`` through its cached AOT executable —
    THE shared hot-path pattern for Executor and ShardedTrainer.

    First call per ``(program, id(fn))``: AOT-compile via
    :func:`planned_executable` (plan registered + budget-checked) and
    cache the executable in the caller-owned ``cache`` dict.  If the
    cached executable later rejects the arguments (aval drift, e.g. a
    partial tail batch), the entry is permanently downgraded to the jit
    wrapper for that fn — jax's own cache then serves every shape with
    no per-call raise/catch — and the registered plan keeps describing
    the first-seen (steady-state) program."""
    key = (program, id(fn))
    exe = cache.get(key)
    if exe is None:
        exe = planned_executable(program, fn, args)
        cache[key] = exe
    try:
        return exe(*args)
    except TypeError:
        if exe is fn:
            raise
        cache[key] = fn
        flight.record("plan_fallback", program=program)
        return fn(*args)


# ----------------------------------------------------------- OOM forensics

def is_oom_error(exc):
    """True when an exception is a backend (device) out-of-memory: an
    ``XlaRuntimeError``-shaped error whose message carries
    ``RESOURCE_EXHAUSTED`` / out-of-memory markers.  Matched on the
    message, not the type — the concrete error class moved between
    jaxlib versions.  A host-side :class:`MemoryError` is deliberately
    NOT matched: annotating host-RAM exhaustion with HBM advice would
    send the postmortem in the wrong direction."""
    if isinstance(exc, MemoryError):
        return False
    msg = str(exc)
    return "RESOURCE_EXHAUSTED" in msg or "out of memory" in msg.lower()


def largest_live_buffers(n=8):
    """The ``n`` largest live device arrays as
    ``(nbytes, shape, dtype)`` tuples, largest first — the "what is
    actually occupying HBM" part of an OOM report.  Empty on API
    drift."""
    try:
        import jax
        arrs = jax.live_arrays()
    except Exception:  # mxlint: allow-broad-except(live_arrays is a debugging API that may be absent or raise mid-teardown; forensics degrade to an empty list)
        return []
    sized = []
    for a in arrs:
        try:
            sized.append((int(a.nbytes), tuple(a.shape), str(a.dtype)))
        except Exception:  # mxlint: allow-broad-except(deleted/donated arrays raise on attribute access while still listed; skip them)
            continue
    sized.sort(key=lambda t: -t[0])
    return sized[:n]


class annotate_oom:
    """Context manager around a dispatch: a backend
    ``RESOURCE_EXHAUSTED`` is re-raised as :class:`HbmOomError` whose
    message carries the program's static memory plan, the live-bytes
    snapshot, and the largest live buffers; the event is counted
    (``mxtpu_oom_total``) and recorded in the flight ring.  Non-OOM
    errors pass through untouched.

    ::

        with memory.annotate_oom("trainer.step"):
            out = compiled(*args)
    """

    def __init__(self, program):
        self.program = program

    def __enter__(self):
        return self

    def __exit__(self, etype, exc, tb):
        if exc is None or isinstance(exc, HbmOomError) \
                or not is_oom_error(exc):
            return False
        counter("mxtpu_oom_total").labels(program=self.program).inc()
        plan = get_plan(self.program)
        live = device_memory_stats()
        buffers = largest_live_buffers()
        flight.record(
            "oom", program=self.program,
            plan_total_bytes=plan.total_bytes if plan else None,
            bytes_in_use=(live or {}).get("bytes_in_use"),
            peak_bytes_in_use=(live or {}).get("peak_bytes_in_use"))
        lines = [
            "device out of memory (RESOURCE_EXHAUSTED) while running "
            "%r." % self.program,
        ]
        if plan is not None:
            lines.append("static memory plan: %s." % plan.breakdown())
        else:
            lines.append("static memory plan: none registered for this "
                         "program.")
        if live:
            lines.append(
                "live device memory: bytes_in_use=%s, peak=%s, limit=%s."
                % (_fmt_bytes(live.get("bytes_in_use", 0)),
                   _fmt_bytes(live.get("peak_bytes_in_use", 0)),
                   _fmt_bytes(live["bytes_limit"])
                   if live.get("bytes_limit") else "unknown"))
        else:
            lines.append("live device memory: backend reports no "
                         "memory_stats.")
        if buffers:
            lines.append("largest live buffers: %s." % "; ".join(
                "%s %s %s" % (_fmt_bytes(b), shape, dtype)
                for b, shape, dtype in buffers))
        static = _static_summary(self.program)
        if static:
            lines.append(static.strip())
        lines.append(
            "Advice: reduce the per-device batch size, enable "
            "rematerialization (MXNET_BACKWARD_DO_MIRROR=1), or shard "
            "more state (tp_rules / pipeline_stages).  A flight-recorder "
            "dump of the final seconds is written when "
            "MXNET_TPU_FLIGHT_DIR is set.")
        raise HbmOomError(" ".join(lines)) from exc
