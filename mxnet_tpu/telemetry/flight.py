"""Flight recorder: a black box for TPU runs.

A bounded, thread-safe ring buffer of recent structured events — step
begin/end, span timings, compile events, kvstore traffic, fault
injections, memory plans, counter deltas — that is cheap enough to run
always and is dumped to a JSON "black box" file when the run dies:
on :class:`~mxnet_tpu.base.MXNetError` in a guarded training seam, on
an annotated ``RESOURCE_EXHAUSTED`` (see :mod:`.memory`), on SIGTERM
preemption (:meth:`ShardedTrainer.install_preemption_handler`), and on
any uncaught exception (the excepthook installed when
``MXNET_TPU_FLIGHT_DIR`` is set).  ``tools/launch.py``'s watchdog
collects dumps left behind by a dead rank and records their paths in
the supervisor JSONL event; ``tools/flight_read.py`` pretty-prints a
dump.

Recording is always on (the ring lives in memory and costs one lock +
dict append per event); *dumping* requires ``MXNET_TPU_FLIGHT_DIR`` to
name a writable directory — without it :func:`dump` is a no-op
returning ``None``, so tests and casual runs never scatter files.

Dump schema (``"schema": "mxtpu-flight/1"``)::

    {
      "schema": "mxtpu-flight/1",
      "reason": "oom" | "error" | "sigterm" | "crash" | <caller string>,
      "ts": <unix seconds>, "pid": ..., "host": ...,
      "rank": <MXNET_TPU_PROCESS_ID>,
      "restart_count": <MXNET_TPU_RESTART_COUNT>,
      "error": <str or null>,
      "events": [{"seq": n, "ts": ..., "kind": ..., ...fields}, ...],
      "counters": {...}, "gauges": {...},     # registry snapshot
      "memory_plans": {program: plan dict},   # telemetry.memory
      "live_memory": {...} | null             # device.memory_stats
    }
"""
from __future__ import annotations

import json
import os
import socket
import sys
import threading
import time
from collections import deque

from ..base import MXNetError
from . import tracing
from .catalog import COUNTER, GAUGE
from .registry import REGISTRY, counter

__all__ = ["FlightRecorder", "RECORDER", "record", "events", "clear",
           "dump", "dump_dir", "capacity", "crash_guard",
           "install_excepthook"]

DEFAULT_CAPACITY = 512


def dump_dir():
    """Dump destination directory (``MXNET_TPU_FLIGHT_DIR``), or None
    when black-box dumping is off."""
    return os.environ.get("MXNET_TPU_FLIGHT_DIR") or None


def capacity():
    """Ring capacity (``MXNET_TPU_FLIGHT_EVENTS``, default 512)."""
    try:
        n = int(os.environ.get("MXNET_TPU_FLIGHT_EVENTS",
                               str(DEFAULT_CAPACITY)))
    except ValueError:
        n = DEFAULT_CAPACITY
    return max(8, n)


class FlightRecorder:
    """Bounded ring of structured events + the dump writer.

    One module-level instance (:data:`RECORDER`) serves the process;
    embedders and tests may build private ones.  All methods are
    thread-safe; ``record`` is the hot path (one lock, one deque
    append, one counter inc).
    """

    def __init__(self, capacity_=None):
        self._lock = threading.Lock()
        self._events = deque(maxlen=capacity_ or capacity())
        self._seq = 0
        self._dumps = 0

    # ----------------------------------------------------------- record
    def record(self, kind, **fields):
        """Append one event; returns its sequence number.  ``fields``
        must be JSON-serializable (the dump writer falls back to repr
        for anything that is not)."""
        ev = {"kind": str(kind), "ts": round(time.time(), 6)}
        ev.update(fields)
        if "trace_id" not in ev:
            # cross-reference: events recorded under an active trace
            # carry its id, so the flight ring and trace files join
            ctx = tracing.current()
            if ctx is not None:
                ev["trace_id"] = ctx.trace_id
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            self._events.append(ev)
        counter("mxtpu_flight_events_total").labels(kind=str(kind)).inc()
        return ev["seq"]

    def events(self):
        """Snapshot of the ring, oldest first (copies, safe to mutate)."""
        with self._lock:
            return [dict(e) for e in self._events]

    def clear(self):
        with self._lock:
            self._events.clear()
            self._seq = 0

    def __len__(self):
        with self._lock:
            return len(self._events)

    # ------------------------------------------------------------- dump
    def dump(self, reason, path=None, error=None, directory=None):
        """Write the black box.  Returns the written path, or None when
        dumping is disabled (no ``path``, no ``directory``, and
        ``MXNET_TPU_FLIGHT_DIR`` unset).  Never raises: the recorder
        must not replace the error it is documenting — write failures
        are logged and swallowed."""
        if path is None:
            directory = directory or dump_dir()
            if not directory:
                return None
            with self._lock:
                self._dumps += 1
                n = self._dumps
            path = os.path.join(
                directory, "flight-%d-%03d-%s.json"
                % (os.getpid(), n, _slug(reason)))
        tmp = "%s.tmp.%d" % (path, os.getpid())
        try:
            doc = self._payload(reason, error)
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True,
                          default=repr)
            os.replace(tmp, path)
        except Exception as e:  # mxlint: allow-broad-except(the black-box writer runs while the error it documents is propagating — a payload/serialization/IO failure here must never replace that error)
            import logging
            logging.getLogger(__name__).warning(
                "flight recorder: cannot write black box %r: %s", path, e)
            try:
                os.remove(tmp)
            except OSError:
                pass
            return None
        counter("mxtpu_flight_dumps_total").labels(
            reason=_slug(reason)).inc()
        return path

    def _payload(self, reason, error):
        from . import memory as memory_mod
        try:
            restart = int(os.environ.get("MXNET_TPU_RESTART_COUNT", "0"))
        except ValueError:
            restart = 0
        from .distview import rank as _rank
        return {
            "schema": "mxtpu-flight/1",
            "reason": str(reason),
            "ts": round(time.time(), 6),
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "rank": _rank(),
            "restart_count": restart,
            "error": None if error is None else str(error),
            "events": self.events(),
            "counters": REGISTRY.flat(kinds=(COUNTER,)),
            "gauges": REGISTRY.flat(kinds=(GAUGE,)),
            "memory_plans": memory_mod.plans_dict(),
            "live_memory": memory_mod.device_memory_stats(),
        }


def _slug(reason):
    return "".join(c if c.isalnum() or c in "-_" else "-"
                   for c in str(reason))[:40] or "dump"


#: the process-wide recorder (module-level helpers below)
RECORDER = FlightRecorder()


def record(kind, **fields):
    """Record one event on the default recorder."""
    return RECORDER.record(kind, **fields)


def events():
    """Snapshot of the default recorder's ring, oldest first."""
    return RECORDER.events()


def clear():
    """Empty the default recorder's ring (telemetry.reset calls this)."""
    RECORDER.clear()


def dump(reason, path=None, error=None, directory=None):
    """Dump the default recorder — see :meth:`FlightRecorder.dump`."""
    return RECORDER.dump(reason, path=path, error=error,
                         directory=directory)


class crash_guard:
    """Context manager: on :class:`MXNetError` (fault injections, budget
    violations, annotated OOMs — anything the framework raises on
    purpose), record an ``error`` event and dump the black box, then
    re-raise unchanged.  Nested guards dump once: the innermost tags the
    exception and outer levels pass it through.

    ::

        with flight.crash_guard("trainer.step"):
            loss = step(...)
    """

    def __init__(self, site, recorder=None):
        self.site = site
        self._rec = recorder or RECORDER

    def __enter__(self):
        return self

    def __exit__(self, etype, exc, tb):
        if exc is None or not isinstance(exc, MXNetError):
            return False
        if getattr(exc, "_mxtpu_flight_dumped", False):
            return False
        try:
            exc._mxtpu_flight_dumped = True
        except AttributeError:
            pass
        from .memory import HbmOomError
        reason = "oom" if isinstance(exc, HbmOomError) else "error"
        self._rec.record("error", site=self.site,
                         error_type=type(exc).__name__,
                         message=str(exc)[:2000])
        self._rec.dump(reason, error=exc)
        return False


_hook_installed = [False]


def install_excepthook():
    """Chain a ``sys.excepthook`` that dumps the black box (reason
    ``crash``) on any uncaught exception, then delegates to the previous
    hook.  Installed automatically at import when
    ``MXNET_TPU_FLIGHT_DIR`` is set, so a worker that dies leaves a dump
    for the launch.py watchdog to collect.  Idempotent."""
    if _hook_installed[0]:
        return
    _hook_installed[0] = True
    prev = sys.excepthook

    def hook(etype, value, tb):
        if not getattr(value, "_mxtpu_flight_dumped", False):
            try:
                RECORDER.record("crash", error_type=etype.__name__,
                                message=str(value)[:2000])
                RECORDER.dump("crash", error=value)
            except Exception:  # mxlint: allow-broad-except(the excepthook must never mask the original crash with its own failure)
                pass
        prev(etype, value, tb)

    sys.excepthook = hook
