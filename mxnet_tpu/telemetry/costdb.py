"""Persistent op/block cost database: the perf ground truth layer.

ROADMAP item 2 (TVM-style autotuner + learned cost model,
arXiv:1802.04799, arXiv:2008.01040) needs *measured-not-inferred*
training data, and until now every measured signal was ephemeral —
spans die with the process, xprof captures are one-off files, and the
MemoryPlan flops/bytes gauges reset on restart.  This module joins the
three existing-but-disconnected signals into durable records:

* **measured wall time** — the span tracer's dispatch timing (sampled
  at the Executor/ShardedTrainer dispatch seam, synchronized via
  ``jax.block_until_ready`` so the number is device-complete, not
  async-dispatch time);
* **flops + bytes_accessed** — the PR 4 :mod:`.memory` accessors
  (``cost_analysis`` of the compiled program) for program records, and
  analytic shape-derived estimates for fused-block / Pallas-kernel
  records (registered at trace time, when the shapes are in hand);
* **block identity** — the PR 6 ``FusionPlan`` block kind plus the
  Pallas block configuration (``block_q``/``block_k``/``bm``), so the
  2176-style block-shape cliffs become queryable by (op, shape).

Each record derives **MFU** (``flops / wall_s / peak_flops``) and
**arithmetic intensity** (``flops / bytes_accessed``) against a
per-backend peak table (env-overridable ``MXNET_TPU_PEAK_FLOPS`` /
``MXNET_TPU_PEAK_BW``), yielding a roofline classification:
``bound="compute"`` when AI >= ridge (``peak_flops/peak_bw``), else
``"bandwidth"``.  Block wall time is *attributed*: the measured program
wall is split across the program's fused blocks proportionally to each
block's roofline-attainable time (``max(flops/peak_flops,
bytes/peak_bw)``), so bandwidth-bound blocks surface with exactly the
depressed MFU the roofline predicts — the targeting input
``tools/perf_top.py`` ranks for the future autotuner.

**Collection flow** (all observability — a costdb failure never fails
the dispatch it observes):

1. trace time: :func:`note_block` (``analysis.fusion.apply_block``) and
   :func:`note_kernel` (``ops/pallas_kernels.py``, ``ops/fused.py``)
   register *pending signatures* with shapes/dtypes/flops estimates;
2. dispatch time: :func:`begin_dispatch`/:func:`end_dispatch` around
   ``Executor._dispatch`` / ``ShardedTrainer._dispatch_planned`` bind
   pending signatures to the program whose compile traced them, and on
   *sampled* dispatches (``MXNET_TPU_COSTDB_SAMPLE``, default every
   16th; the first post-compile dispatch is always sampled; ``0``
   disables measurement) measure a synchronized wall time and record
   the program + its blocks/kernels;
3. persistence: :func:`flush` appends the aggregated records as JSONL
   (schema ``mxtpu-costdb/1``, one record per line) under the
   ``MXNET_TPU_COSTDB`` directory (auto-flushed at interpreter exit
   when the knob is set) and notes a ``costdb_flush`` flight event;
   :func:`read_records` loads/validates a file or directory back.

Metrics: ``mxtpu_block_mfu{block}`` (latest derived MFU per fused
block / kernel) and ``mxtpu_costdb_records_total{kind}`` (records
created in the in-memory database).

Consumers: ``tools/perf_top.py`` (worst-MFU ranking with bound-ness),
``bench.py`` (roll-up embedded in BENCH JSON via :func:`summary`),
``ShardedTrainer.cost_summary()``.  See docs/api/telemetry.md.
"""
from __future__ import annotations

import hashlib
import itertools
import json
import os
import threading
import time

__all__ = [
    "SCHEMA", "CostDB", "DB",
    "db_dir", "sample_every", "backend_name",
    "peak_flops", "peak_bandwidth", "roofline",
    "note_block", "note_kernel", "begin_dispatch", "end_dispatch",
    "bind_pending", "next_scope", "drop_scope",
    "record", "records", "summary", "flush", "reset", "read_records",
]

SCHEMA = "mxtpu-costdb/1"

#: per-backend (peak_flops/s, peak_bytes/s) — deliberately conservative
#: "dense-math peak" numbers (TPU v5e bf16 MXU + HBM, A100-class GPU,
#: a many-core host CPU).  These anchor MFU/roofline *ratios*; absolute
#: calibration belongs to the env overrides below.
PEAKS = {
    "tpu": (197e12, 819e9),
    "gpu": (312e12, 2.0e12),
    "cpu": (5e11, 1e11),
}
_FALLBACK_PEAKS = (5e11, 1e11)


def db_dir():
    """Persistence directory (``MXNET_TPU_COSTDB``), or None when the
    database is in-memory only (flush becomes a no-op)."""
    return os.environ.get("MXNET_TPU_COSTDB") or None


def sample_every():
    """``MXNET_TPU_COSTDB_SAMPLE``: measure every Nth post-compile
    dispatch per program (default 16; the first post-compile dispatch
    is always measured; ``0`` disables measurement — signatures are
    still collected)."""
    try:
        n = int(os.environ.get("MXNET_TPU_COSTDB_SAMPLE", "16"))
    except ValueError:
        n = 16
    return max(0, n)


#: platform-name aliases -> canonical peak-table key (the TPU tunnel
#: plugin registers its platform as "axon", not "tpu"; without this
#: mapping a real-chip run would silently rate itself against the
#: fallback peaks and report absurd MFU)
BACKEND_ALIASES = {"axon": "tpu", "cuda": "gpu", "rocm": "gpu"}

_SCOPES = itertools.count(1)


def next_scope():
    """A process-unique dispatch-scope token.  Executor/ShardedTrainer
    take one at construction — and a fresh one on every rebuild — and
    pass ``key=(scope, id(fn))`` to :func:`begin_dispatch`: ``id(fn)``
    alone is reused by the allocator once a discarded function is
    collected, which would let a rebuilt instance's compile dispatch
    masquerade as post-warm and get its multi-second compile timed as
    dispatch wall."""
    return next(_SCOPES)


def backend_name():
    """The jax backend platform normalized to a peak-table key
    (``tpu``/``gpu``/``cpu``; ``axon``->``tpu``, ``cuda``/``rocm``->
    ``gpu``), or ``cpu`` when the backend cannot be probed (costdb
    must never raise)."""
    try:
        import jax
        name = jax.default_backend()
    except Exception:  # mxlint: allow-broad-except(backend probing can fail before init or mid-teardown; cost attribution degrades to the cpu peak table)
        return "cpu"
    return BACKEND_ALIASES.get(name, name)


def _env_float(name):
    v = os.environ.get(name)
    if not v:
        return None
    try:
        return float(v)
    except ValueError:
        return None


def peak_flops(backend=None):
    """Peak FLOPs/s for ``backend`` (default: the live jax backend).
    ``MXNET_TPU_PEAK_FLOPS`` overrides the table — set it when the
    chip generation differs from the baked-in defaults."""
    env = _env_float("MXNET_TPU_PEAK_FLOPS")
    if env and env > 0:
        return env
    return PEAKS.get(backend or backend_name(), _FALLBACK_PEAKS)[0]


def peak_bandwidth(backend=None):
    """Peak memory bytes/s for ``backend`` (default: the live jax
    backend); ``MXNET_TPU_PEAK_BW`` overrides the table."""
    env = _env_float("MXNET_TPU_PEAK_BW")
    if env and env > 0:
        return env
    return PEAKS.get(backend or backend_name(), _FALLBACK_PEAKS)[1]


def roofline(flops, bytes_accessed, wall_s, backend=None):
    """Derive the roofline fields for one record: ``mfu``,
    ``ai`` (arithmetic intensity, flops/byte), ``bound``
    (``compute``/``bandwidth`` by AI vs the ridge point),
    ``attainable_s`` (the roofline-model lower bound on wall time) and
    ``attained_frac`` (attainable/measured — 1.0 means running at the
    roofline).  Fields that cannot be derived are None; never raises."""
    pf = peak_flops(backend)
    pbw = peak_bandwidth(backend)
    out = {"mfu": None, "ai": None, "bound": None,
           "attainable_s": None, "attained_frac": None,
           "peak_flops": pf, "peak_bw": pbw}
    flops = None if flops is None else float(flops)
    bytes_accessed = None if bytes_accessed is None \
        else float(bytes_accessed)
    if flops is not None and wall_s and wall_s > 0 and pf > 0:
        out["mfu"] = flops / wall_s / pf
    if flops is not None and bytes_accessed:
        out["ai"] = flops / bytes_accessed
        ridge = pf / pbw if pbw > 0 else float("inf")
        out["bound"] = "compute" if out["ai"] >= ridge else "bandwidth"
    att = _attainable_s(flops, bytes_accessed, pf, pbw)
    if att is not None:
        out["attainable_s"] = att
        if wall_s and wall_s > 0:
            out["attained_frac"] = min(1.0, att / wall_s)
    return out


def _attainable_s(flops, bytes_accessed, pf, pbw):
    """Roofline lower bound: max(compute time, memory time)."""
    parts = []
    if flops is not None and pf > 0:
        parts.append(flops / pf)
    if bytes_accessed is not None and pbw > 0:
        parts.append(bytes_accessed / pbw)
    return max(parts) if parts else None


def _sig_hash(payload):
    blob = json.dumps(payload, sort_keys=True, default=repr)
    return hashlib.sha1(blob.encode("utf-8")).hexdigest()[:12]


def _shapes_of(args, limit=4):
    """Compact (shapes, dtypes, n_leaves, digest) signature of a
    dispatch's argument pytree — the first ``limit`` leaves spelled
    out for display, plus a digest over EVERY leaf's shape+dtype that
    the record key includes.  Trainer args lead with the params tree,
    so without the full digest a partial-final-batch dispatch (whose
    batch leaf sits past ``limit``) would collapse into the full-batch
    record and corrupt its min-wall MFU."""
    try:
        import jax
        leaves = jax.tree_util.tree_leaves(args)
    except Exception:  # mxlint: allow-broad-except(signature capture is best-effort observability over arbitrary caller pytrees)
        return [], [], 0, None
    shapes, dtypes = [], []
    for leaf in leaves[:limit]:
        shapes.append(list(getattr(leaf, "shape", ()) or ()))
        dtypes.append(str(getattr(leaf, "dtype", type(leaf).__name__)))
    h = hashlib.sha1()
    for leaf in leaves:
        h.update(repr((tuple(getattr(leaf, "shape", ()) or ()),
                       str(getattr(leaf, "dtype",
                                   type(leaf).__name__)))).encode())
    return shapes, dtypes, len(leaves), h.hexdigest()[:12]


class CostDB:
    """The in-memory aggregate store + pending-signature registry.

    One module-level instance (:data:`DB`) serves the process; tests
    build private ones.  All methods are thread-safe and never raise
    out of the observation path.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._records = {}         # (kind, name, sig) -> record dict
        self._pending = []         # unbound trace-time signatures
        self._bound = {}           # program -> [signature, ...]
        self._counts = {}          # program -> dispatches observed

    # ------------------------------------------------ trace-time notes
    def note_block(self, name, block_kind, shapes, dtypes, flops=None,
                   bytes_accessed=None, block_config=None, layout=None,
                   pallas=False, graph=None, plan=None):
        """Register a fused block traced right now (pending until the
        surrounding program's dispatch binds it).  Called from
        ``analysis.fusion.apply_block`` with trace-time shapes.
        ``graph``/``plan``: the owning graph's structural digest and
        the dispatched plan identity (``greedy`` or a searched
        ``plan-*`` id) — ``tools/perf_top.py --suggest`` joins them
        against the ``graph_plan`` tuning-cache entries.  Never raises
        — it runs inside a jit trace, which must not pay for
        observability."""
        try:
            self._note({
                "kind": "block", "name": str(name),
                "block_kind": block_kind,
                "shapes": [list(s) for s in shapes],
                "dtypes": [str(d) for d in dtypes],
                "flops": None if flops is None else float(flops),
                "bytes_accessed": None if bytes_accessed is None
                else float(bytes_accessed),
                "block_config": dict(block_config) if block_config
                else None,
                "layout": layout, "pallas": bool(pallas),
                "graph": graph, "plan": plan,
            })
        except MemoryError:  # pragma: no cover - never mask resource exhaustion
            raise
        except Exception:  # mxlint: allow-broad-except(signature capture inside a jit trace; any failure must not fail the compile)
            pass

    def note_kernel(self, op, shapes, dtypes, flops=None,
                    bytes_accessed=None, block_config=None):
        """Register a Pallas kernel instantiation (its chosen block
        shapes keyed by the problem shape — the queryable form of the
        block-size cliffs).  Never raises (jit-trace context, as
        :meth:`note_block`)."""
        try:
            self._note({
                "kind": "kernel", "name": str(op), "block_kind": None,
                "shapes": [list(s) for s in shapes],
                "dtypes": [str(d) for d in dtypes],
                "flops": None if flops is None else float(flops),
                "bytes_accessed": None if bytes_accessed is None
                else float(bytes_accessed),
                "block_config": dict(block_config) if block_config
                else None,
                "layout": None, "pallas": True,
            })
        except MemoryError:  # pragma: no cover - never mask resource exhaustion
            raise
        except Exception:  # mxlint: allow-broad-except(signature capture inside a jit trace; any failure must not fail the compile)
            pass

    @staticmethod
    def _sig_ident(sig):
        """Identity of a trace-time signature: kind + name + shapes +
        block config.  Shapes/config are part of it so two
        instantiations of the same kernel in ONE program (e.g. cross-
        and self-attention flash calls at different seq lengths) both
        survive; a retrace of the SAME instantiation refreshes in
        place."""
        return (sig["kind"], sig["name"],
                json.dumps(sig["shapes"]),
                json.dumps(sig["block_config"], sort_keys=True))

    def _note(self, sig):
        ident = self._sig_ident(sig)
        with self._lock:
            for i, p in enumerate(self._pending):
                if self._sig_ident(p) == ident:
                    self._pending[i] = sig
                    return
            self._pending.append(sig)

    # -------------------------------------------------- dispatch seam
    def begin_dispatch(self, program, key=None):
        """Mark a dispatch of ``program`` beginning; returns the
        observation token :func:`end_dispatch` consumes.  ``key``
        (callers pass ``id(fn)``) scopes the dispatch counter to ONE
        compiled function — program names are fixed strings shared by
        every Executor/Trainer instance, and without the key a second
        instance's compile dispatch would look post-warm and get
        timed.  The first observed dispatch per (program, key) is the
        compile and is never timed; afterwards every Nth
        (``MXNET_TPU_COSTDB_SAMPLE``) is, starting with the first
        post-compile one."""
        ckey = (program, key)
        with self._lock:
            count = self._counts.get(ckey, 0)
            self._counts[ckey] = count + 1
        n = sample_every()
        sampled = (n > 0 and count > 0
                   and (n <= 1 or count % n == 1))
        return (program, key,
                time.perf_counter() if sampled else None)

    def end_dispatch(self, obs, out=None, args=None, mesh=None,
                     failed=False, steps=1):
        """Close a dispatch observation: bind any signatures the
        compile just traced to this program, and on sampled dispatches
        synchronize on ``out`` and record the program + its bound
        blocks/kernels.  ``steps``: how many training steps the ONE
        dispatch executed (``run_steps`` chains N inside one program
        while the trace — whose flops the signatures carry — covers a
        single step; the measured wall is divided by it so per-step
        flops meet per-step wall).  ``failed=True`` (the dispatch
        raised) still binds — otherwise the signatures would dangle
        and bind to whatever program dispatches next — but never
        times.  Swallows every failure — observability must not fail
        the train step."""
        try:
            self._end_dispatch(obs, out, args, mesh, failed, steps)
        except Exception:  # mxlint: allow-broad-except(cost recording is observability wrapped around the training hot path; any failure here must never fail the dispatch it measured)
            pass

    def bind_pending(self, program, key=None):
        """Bind every pending trace-time signature to the (program,
        key) dispatch scope — ``key`` is the caller's ``id(fn)``, so
        two Executor/Trainer instances sharing the fixed program-name
        strings cannot cross-attribute each other's blocks.  One drain
        is one compile's burst: for each (kind, name) present in the
        burst, the burst's instantiation set REPLACES the previously
        bound set of that (kind, name) — so a retrace with new shapes
        (partial final batch) cannot stack a second shape variant that
        would forever split the attributed wall, while a single trace
        carrying several instantiations of one kernel (different seq
        lengths) keeps them all.  Multi-process dispatch paths call
        this directly (bind-only, no timing)."""
        with self._lock:
            if not self._pending:
                return
            burst_names = {(s["kind"], s["name"]) for s in self._pending}
            bound = self._bound.setdefault((program, key), [])
            bound[:] = [s for s in bound
                        if (s["kind"], s["name"]) not in burst_names]
            bound.extend(self._pending)
            self._pending = []

    def _end_dispatch(self, obs, out, args, mesh, failed=False,
                      steps=1):
        program, key, t0 = obs
        self.bind_pending(program, key=key)
        if t0 is None or failed:
            return
        import jax
        jax.block_until_ready(out)
        # per-step wall: a run_steps chain is `steps` full updates in
        # one dispatch, and the bound signatures carry ONE step's flops
        wall = (time.perf_counter() - t0) / max(1, int(steps))
        backend = backend_name()
        mesh_d = dict(mesh) if mesh else None
        shapes, dtypes, n_leaves, digest = _shapes_of(args)
        from . import memory as memory_mod
        plan = memory_mod.get_plan(program)
        cost = plan.cost if plan is not None else {}
        # the compiled chain's cost_analysis covers all `steps` too:
        # scale both sides so per-step flops meet per-step wall
        scale = 1.0 / max(1, int(steps))
        self.record(
            "program", program, wall_s=wall,
            flops=None if cost.get("flops") is None
            else cost["flops"] * scale,
            bytes_accessed=None if cost.get("bytes_accessed") is None
            else cost["bytes_accessed"] * scale,
            shapes=shapes, dtypes=dtypes, n_leaves=n_leaves,
            leaves_digest=digest,
            mesh=mesh_d, backend=backend, program=program)
        with self._lock:
            sigs = list(self._bound.get((program, key), ()))
        if not sigs:
            return
        # attribute the measured wall across the program's blocks by
        # roofline-attainable share: a bandwidth-bound block's MFU then
        # lands exactly where the roofline predicts it
        pf, pbw = peak_flops(backend), peak_bandwidth(backend)
        atts = [_attainable_s(s["flops"], s["bytes_accessed"], pf, pbw)
                or 0.0 for s in sigs]
        total_att = sum(atts)
        for sig, att in zip(sigs, atts):
            wall_b = (wall * att / total_att) if total_att > 0 else None
            self.record(
                sig["kind"], sig["name"], wall_s=wall_b,
                flops=sig["flops"],
                bytes_accessed=sig["bytes_accessed"],
                shapes=sig["shapes"], dtypes=sig["dtypes"],
                mesh=mesh_d, backend=backend, program=program,
                block_kind=sig["block_kind"],
                block_config=sig["block_config"],
                layout=sig["layout"], pallas=sig["pallas"],
                graph=sig.get("graph"), plan=sig.get("plan"),
                source="span+roofline-attribution")

    # ------------------------------------------------------- records
    def record(self, kind, name, wall_s=None, flops=None,
               bytes_accessed=None, shapes=(), dtypes=(), n_leaves=None,
               leaves_digest=None,
               mesh=None, backend=None, program=None, block_kind=None,
               block_config=None, layout=None, pallas=None,
               graph=None, plan=None,
               source="span"):
        """Upsert one aggregate record.  The record key is (kind, name,
        signature-hash of shapes/dtypes/mesh/backend/block config) —
        re-observations of the same key aggregate (count, min/mean
        wall) and the roofline fields are re-derived from the *minimum*
        observed wall (the least-noise estimate, the convention
        benchmarking uses).  ``graph``/``plan`` (block records) name
        the owning graph digest and the dispatched fusion-plan
        identity; the latest observation wins — they annotate, and do
        not split, the record key."""
        backend = backend or backend_name()
        key_payload = {
            "shapes": [list(s) for s in shapes],
            "dtypes": [str(d) for d in dtypes],
            "n_leaves": n_leaves, "leaves_digest": leaves_digest,
            "mesh": mesh, "backend": backend,
            "block_config": block_config, "block_kind": block_kind,
        }
        sig = _sig_hash(key_payload)
        key = (kind, str(name), sig)
        wall_s = None if wall_s is None else float(wall_s)
        with self._lock:
            rec = self._records.get(key)
            created = rec is None
            if created:
                rec = {
                    "schema": SCHEMA, "kind": kind, "name": str(name),
                    "sig": sig, "program": program,
                    "block_kind": block_kind,
                    "block_config": block_config,
                    "layout": layout, "pallas": pallas,
                    "shapes": key_payload["shapes"],
                    "dtypes": key_payload["dtypes"],
                    "n_leaves": n_leaves,
                    "leaves_digest": leaves_digest,
                    "mesh": mesh, "backend": backend,
                    "graph": graph, "plan": plan,
                    "count": 0, "wall_s": None, "mean_wall_s": None,
                    "total_wall_s": 0.0,
                    "flops": None, "bytes_accessed": None,
                    "source": source,
                }
                self._records[key] = rec
            if flops is not None:
                rec["flops"] = float(flops)
            if bytes_accessed is not None:
                rec["bytes_accessed"] = float(bytes_accessed)
            if graph is not None:
                rec["graph"] = graph
            if plan is not None:
                rec["plan"] = plan
            if program is not None:
                rec["program"] = program
            rec["ts"] = round(time.time(), 6)
            if wall_s is not None:
                rec["count"] += 1
                rec["total_wall_s"] += wall_s
                rec["wall_s"] = wall_s if rec["wall_s"] is None \
                    else min(rec["wall_s"], wall_s)
                rec["mean_wall_s"] = rec["total_wall_s"] / rec["count"]
            rec.update(roofline(rec["flops"], rec["bytes_accessed"],
                                rec["wall_s"], backend))
            mfu = rec["mfu"]
        self._emit_metrics(kind, name, created, mfu)
        return key

    def _emit_metrics(self, kind, name, created, mfu):
        try:
            from .registry import counter, gauge
            if created:
                counter("mxtpu_costdb_records_total").labels(
                    kind=kind).inc()
            if mfu is not None and kind in ("block", "kernel"):
                gauge("mxtpu_block_mfu").labels(block=str(name)).set(mfu)
        except Exception:  # mxlint: allow-broad-except(metric emission is observability; a registry failure must not fail the recording path)
            pass

    def records(self):
        """Snapshot of every aggregate record (copies, JSON-ready)."""
        with self._lock:
            return [dict(r) for r in self._records.values()]

    def summary(self, top=5):
        """Roll-up dict for reports: record/kind counts, per-program
        measured wall + MFU, and the ``top`` worst-MFU blocks/kernels
        — the block the autotuner should look at first leads."""
        recs = self.records()
        by_kind = {}
        for r in recs:
            by_kind[r["kind"]] = by_kind.get(r["kind"], 0) + 1
        programs = {}
        for r in recs:
            if r["kind"] != "program" or r["wall_s"] is None:
                continue
            programs[r["name"]] = {
                "wall_s": round(r["wall_s"], 6),
                "flops": r["flops"],
                "bytes_accessed": r["bytes_accessed"],
                "mfu": None if r["mfu"] is None else round(r["mfu"], 4),
                "bound": r["bound"],
                "count": r["count"],
            }
        ranked = sorted(
            (r for r in recs if r["kind"] in ("block", "kernel")
             and r["mfu"] is not None),
            key=lambda r: r["mfu"])
        worst = [{
            "name": r["name"], "kind": r["kind"],
            "block_kind": r["block_kind"],
            "mfu": round(r["mfu"], 4), "bound": r["bound"],
            "block_config": r["block_config"],
        } for r in ranked[:top]]
        return {
            "schema": SCHEMA,
            "records": len(recs),
            "by_kind": by_kind,
            "backend": backend_name(),
            "peak_flops": peak_flops(),
            "peak_bw": peak_bandwidth(),
            "programs": programs,
            "worst_mfu": worst,
        }

    # --------------------------------------------------- persistence
    def flush(self, directory=None):
        """Append the current aggregates to
        ``<dir>/costdb-<pid>.jsonl`` (``directory`` defaults to
        ``MXNET_TPU_COSTDB``; no directory -> no-op returning None).
        Each line is one self-describing ``mxtpu-costdb/1`` record;
        repeated flushes append snapshots and the reader keeps the
        last occurrence per key.  Notes a ``costdb_flush`` flight
        event.  Never raises."""
        directory = directory or db_dir()
        if not directory:
            return None
        recs = self.records()
        if not recs:
            return None
        path = os.path.join(directory, "costdb-%d.jsonl" % os.getpid())
        try:
            os.makedirs(directory, exist_ok=True)
            with open(path, "a") as f:
                for r in recs:
                    f.write(json.dumps(r, sort_keys=True, default=repr)
                            + "\n")
        except OSError as e:
            import logging
            logging.getLogger(__name__).warning(
                "costdb: cannot write %r: %s", path, e)
            return None
        try:
            from . import flight
            flight.record("costdb_flush", path=path, records=len(recs))
        except Exception:  # mxlint: allow-broad-except(flight noting is observability-of-observability; never let it mask a successful flush)
            pass
        return path

    def drop_scope(self, scope):
        """Prune the dispatch counts and bindings of a retired scope
        token (a rebuilt trainer calls this for its OLD scope so
        long-running rebuild loops do not grow the maps without
        bound).  Aggregate records are kept — they are the product."""
        with self._lock:
            stale = [k for k in self._counts
                     if isinstance(k[1], tuple) and k[1]
                     and k[1][0] == scope]
            for k in stale:
                del self._counts[k]
            stale = [k for k in self._bound
                     if isinstance(k[1], tuple) and k[1]
                     and k[1][0] == scope]
            for k in stale:
                del self._bound[k]

    def reset(self):
        """Forget every record, pending signature, binding, and
        dispatch count (telemetry.reset calls this)."""
        with self._lock:
            self._records.clear()
            self._pending = []
            self._bound.clear()
            self._counts.clear()


#: the process-wide database (module-level helpers below)
DB = CostDB()


def note_block(*args, **kwargs):
    """Register a traced fused block — see :meth:`CostDB.note_block`."""
    return DB.note_block(*args, **kwargs)


def note_kernel(*args, **kwargs):
    """Register a Pallas kernel choice — :meth:`CostDB.note_kernel`."""
    return DB.note_kernel(*args, **kwargs)


def begin_dispatch(program, key=None):
    """Open a dispatch observation — :meth:`CostDB.begin_dispatch`."""
    return DB.begin_dispatch(program, key=key)


def bind_pending(program, key=None):
    """Bind pending signatures only — :meth:`CostDB.bind_pending`.
    Never raises (multi-process dispatch paths call it from a
    ``finally``, where an error would mask the step's real result)."""
    try:
        DB.bind_pending(program, key=key)
    except Exception:  # mxlint: allow-broad-except(observability on the dispatch hot path; a binding failure must never mask the dispatch result propagating through the caller's finally)
        pass


def drop_scope(scope):
    """Prune a retired scope's counters — :meth:`CostDB.drop_scope`.
    Never raises (called from rebuild paths)."""
    try:
        DB.drop_scope(scope)
    except Exception:  # mxlint: allow-broad-except(scope pruning is bookkeeping; a failure must not break the rebuild that triggered it)
        pass


def end_dispatch(obs, out=None, args=None, mesh=None, failed=False,
                 steps=1):
    """Close a dispatch observation — :meth:`CostDB.end_dispatch`."""
    return DB.end_dispatch(obs, out=out, args=args, mesh=mesh,
                           failed=failed, steps=steps)


def record(*args, **kwargs):
    """Upsert one record on the default DB — :meth:`CostDB.record`."""
    return DB.record(*args, **kwargs)


def records():
    """Snapshot of the default DB's records."""
    return DB.records()


def summary(top=5):
    """Roll-up of the default DB — :meth:`CostDB.summary`."""
    return DB.summary(top=top)


def flush(directory=None):
    """Persist the default DB — :meth:`CostDB.flush`."""
    return DB.flush(directory=directory)


def reset():
    """Clear the default DB (telemetry.reset calls this)."""
    DB.reset()


# ------------------------------------------------------------- reader

_REQUIRED_FIELDS = ("schema", "kind", "name", "sig")


def _validate(rec, where):
    if not isinstance(rec, dict):
        raise ValueError("%s: record is not an object" % where)
    for f in _REQUIRED_FIELDS:
        if f not in rec:
            raise ValueError("%s: record missing %r" % (where, f))
    if rec["schema"] != SCHEMA:
        raise ValueError("%s: schema %r != %r"
                         % (where, rec["schema"], SCHEMA))
    if rec["kind"] not in ("program", "block", "kernel", "op"):
        raise ValueError("%s: unknown record kind %r"
                         % (where, rec["kind"]))
    return rec


def read_records(path, strict=False):
    """Load cost records from a ``costdb-*.jsonl`` file or a directory
    of them.  Duplicate (kind, name, sig) keys — repeated flush
    snapshots, multiple runs sharing the directory — dedup to the most
    RECENT record by its ``ts`` field (file order breaks ties; lexical
    filename order alone would let an old run's pid win).
    ``strict=True`` raises :class:`ValueError` on the first malformed
    line / wrong-schema record; the default skips bad lines and
    reports them in the returned ``(records, skipped)`` tuple."""
    if os.path.isdir(path):
        files = sorted(
            os.path.join(path, f) for f in os.listdir(path)
            if f.startswith("costdb") and f.endswith(".jsonl"))
        if not files and strict:
            raise ValueError("no costdb-*.jsonl files under %r" % path)
    else:
        files = [path]
    out, skipped = {}, 0
    for fp in files:
        try:
            fh = open(fp)
        except OSError as e:
            if strict:
                raise ValueError("cannot read %r: %s" % (fp, e))
            skipped += 1
            continue
        with fh:
            for i, line in enumerate(fh, 1):
                line = line.strip()
                if not line:
                    continue
                where = "%s:%d" % (os.path.basename(fp), i)
                try:
                    rec = _validate(json.loads(line), where)
                except ValueError:
                    if strict:
                        raise
                    skipped += 1
                    continue
                key = (rec["kind"], rec["name"], rec["sig"])
                prev = out.get(key)
                if prev is None or _rec_ts(rec) >= _rec_ts(prev):
                    out[key] = rec
    return list(out.values()), skipped


def _rec_ts(rec):
    ts = rec.get("ts")
    return float(ts) if isinstance(ts, (int, float)) else float("-inf")


# auto-persist: a run that armed MXNET_TPU_COSTDB keeps its ground
# truth even when the training script never calls flush() itself.
# Registered unconditionally — flush() re-reads the env and no-ops
# when the knob is unset, so a script that sets MXNET_TPU_COSTDB
# AFTER importing still gets the documented exit-time flush.
import atexit
atexit.register(flush)
