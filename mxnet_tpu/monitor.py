"""Monitor: tap intermediate outputs during forward.

Reference: ``python/mxnet/monitor.py`` (Monitor.install hooks the executor
monitor callback, :16-122; the reference disables bulk-exec segments for
per-op visibility).

TPU-native default: the JIT-SAFE numerics path
(:mod:`mxnet_tpu.telemetry.numerics` via
``Executor.set_stats_monitor``) — each matched node output's stat
bundle (l2 / mean-abs / max-abs / non-finite count / zero fraction) is
computed as scalar reductions INSIDE one compiled forward, so an
installed monitor costs one small device fetch per activated forward
instead of a host sync per node (the MXL002 hazard; an activated
``forward_backward`` runs as separate forward + backward programs,
the same shape the eager route always had).  The reference's
eager per-node route (``_forward_monitored``) remains available as
``Monitor(..., eager=True)`` and is selected automatically when a
custom ``stat_func`` is supplied — an arbitrary python stat needs the
full array on the host.
"""
from __future__ import annotations

import logging
import re

import numpy as np

from . import telemetry
from .ndarray import NDArray

__all__ = ["Monitor"]

_STAT_GAUGE = telemetry.gauge("mxtpu_monitor_stat")

#: the in-graph stat reported as THE monitor value on the jit-safe
#: path; matches the default ``asum_stat`` (mean absolute value)
_DEFAULT_STAT = "mean_abs"


class Monitor:
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False,
                 eager=None):
        """``interval``: activate every Nth ``tic()``.  ``stat_func``:
        custom array->stat callable — implies the EAGER path (the full
        array must reach the host).  ``eager``: force the reference's
        host-sync-per-node route (default: eager only when a custom
        ``stat_func`` demands it)."""
        if eager is None:
            eager = stat_func is not None
        self.eager = bool(eager)
        if stat_func is None:
            def asum_stat(x):
                return x.abs().mean() if hasattr(x, "abs") else \
                    abs(x.asnumpy()).mean()
            stat_func = asum_stat
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort

        def stat_helper(name, array):
            if not self.activated or not self.re_prog.match(name):
                return
            self.queue.append((self.step, name, self.stat_func(array)))
        self.stat_helper = stat_helper

        def stats_helper(name, stats):
            # jit-safe route: the executor already pattern-filtered at
            # trace time and delivers host floats — no device traffic here
            if not self.activated:
                return
            self.queue.append((self.step, name, stats))
        self.stats_helper = stats_helper

    def install(self, exe):
        """Hook an executor (reference Monitor.install).  Default:
        the jit-safe stats route; ``eager=True``: the reference
        per-node callback route."""
        if self.eager:
            exe.set_monitor_callback(self.stat_helper)
        else:
            exe.set_stats_monitor(self.stats_helper,
                                  pattern=self.re_prog,
                                  active=lambda: self.activated)
        self.exes.append(exe)

    def tic(self):
        if self.step % self.interval == 0:
            for exe in self.exes:
                for array in exe.arg_arrays:
                    pass  # params visible via stat on demand
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        if not self.activated:
            return []
        self.activated = False
        res = []
        queue = self.queue
        if self.sort:
            queue = sorted(queue, key=lambda x: x[1])
        for n, k, v_list in queue:
            if isinstance(v_list, dict):
                # jit-safe stat bundle: the reported value is the
                # default stat (mean |x|); the bundle rides the repr so
                # log lines keep the non-finite/zero-fraction signal
                val = v_list.get(_DEFAULT_STAT, 0.0)
                s = "%g" % val
                if v_list.get("nonfinite"):
                    s += "\tnonfinite=%d" % v_list["nonfinite"]
                res.append((n, k, s))
                try:
                    _STAT_GAUGE.labels(tensor=str(k)).set(float(val))
                except (TypeError, ValueError):
                    pass
                continue
            if isinstance(v_list, NDArray):
                v_list = [v_list]
            if not isinstance(v_list, list):
                v_list = [v_list]
            s = ""
            for v in v_list:
                if isinstance(v, NDArray):
                    v = v.asnumpy()
                s += str(v) + "\t"
            res.append((n, k, s))
            # mirror the stat into the telemetry registry (labeled by
            # tensor name) so installed monitors land in the JSONL
            # step-log / Prometheus surface, not only the log lines.
            # toc() already synced the values, so this costs no extra
            # device round trip; non-scalar stats record their mean.
            try:
                first = v_list[0]
                if isinstance(first, NDArray):
                    first = first.asnumpy()
                _STAT_GAUGE.labels(tensor=str(k)).set(
                    float(np.mean(np.asarray(first))))
            except (TypeError, ValueError, IndexError):
                pass  # non-numeric or empty custom stat_func output
        self.queue = []
        return res

    def toc_print(self):
        res = self.toc()
        for n, k, v in res:
            logging.info("Batch: %7d %30s %s", n, k, v)
