"""Monitor: tap intermediate outputs during forward.

Reference: ``python/mxnet/monitor.py`` (Monitor.install hooks the executor
monitor callback, :16-122; the reference disables bulk-exec segments for
per-op visibility — here the executor switches to the eager per-node path
while a callback is installed).
"""
from __future__ import annotations

import logging
import re

import numpy as np

from . import telemetry
from .ndarray import NDArray

__all__ = ["Monitor"]

_STAT_GAUGE = telemetry.gauge("mxtpu_monitor_stat")


class Monitor:
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def asum_stat(x):
                return x.abs().mean() if hasattr(x, "abs") else \
                    abs(x.asnumpy()).mean()
            stat_func = asum_stat
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort

        def stat_helper(name, array):
            if not self.activated or not self.re_prog.match(name):
                return
            self.queue.append((self.step, name, self.stat_func(array)))
        self.stat_helper = stat_helper

    def install(self, exe):
        """Hook an executor (reference Monitor.install)."""
        exe.set_monitor_callback(self.stat_helper)
        self.exes.append(exe)

    def tic(self):
        if self.step % self.interval == 0:
            for exe in self.exes:
                for array in exe.arg_arrays:
                    pass  # params visible via stat on demand
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        if not self.activated:
            return []
        self.activated = False
        res = []
        queue = self.queue
        if self.sort:
            queue = sorted(queue, key=lambda x: x[1])
        for n, k, v_list in queue:
            if isinstance(v_list, NDArray):
                v_list = [v_list]
            if not isinstance(v_list, list):
                v_list = [v_list]
            s = ""
            for v in v_list:
                if isinstance(v, NDArray):
                    v = v.asnumpy()
                s += str(v) + "\t"
            res.append((n, k, s))
            # mirror the stat into the telemetry registry (labeled by
            # tensor name) so installed monitors land in the JSONL
            # step-log / Prometheus surface, not only the log lines.
            # toc() already synced the values, so this costs no extra
            # device round trip; non-scalar stats record their mean.
            try:
                first = v_list[0]
                if isinstance(first, NDArray):
                    first = first.asnumpy()
                _STAT_GAUGE.labels(tensor=str(k)).set(
                    float(np.mean(np.asarray(first))))
            except (TypeError, ValueError, IndexError):
                pass  # non-numeric or empty custom stat_func output
        self.queue = []
        return res

    def toc_print(self):
        res = self.toc()
        for n, k, v in res:
            logging.info("Batch: %7d %30s %s", n, k, v)
