"""Symbol: the declarative graph IR.

Reference: ``python/mxnet/symbol.py`` (2092 L) over nnvm's C++ ``Symbol``/
``Graph`` (SURVEY §2.2).  TPU-native re-design: a Symbol is a lightweight
python DAG of ``_Node``s (op + parsed attrs + input edges).  There is no
separate graph compiler — ``bind`` traces the DAG into one JAX function and
``jax.jit`` is the whole §3.4 pass pipeline (gradient, memory planning,
fusion, placement all happen inside XLA).  Shape/type inference runs
``jax.eval_shape`` over the same trace, with per-op parameter-shape hooks
(:mod:`mxnet_tpu.ops.shapes`) standing in for the reference's FInferShape.

JSON serialization keeps the reference's node/arg_nodes/heads layout
(``nnvm::Symbol::Save``; ``src/c_api/c_api_symbolic.cc:400``) so checkpoints
interop at the file level.
"""
from __future__ import annotations

import json

from .base import MXNetError
from .context import current_context
from . import attribute, name as _name_mod
from .ops import registry as _registry
from .ops.registry import OpContext, apply_op, get_op
from .ops import shapes as _shapes

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json",
           "zeros", "ones", "arange"]

_META_PREFIX = "__"

# generated op functions (mx.sym.slice, mx.sym.sum, ...) are injected into
# this module's globals and would shadow python builtins used below
_py_slice = slice


class _Node:
    """One graph node: a variable (op is None) or an op application."""
    __slots__ = ("op", "name", "attrs", "raw_attr", "inputs", "num_args")

    def __init__(self, op, name, attrs=None, raw_attr=None, inputs=None,
                 num_args=0):
        self.op = op                    # Operator | None (variable)
        self.name = name
        self.attrs = attrs or {}        # parsed op params
        self.raw_attr = raw_attr or {}  # meta attrs (ctx_group, lr_mult, ...)
        self.inputs = inputs or []      # list[(Node, out_index)]
        self.num_args = num_args        # inputs[:num_args] are args, rest aux

    @property
    def is_variable(self):
        return self.op is None

    def num_outputs(self):
        return 1 if self.op is None else self.op.get_num_outputs(self.attrs)

    def arg_names(self):
        return [] if self.op is None else self.op.get_arg_names(self.attrs)

    def aux_names(self):
        return [] if self.op is None else self.op.get_aux_names(self.attrs)

    def output_names(self):
        n = self.num_outputs()
        if self.op is None:
            return [self.name]
        if n == 1:
            return [self.name + "_output"]
        return ["%s_output%d" % (self.name, i) for i in range(n)]


def _topo_order(entries):
    """Iterative DFS post-order over the DAG (inputs before consumers)."""
    order, visited = [], set()
    stack = [(n, False) for (n, _) in reversed(entries)]
    while stack:
        node, expanded = stack.pop()
        if id(node) in visited:
            continue
        if expanded:
            visited.add(id(node))
            order.append(node)
        else:
            stack.append((node, True))
            for (src, _) in reversed(node.inputs):
                if id(src) not in visited:
                    stack.append((src, False))
    return order


def _classify_vars(topo):
    """Split variable nodes into (args, aux) in first-appearance order."""
    aux_ids = set()
    for node in topo:
        for (src, _) in node.inputs[node.num_args:]:
            if src.is_variable:
                aux_ids.add(id(src))
    args, aux = [], []
    for node in topo:
        if node.is_variable:
            (aux if id(node) in aux_ids else args).append(node)
    return args, aux


def eval_graph(topo, entries, var_values, is_train=False, key=None,
               monitor=None, batch_size=None, device_map=None,
               seed_vals=None):
    """Execute the DAG as a pure function.

    ``var_values``: dict id(var-node) -> array.  Returns (head values,
    aux-updates dict id(var-node) -> new array).  Stochastic nodes fold
    their topo index into ``key`` so replay is deterministic.

    ``batch_size`` specializes 0-dims in init-op shapes (the RNN toolkit's
    deferred begin_state zeros; the reference resolves these via nnvm
    backward shape inference).

    ``device_map`` (id(node) -> jax.Device) places each op on a device —
    the model-parallel ctx_group path (reference AssignContext +
    PlaceDevice inserting _CrossDeviceCopy, graph_executor.cc:249-341;
    here the copy is a jax.device_put and XLA async dispatch overlaps the
    per-device segments).
    """
    import jax
    # seed_vals: id(node) -> output tuple for nodes evaluated OUTSIDE this
    # call (the pipeline-parallel path seeds each stage's boundary input)
    vals = {} if seed_vals is None else dict(seed_vals)
    aux_updates = {}
    device_map = device_map or {}

    # optional conv1x1+BN fusion (ops/fused.py): deferred convs carry
    # their input values to the consuming BatchNorm node
    fuse_plan, fuse_skip = {}, set()
    stem_plan = set()
    elide_plan = set()
    if is_train and not device_map:
        from .ops import fused as _fused
        from .ops.nn import current_image_layout
        if current_image_layout() == "NHWC":
            if _fused.fusion_enabled():
                fuse_plan, fuse_skip = _fused.plan_conv_bn_fusion(
                    topo, entries)
            if _fused.stem_s2d_enabled():
                stem_plan = _fused.plan_stem_s2d(topo)
            if _fused.elide_names():
                # convs whose backward-data exists only to feed an input
                # BN's beta grad (ops/fused.py input-BN dX elision)
                elide_plan = _fused.plan_input_bn_elide(
                    topo, entries, _fused.elide_names())

    # block-granularity fusion pass (analysis.fusion): conv+BN+ReLU /
    # FC+act chains lowered to single custom-vjp regions with a pinned
    # layout per boundary.  Runs in train AND eval traces (eval keeps
    # the global-stats BN semantics inside the region); the per-node
    # monitor path stays unfused so callbacks see every output, and
    # seeded partial graphs (pipeline stages) never fuse — a chain can
    # straddle the stage boundary, whose members are outside this topo.
    block_plan = None
    if monitor is None and not device_map and seed_vals is None:
        from .ops import fused as _fused
        if _fused.block_fusion_enabled():
            from .ops.nn import current_image_layout
            from .analysis import fusion as _fusion
            block_plan = _fusion.plan_block_fusion(
                topo, entries, layout=current_image_layout(),
                is_train=is_train,
                exclude=(set(fuse_skip) | set(fuse_plan) | stem_plan
                         | elide_plan))
            if not block_plan.blocks:
                block_plan = None

    for i, node in enumerate(topo):
        if node.is_variable:
            try:
                vals[id(node)] = (var_values[id(node)],)
            except KeyError:
                raise MXNetError("no value bound for variable %r" % node.name)
            continue
        if id(node) in fuse_skip:
            # conv deferred into its BatchNorm consumer
            vals[id(node)] = (tuple(vals[id(src)][idx]
                                    for (src, idx) in node.inputs),)
            continue
        if id(node) in fuse_plan:
            from .ops import fused as _fused
            conv_node = fuse_plan[id(node)]
            conv_ins = vals[id(conv_node)][0]
            bn_ins = [vals[id(src)][idx]
                      for (src, idx) in node.inputs[1:]]
            outs = _fused.fused_conv_bn_apply(
                conv_node.attrs, node.attrs, is_train,
                conv_ins[0], conv_ins[1], *bn_ins)
            n_vis = node.num_outputs()
            n_aux = len(node.inputs) - node.num_args
            vals[id(node)] = outs[:n_vis]
            for (src, _), upd in zip(node.inputs[node.num_args:],
                                     outs[n_vis:n_vis + n_aux]):
                if src.is_variable:
                    aux_updates[id(src)] = upd
            if monitor is not None:
                for oname, val in zip(node.output_names(), outs[:n_vis]):
                    monitor(oname, val)
            continue
        if id(node) in stem_plan:
            from .ops import fused as _fused
            s_ins = [vals[id(src)][idx] for (src, idx) in node.inputs]
            sx = s_ins[0]
            if sx.ndim == 4 and sx.shape[1] % 2 == 0 \
                    and sx.shape[2] % 2 == 0:
                vals[id(node)] = (_fused.stem_s2d_conv(
                    sx, s_ins[1], elide=id(node) in elide_plan),)
                continue
            # odd spatial size: fall through to the direct conv
        if id(node) in elide_plan:
            from .ops import fused as _fused
            e_ins = [vals[id(src)][idx] for (src, idx) in node.inputs]
            vals[id(node)] = (_fused.elided_conv_apply(
                node.attrs, e_ins[0], e_ins[1]),)
            continue
        if block_plan is not None:
            if id(node) in block_plan.skip:
                # interior of a fused block: evaluated at its terminal
                vals[id(node)] = (None,) * node.num_outputs()
                continue
            blk = block_plan.blocks.get(id(node))
            if blk is not None:
                from .analysis import fusion as _fusion
                out, bn_node, bn_aux = _fusion.apply_block(blk, vals,
                                                           is_train)
                vals[id(node)] = (out,)
                if bn_node is not None:
                    for (src, _), upd in zip(
                            bn_node.inputs[bn_node.num_args:], bn_aux):
                        if src.is_variable:
                            aux_updates[id(src)] = upd
                continue
        ins = [vals[id(src)][idx] for (src, idx) in node.inputs]
        dev = device_map.get(id(node))
        if dev is not None:
            ins = [jax.device_put(x, dev) for x in ins]
        node_attrs = node.attrs
        shp = node_attrs.get("shape")
        # deferred batch dim: ONLY for source ops (zeros/ones/... with no
        # inputs, e.g. RNN begin_state) — ops WITH inputs (Reshape, ...)
        # give 0 its own meaning ("copy this dim from the input") and
        # resolve it themselves
        if (not node.inputs and isinstance(shp, (tuple, list))
                and any(s == 0 for s in shp)):
            if batch_size is None:
                raise MXNetError(
                    "node %r has a deferred (0) dim in shape %s but no "
                    "batch size is known" % (node.name, shp))
            node_attrs = dict(node_attrs)
            node_attrs["shape"] = tuple(batch_size if s == 0 else int(s)
                                        for s in shp)
        stoch = node.op.stochastic
        if callable(stoch):
            stoch = stoch(node_attrs)
        k = None
        if stoch and key is not None:
            k = jax.random.fold_in(key, i)
        octx = OpContext(is_train=is_train, key=k)
        outs = apply_op(node.op, node_attrs, octx, *ins)
        n_vis = node.num_outputs()
        n_aux = len(node.inputs) - node.num_args
        vals[id(node)] = outs[:n_vis]
        for (src, _), upd in zip(node.inputs[node.num_args:],
                                 outs[n_vis:n_vis + n_aux]):
            if src.is_variable:
                aux_updates[id(src)] = upd
        if monitor is not None:
            for oname, val in zip(node.output_names(), outs[:n_vis]):
                monitor(oname, val)
    heads = [vals[id(n)][i] for (n, i) in entries]
    return heads, aux_updates


class Symbol:
    """An immutable multi-output handle into the graph."""
    __slots__ = ("_entries",)

    def __init__(self, entries):
        self._entries = list(entries)  # list[(Node, out_index)]

    # ------------------------------------------------------------- identity
    @property
    def name(self):
        if len(self._entries) == 1:
            return self._entries[0][0].name
        return None

    def __repr__(self):
        if len(self._entries) == 1:
            return "<Symbol %s>" % self._entries[0][0].name
        return "<Symbol group [%s]>" % ", ".join(
            n.name for (n, _) in self._entries)

    def __iter__(self):
        return (Symbol([e]) for e in self._entries)

    def __len__(self):
        return len(self._entries)

    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            if index not in names:
                # allow bare node name too
                for i, (n, _) in enumerate(self._entries):
                    if n.name == index:
                        return Symbol([self._entries[i]])
                raise ValueError("cannot find output %r" % index)
            index = names.index(index)
        if isinstance(index, _py_slice):
            return Symbol(self._entries[index])
        return Symbol([self._entries[index]])

    # ---------------------------------------------------------- arithmetic
    def _binary(self, other, op_ss, op_s, swap=False):
        if isinstance(other, Symbol):
            return _create(op_ss, None, None, [self, other], {})
        if isinstance(other, (int, float)):
            return _create(op_s, None, None, [self], {"scalar": float(other)})
        raise TypeError("unsupported operand type %r" % type(other))

    def __add__(self, other):
        return self._binary(other, "elemwise_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, other):
        return self._binary(other, "elemwise_sub", "_minus_scalar")

    def __rsub__(self, other):
        return self._binary(other, "elemwise_sub", "_rminus_scalar")

    def __mul__(self, other):
        return self._binary(other, "elemwise_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binary(other, "elemwise_div", "_div_scalar")

    def __rtruediv__(self, other):
        return self._binary(other, "elemwise_div", "_rdiv_scalar")

    __div__ = __truediv__
    __rdiv__ = __rtruediv__

    def __pow__(self, other):
        return self._binary(other, "_power", "_power_scalar")

    def __neg__(self):
        return _create("negative", None, None, [self], {})

    def __copy__(self):
        return Symbol(list(self._entries))

    def __deepcopy__(self, memo):
        return load_json(self.tojson())

    def __eq__(self, other):
        if isinstance(other, Symbol):
            return self._entries == other._entries
        return NotImplemented

    def __hash__(self):
        return hash(tuple((id(n), i) for (n, i) in self._entries))

    # -------------------------------------------------------------- listing
    def _topo(self):
        return _topo_order(self._entries)

    def list_arguments(self):
        args, _ = _classify_vars(self._topo())
        return [n.name for n in args]

    def list_auxiliary_states(self):
        _, aux = _classify_vars(self._topo())
        return [n.name for n in aux]

    def list_outputs(self):
        out = []
        for (node, idx) in self._entries:
            out.append(node.output_names()[idx])
        return out

    def get_internals(self):
        """All internal outputs as a group (reference symbol.py
        get_internals; used for feature extraction and shared binding)."""
        entries = []
        for node in self._topo():
            for i in range(node.num_outputs()):
                entries.append((node, i))
        return Symbol(entries)

    def get_children(self):
        if len(self._entries) != 1:
            raise MXNetError("get_children requires a single-output symbol")
        node = self._entries[0][0]
        if not node.inputs:
            return None
        return Symbol(list(node.inputs))

    # ---------------------------------------------------------------- attrs
    def attr(self, key):
        if len(self._entries) == 1:
            node = self._entries[0][0]
            if key == "name":
                return node.name
            v = node.raw_attr.get(key)
            if v is None and node.op is not None and key in node.attrs:
                return _attr_str(node.attrs[key])
            return v
        return None

    def list_attr(self):
        if len(self._entries) != 1:
            return {}
        return dict(self._entries[0][0].raw_attr)

    def attr_dict(self):
        out = {}
        for node in self._topo():
            d = dict(node.raw_attr)
            if node.op is not None:
                d.update({k: _attr_str(v) for k, v in node.attrs.items()})
            if d:
                out[node.name] = d
        return out

    def _set_attr(self, **kwargs):
        if len(self._entries) != 1:
            raise MXNetError("_set_attr requires a single-output symbol")
        node = self._entries[0][0]
        for k, v in kwargs.items():
            if not isinstance(v, str):
                raise ValueError("attribute values must be strings")
            node.raw_attr[k] = v

    # ------------------------------------------------------------ inference
    def infer_shape(self, *args, **kwargs):
        res = self._infer_shape_impl(False, *args, **kwargs)
        return res

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer_shape_impl(True, *args, **kwargs)

    def _infer_shape_impl(self, partial, *args, **kwargs):
        import jax
        import jax.numpy as jnp

        known = {}
        if args:
            arg_list = self.list_arguments()
            for a_name, a_shape in zip(arg_list, args):
                if a_shape is not None:
                    known[a_name] = tuple(a_shape)
        for k, v in kwargs.items():
            if v is not None:
                known[k] = tuple(v)

        topo = self._topo()
        arg_nodes, aux_nodes = _classify_vars(topo)
        shapes = {}   # id(node) -> shape for variables
        dtypes = {}
        for node in arg_nodes + aux_nodes:
            if node.name in known:
                shapes[id(node)] = known[node.name]
            elif "__shape__" in node.raw_attr:
                shapes[id(node)] = tuple(
                    json.loads(node.raw_attr["__shape__"]))
            dtypes[id(node)] = node.raw_attr.get("__dtype__", "float32")

        batch_size = None
        for n in arg_nodes:
            if id(n) in shapes and len(shapes[id(n)]) > 0:
                batch_size = int(shapes[id(n)][0])
                break

        # propagate: per-op param-shape hooks fill parameter/aux variables
        for node in topo:
            if node.is_variable:
                continue
            hook = _shapes.get_param_shapes(node.op.name)
            if hook is None:
                continue
            names = node.arg_names() + node.aux_names()
            known_in = {}
            for nm, (src, idx) in zip(names, node.inputs):
                if src.is_variable and id(src) in shapes:
                    known_in[nm] = shapes[id(src)]
                elif not src.is_variable:
                    pass  # outputs handled by eval_shape below; hooks only
                          # need data shapes, resolved in the eval pass
            # run a partial eval up to this node to learn non-var input shapes
            inferred = hook(node.attrs, _resolve_input_shapes(
                node, shapes, dtypes, topo, known_in, batch_size))
            for nm, shp in inferred.items():
                try:
                    slot = names.index(nm)
                except ValueError:
                    continue
                src, _ = node.inputs[slot]
                if src.is_variable and id(src) not in shapes:
                    shapes[id(src)] = tuple(shp)

        missing = [n.name for n in arg_nodes + aux_nodes
                   if id(n) not in shapes]
        if missing and not partial:
            raise MXNetError(
                "infer_shape: cannot infer shapes for %s; provide them "
                "explicitly" % missing)
        if missing:
            arg_shapes = [shapes.get(id(n)) for n in arg_nodes]
            aux_shapes = [shapes.get(id(n)) for n in aux_nodes]
            return arg_shapes, None, aux_shapes

        # full eval_shape for outputs
        entries = self._entries

        def fn(var_vals):
            heads, _aux = eval_graph(topo, entries, var_vals,
                                     is_train=False, key=None,
                                     batch_size=batch_size)
            return heads

        var_vals = {id(n): jax.ShapeDtypeStruct(shapes[id(n)],
                                                jnp.dtype(dtypes[id(n)]))
                    for n in arg_nodes + aux_nodes}
        out_structs = jax.eval_shape(fn, var_vals)
        arg_shapes = [shapes[id(n)] for n in arg_nodes]
        aux_shapes = [shapes[id(n)] for n in aux_nodes]
        out_shapes = [tuple(s.shape) for s in out_structs]
        return arg_shapes, out_shapes, aux_shapes

    def infer_type(self, *args, **kwargs):
        import jax
        import jax.numpy as jnp
        import numpy as np

        known = {}
        if args:
            for a_name, a_type in zip(self.list_arguments(), args):
                if a_type is not None:
                    known[a_name] = np.dtype(a_type).name
        for k, v in kwargs.items():
            if v is not None:
                known[k] = np.dtype(v).name
        topo = self._topo()
        arg_nodes, aux_nodes = _classify_vars(topo)
        # reference InferType propagates the known dtype to the other
        # float arguments of each op (same-dtype rule): typing only the
        # data input types the whole net (test_utils.check_consistency
        # depends on this).  Conservative version: when every explicitly
        # known dtype agrees on one float type, unknown un-attributed
        # args default to it instead of float32.
        default = "float32"
        kt = {np.dtype(v).name for v in known.values()}
        if len(kt) == 1 and np.dtype(next(iter(kt))).kind == "f":
            default = next(iter(kt))
        arg_types = [np.dtype(known.get(
            n.name, n.raw_attr.get("__dtype__", default)))
            for n in arg_nodes]
        aux_types = [np.dtype(known.get(
            n.name, n.raw_attr.get("__dtype__", "float32")))
            for n in aux_nodes]
        # outputs via eval_shape with unit shapes is unreliable (shape-
        # dependent ops); reuse infer_shape machinery when shapes known is
        # overkill — outputs inherit the head dtype of a tiny trace.
        try:
            shape_kwargs = {}
            arg_shapes, out_shapes, _ = self.infer_shape_partial()
            if out_shapes is None:
                raise MXNetError("partial")
            # full shapes known: trace dtypes exactly
            var_vals = {}
            for n, t in zip(arg_nodes, arg_types):
                var_vals[id(n)] = jax.ShapeDtypeStruct(
                    tuple(arg_shapes[arg_nodes.index(n)]), jnp.dtype(t))
            for n, t in zip(aux_nodes, aux_types):
                var_vals[id(n)] = jax.ShapeDtypeStruct((1,), jnp.dtype(t))
            entries = self._entries

            def fn(vv):
                heads, _ = eval_graph(topo, entries, vv)
                return heads
            outs = jax.eval_shape(fn, var_vals)
            out_types = [np.dtype(o.dtype) for o in outs]
        except Exception:  # mxlint: allow-broad-except(dtype trace is best-effort over arbitrary fcomputes; fall back to float32)
            out_types = [np.dtype("float32")] * len(self._entries)
        return arg_types, out_types, aux_types

    # -------------------------------------------------------- verification
    def verify(self, shapes=None, types=None, tp_size=1,
               check_registry=False, mesh=None, parallel=None,
               memory=None, **shape_kwargs):
        """Statically verify the graph BEFORE any compile/device time.

        Runs the :mod:`mxnet_tpu.analysis` graph verifier: per-node
        shape/dtype consistency against the op registry, missing
        param-shape rules, dead inputs, duplicate names, cycles, and
        (``tp_size`` > 1) tensor-parallel sharding coverage.  Input
        shapes go in like ``infer_shape``'s kwargs::

            report = net.verify(data=(32, 3, 224, 224))
            if not report.ok:
                print(report)          # node-level diagnostics
            report.raise_if_errors()   # or fail hard

        ``mesh`` ({axis: size}) additionally runs the distributed-
        correctness pass (MXG011-016) for the composed parallel step
        described by ``parallel`` (an ``analysis.build_config`` dict)::

            net.verify(data=(32, 8, 64), mesh={"data": 2, "pipe": 2},
                       parallel=analysis.build_config(
                           pipeline_stages=2, data_shapes=...))

        ``memory`` (True or an ``analysis.memlive.check_memory``
        options dict) additionally runs the static memory-liveness
        pass (MXG017-021): predicted peak HBM vs the armed budget,
        remat/ZeRO/donation advice — all before any compile::

            net.verify(data=(32, 3, 224, 224),
                       memory={"is_train": True, "n_slots": 2,
                               "mesh": {"data": 8}})

        Returns an :class:`mxnet_tpu.analysis.Report`.
        """
        from .analysis import verify_symbol
        known = dict(shapes or {})
        known.update(shape_kwargs)
        return verify_symbol(self, shapes=known, types=types,
                             tp_size=tp_size,
                             check_registry=check_registry,
                             mesh=mesh, parallel=parallel,
                             memory=memory)

    # ------------------------------------------------------------- binding
    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None,
             strict=False):
        from .executor import Executor
        return Executor(self, ctx or current_context(), args, args_grad,
                        grad_req, aux_states, group2ctx=group2ctx,
                        shared_exec=shared_exec, strict=strict)

    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    group2ctx=None, shared_exec=None, strict=False,
                    **kwargs):
        """Infer shapes from kwargs, allocate arrays, bind.

        Reference: python/mxnet/symbol.py:1163 (python-side allocation then
        bind)."""
        from . import ndarray as nd
        ctx = ctx or current_context()
        arg_shapes, _, aux_shapes = self.infer_shape(**kwargs)
        arg_types, _, aux_types = self.infer_type(
            **{k: v for k, v in (type_dict or {}).items()})
        arg_names = self.list_arguments()
        args = [nd.zeros(s, ctx=ctx, dtype=t)
                for s, t in zip(arg_shapes, arg_types)]
        aux_states = [nd.zeros(s, ctx=ctx, dtype=t)
                      for s, t in zip(aux_shapes, aux_types)]
        if isinstance(grad_req, str):
            reqs = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, (list, tuple)):
            reqs = dict(zip(arg_names, grad_req))
        else:
            reqs = {n: grad_req.get(n, "null") for n in arg_names}
        args_grad = {}
        for n, s, t in zip(arg_names, arg_shapes, arg_types):
            if reqs.get(n, "null") != "null":
                args_grad[n] = nd.zeros(s, ctx=ctx, dtype=t)
        return self.bind(ctx, args, args_grad, reqs, aux_states,
                         group2ctx=group2ctx, shared_exec=shared_exec,
                         strict=strict)

    # -------------------------------------------------------------- ser/de
    def tojson(self):
        """Serialize in the reference's JSON graph layout
        (nnvm::Symbol::Save; heads/arg_nodes/nodes)."""
        topo = self._topo()
        node_ids = {id(n): i for i, n in enumerate(topo)}
        nodes = []
        arg_nodes = []
        for i, node in enumerate(topo):
            if node.is_variable:
                arg_nodes.append(i)
                entry = {"op": "null", "name": node.name, "inputs": []}
                if node.raw_attr:
                    entry["attrs"] = dict(node.raw_attr)
            else:
                attrs = {k: _attr_str(v) for k, v in node.attrs.items()}
                attrs.update(node.raw_attr)
                entry = {"op": node.op.name, "name": node.name,
                         "inputs": [[node_ids[id(s)], idx, 0]
                                    for (s, idx) in node.inputs]}
                if attrs:
                    entry["attrs"] = attrs
            nodes.append(entry)
        heads = [[node_ids[id(n)], idx, 0] for (n, idx) in self._entries]
        row_ptr = [0]
        for n in topo:
            row_ptr.append(row_ptr[-1] + n.num_outputs())
        return json.dumps({
            "nodes": nodes,
            "arg_nodes": arg_nodes,
            "node_row_ptr": row_ptr,
            "heads": heads,
            "attrs": {"mxnet_version": ["int", 1001]},
        }, indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # ------------------------------------------------------------- helpers
    def _single_entry(self):
        if len(self._entries) != 1:
            raise MXNetError("operation requires a single-output symbol; "
                             "got %d outputs" % len(self._entries))
        return self._entries[0]

    # evaluation helper for tests / debugging
    def eval(self, ctx=None, **kwargs):
        ex = self.bind(ctx or current_context(),
                       {k: v for k, v in kwargs.items()})
        return ex.forward()


def _attr_str(v):
    if isinstance(v, bool):
        return "True" if v else "False"
    if v is None:
        return "None"
    if isinstance(v, (list, tuple)):
        return "(" + ", ".join(str(x) for x in v) + ")"
    return str(v)


def _resolve_input_shapes(node, var_shapes, var_dtypes, topo, seed,
                          batch_size=None):
    """Best-effort shapes of ``node``'s inputs by name (for shape hooks).

    Variable inputs read ``var_shapes``; op-output inputs are resolved by an
    eval_shape over the sub-graph when all its variables are known.
    """
    import jax
    import jax.numpy as jnp
    names = node.arg_names() + node.aux_names()
    out = dict(seed)
    for nm, (src, idx) in zip(names, node.inputs):
        if nm in out:
            continue
        if src.is_variable:
            if id(src) in var_shapes:
                out[nm] = var_shapes[id(src)]
            continue
        # op output: eval_shape the ancestor sub-graph
        sub_topo = _topo_order([(src, idx)])
        needed = [n for n in sub_topo if n.is_variable]
        if any(id(n) not in var_shapes for n in needed):
            continue
        var_vals = {id(n): jax.ShapeDtypeStruct(
            var_shapes[id(n)], jnp.dtype(var_dtypes.get(id(n), "float32")))
            for n in needed}
        bsz = batch_size
        if bsz is None:
            for n in needed:
                if len(var_shapes[id(n)]) > 0:
                    bsz = int(var_shapes[id(n)][0])
                    break

        def fn(vv, _sub_topo=sub_topo, _src=src, _idx=idx, _bsz=bsz):
            heads, _ = eval_graph(_sub_topo, [(_src, _idx)], vv,
                                  batch_size=_bsz)
            return heads[0]
        try:
            st = jax.eval_shape(fn, var_vals)
            out[nm] = tuple(st.shape)
        except Exception:  # mxlint: allow-broad-except(sub-graph shape resolution is best-effort; Symbol.verify localizes the real error)
            pass
    return out


# ---------------------------------------------------------------- creation
def Variable(name, attr=None, shape=None, lr_mult=None, wd_mult=None,
             dtype=None, init=None, **kwargs):
    """Create a variable symbol (reference symbol.py Variable)."""
    if not isinstance(name, str):
        raise TypeError("expect a string for variable name")
    raw = attribute.current().get(attr)
    if shape is not None:
        raw["__shape__"] = json.dumps(list(shape))
    if lr_mult is not None:
        raw["__lr_mult__"] = str(lr_mult)
    if wd_mult is not None:
        raw["__wd_mult__"] = str(wd_mult)
    if dtype is not None:
        import numpy as np
        raw["__dtype__"] = np.dtype(dtype).name
    if init is not None:
        if not isinstance(init, str):
            init = init.dumps()
        raw["__init__"] = init
    for k, v in kwargs.items():
        if k.startswith("__") and k.endswith("__"):
            raw[k] = str(v)
        else:
            raise ValueError("unknown variable option %r" % k)
    node = _Node(None, name, raw_attr=raw)
    return Symbol([(node, 0)])


var = Variable


def Group(symbols):
    """Group symbols into one multi-output symbol."""
    entries = []
    for s in symbols:
        if not isinstance(s, Symbol):
            raise TypeError("expect Symbols in Group")
        entries.extend(s._entries)
    return Symbol(entries)


def _create(op_name, name, attr, sym_args, attr_kwargs, sym_kwargs=None):
    """Compose a new op node (the generated mx.sym.<op> body)."""
    op = get_op(op_name)
    sym_kwargs = sym_kwargs or {}

    if op.key_var_num_args and op.key_var_num_args not in attr_kwargs:
        attr_kwargs = dict(attr_kwargs)
        attr_kwargs[op.key_var_num_args] = (
            len(sym_args) + len(sym_kwargs)) or 1
    attrs = op.parse_attrs(attr_kwargs)
    arg_names = op.get_arg_names(attrs)
    aux_names = op.get_aux_names(attrs)
    all_names = arg_names + aux_names

    hint = op.name.lower().lstrip("_")
    name = _name_mod.current().get(name, hint)
    raw = attribute.current().get(attr)

    slots = {}
    for i, s in enumerate(sym_args):
        if i >= len(all_names):
            raise MXNetError("%s: too many positional inputs" % op_name)
        slots[all_names[i]] = s
    for k, v in sym_kwargs.items():
        if k in slots:
            raise MXNetError("%s: duplicate input %r" % (op_name, k))
        slots[k] = v

    inputs = []
    for nm in all_names:
        s = slots.get(nm)
        if s is None:
            # auto-create the parameter/aux variable (reference: nnvm
            # Symbol composition fills missing inputs with variables)
            s = Variable("%s_%s" % (name, nm))
        if not isinstance(s, Symbol):
            raise TypeError("%s: input %r must be a Symbol" % (op_name, nm))
        inputs.append(s._single_entry())

    node = _Node(op, name, attrs=attrs, raw_attr=raw, inputs=inputs,
                 num_args=len(arg_names))
    return Symbol([(node, i) for i in range(node.num_outputs())])


def _make_sym_function(op):
    def fn(*args, name=None, attr=None, out=None, **kwargs):
        sym_args = []
        for a in args:
            if not isinstance(a, Symbol):
                raise TypeError("positional inputs must be Symbols")
            sym_args.append(a)
        sym_kwargs, attr_kwargs = {}, {}
        for k, v in kwargs.items():
            if isinstance(v, Symbol):
                sym_kwargs[k] = v
            else:
                attr_kwargs[k] = v
        return _create(op.name, name, attr, sym_args, attr_kwargs, sym_kwargs)
    fn.__name__ = op.name
    fn.__doc__ = op.doc
    return fn


def _register_sym_functions():
    g = globals()
    for opname in _registry.list_ops():
        op = get_op(opname)
        g[opname] = _make_sym_function(op)
    for alias, target in _registry._ALIASES.items():
        g[alias] = g[target]


_register_sym_functions()


# convenience creators mirroring mx.sym.zeros/ones/arange.  A 0 in shape is
# a deferred batch dim resolved at bind time (the RNN begin_state pattern);
# meta kwargs (e.g. __layout__) become node attrs.
def zeros(shape, dtype="float32", name=None, **kwargs):
    attr = {k: str(v) for k, v in kwargs.items()
            if k.startswith("__") and k.endswith("__")}
    return _create("_zeros", name, attr or None, [],
                   {"shape": tuple(shape), "dtype": dtype})


def ones(shape, dtype="float32", name=None, **kwargs):
    attr = {k: str(v) for k, v in kwargs.items()
            if k.startswith("__") and k.endswith("__")}
    return _create("_ones", name, attr or None, [],
                   {"shape": tuple(shape), "dtype": dtype})


def arange(start, stop=None, step=1.0, repeat=1, dtype="float32", name=None):
    return _create("_arange", name, None, [],
                   {"start": start, "stop": stop, "step": step,
                    "repeat": repeat, "dtype": dtype})


# ---------------------------------------------------------------- loading
def load_json(json_str):
    """Deserialize from the reference JSON layout."""
    data = json.loads(json_str)
    raw_nodes = data["nodes"]
    built = []
    for entry in raw_nodes:
        raw_attr = dict(entry.get("attrs", entry.get("attr", {}) or {}))
        if entry["op"] == "null":
            node = _Node(None, entry["name"], raw_attr=raw_attr)
        else:
            op = get_op(entry["op"])
            params = {k: v for k, v in raw_attr.items()
                      if not (k.startswith(_META_PREFIX))}
            meta = {k: v for k, v in raw_attr.items()
                    if k.startswith(_META_PREFIX)}
            attrs = op.parse_attrs(params)
            inputs = [(built[src], idx)
                      for (src, idx, *_rest) in entry["inputs"]]
            node = _Node(op, entry["name"], attrs=attrs, raw_attr=meta,
                         inputs=inputs,
                         num_args=len(op.get_arg_names(attrs)))
        built.append(node)
    entries = [(built[i], idx) for (i, idx, *_r) in data["heads"]]
    return Symbol(entries)


def load(fname):
    with open(fname) as f:
        return load_json(f.read())
