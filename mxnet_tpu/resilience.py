"""Resilience primitives: fault injection, retry/backoff, atomic files.

The reference framework's distributed story is ps-lite heartbeats plus
restart-from-checkpoint (``src/kvstore/kvstore_dist.h:39-80``, SURVEY
§5.8).  The TPU-native port replaced ps-lite with ``jax.distributed``
collectives, so failure handling moves into the framework itself.  This
module is the shared substrate the other layers build on:

* a deterministic, seeded **fault-injection registry**: seams are
  declared at named sites (:data:`KNOWN_SITES`) via :func:`fault_point`
  calls in production code, and armed through ``MXNET_TPU_FAULTS`` (or
  :func:`configure_faults`) so tests and chaos runs reproduce exact
  failure sequences.  Spec grammar (sites separated by ``;``)::

      MXNET_TPU_FAULTS="recordio.read:p=0.05,seed=7;checkpoint.save:n=1"

  per-site keys: ``p`` (probability, default 1), ``seed`` (per-site RNG
  seed, default 0), ``n`` (max injections, default unlimited), ``after``
  (skip the first K evaluations), ``kind`` (``error`` raises
  :class:`FaultInjected`, ``delay`` sleeps ``delay`` seconds — a
  simulated hang for timeout paths);

* **retry/timeout/backoff primitives**: :func:`backoff_delays`
  (exponential with deterministic jitter), :func:`retry_call` /
  :func:`retryable` (deadline-aware bounded retry), :func:`with_timeout`
  (thread-based timeout wrapper), :class:`Deadline`;

* **atomic file + checkpoint-manifest helpers**: :func:`atomic_write`
  (tmp file, fsync, rename — the crash-safe write used by every
  checkpoint path) and :func:`write_manifest` / :func:`verify_manifest`
  (per-array CRC32 records that let a loader prove a checkpoint is
  complete before unpickling it).

See ``docs/api/resilience.md`` for the full grammar and knob table.
"""
from __future__ import annotations

import json
import logging
import os
import random
import struct
import threading
import time
import zlib

from .base import MXNetError
from . import telemetry as _telemetry

_RETRIES = _telemetry.counter("mxtpu_retry_total")
_FAULTS = _telemetry.counter("mxtpu_fault_injected_total")

__all__ = [
    "KNOWN_SITES", "FaultInjected", "TimeoutError",
    "configure_faults", "clear_faults", "fault_point", "fault_stats",
    "faults_active",
    "Deadline", "backoff_delays", "retry_call", "retryable",
    "with_timeout",
    "atomic_write", "array_crc32", "manifest_path", "write_manifest",
    "verify_manifest", "load_manifest",
]

# the declared seam names; fault_point() accepts others (a typo'd site
# simply never fires), but configure_faults() warns on unknown names so
# chaos specs fail loudly instead of silently testing nothing
KNOWN_SITES = (
    "recordio.read", "checkpoint.save", "checkpoint.load",
    "multihost.init", "multihost.barrier", "io.prefetch",
    # per-image decode seam (image.imdecode): kind=delay seeds a slow
    # decode stage for ioview bottleneck-attribution drills
    "io.decode",
    # durable data-iterator restore (io_resume.restore_iterator): fires
    # BEFORE any iterator mutation, so an injected fault leaves the
    # iterator restartable from the very same state; io.remap fires in
    # the elastic cursor re-cut (io_resume.remap_state) the same way
    "io.resume", "io.remap",
    "trainer.step",
    # bucketed gradient allreduce (parallel/overlap.py,
    # docs/api/overlap.md): fires at every bucket launch — arming it
    # with after=N faults a launch mid-drain, and the drain's
    # all-or-nothing contract (optimizer state untouched) is the thing
    # under test
    "kvstore.collective",
    # elastic training (parallel/reshard.py, docs/api/reshard.md):
    # per-param gather/scatter of a mesh reshape, and the world-size
    # change detection on a rank join/leave resume
    "reshard.gather", "reshard.scatter", "elastic.rejoin",
    # training-health numerics (telemetry/numerics.py): armed, the
    # trainer poisons a data input with NaNs instead of raising — the
    # numerics detection + provenance path is the thing under test
    "numerics.nonfinite",
    # serving batcher (serving/batcher.py, docs/api/serving.md): fires
    # immediately before a coalesced batch is dispatched on its ladder
    # rung — every request of the batch must fail FAST with the
    # injected error while the scheduler keeps draining the queue
    "serve.dispatch",
)


class FaultInjected(MXNetError):
    """Raised by an armed :func:`fault_point` seam (never by real code)."""

    def __init__(self, site, hit):
        super().__init__(
            "injected fault at site %r (injection #%d) — armed via "
            "MXNET_TPU_FAULTS / configure_faults()" % (site, hit))
        self.site = site
        self.hit = hit


class TimeoutError(MXNetError):
    """A :func:`with_timeout`-wrapped call exceeded its deadline."""


# --------------------------------------------------------------- fault registry

class _Site:
    __slots__ = ("name", "p", "seed", "times", "after", "kind", "delay",
                 "rng", "calls", "hits")

    def __init__(self, name, p=1.0, seed=0, times=None, after=0,
                 kind="error", delay=0.05):
        self.name = name
        self.p = float(p)
        self.seed = int(seed)
        self.times = None if times is None else int(times)
        self.after = int(after)
        if kind not in ("error", "delay"):
            raise MXNetError("fault site %r: unknown kind=%r "
                             "(use error|delay)" % (name, kind))
        self.kind = kind
        self.delay = float(delay)
        self.rng = random.Random(self.seed)
        self.calls = 0
        self.hits = 0


_LOCK = threading.Lock()
_SITES = {}
_ENV_SNAPSHOT = None     # last-parsed MXNET_TPU_FAULTS value


def _parse_spec(spec):
    sites = {}
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            name, _, params = part.partition(":")
        else:
            name, params = part, ""
        name = name.strip()
        if name not in KNOWN_SITES:
            logging.warning(
                "MXNET_TPU_FAULTS: site %r is not a declared seam %s — "
                "the spec will never fire there", name, list(KNOWN_SITES))
        kw = {}
        for item in params.split(","):
            item = item.strip()
            if not item:
                continue
            if "=" not in item:
                raise MXNetError(
                    "bad fault spec %r: expected key=value, got %r "
                    "(grammar: site:p=0.05,seed=7[,n=3,after=2,"
                    "kind=error|delay,delay=0.1];site2:...)" % (spec, item))
            k, _, v = item.partition("=")
            k = k.strip()
            if k not in ("p", "seed", "n", "after", "kind", "delay"):
                raise MXNetError("bad fault spec %r: unknown key %r"
                                 % (spec, k))
            kw["times" if k == "n" else k] = v.strip()
        sites[name] = _Site(name, **kw)
    return sites


def configure_faults(spec=None):
    """Arm fault sites from a spec string (or ``MXNET_TPU_FAULTS`` when
    ``spec`` is None).  Replaces the current configuration and resets
    per-site counters/RNGs, so the injection sequence is reproducible
    from the seed alone."""
    global _SITES, _ENV_SNAPSHOT
    if spec is None:
        spec = os.environ.get("MXNET_TPU_FAULTS", "")
    with _LOCK:
        _SITES = _parse_spec(spec)
        _ENV_SNAPSHOT = os.environ.get("MXNET_TPU_FAULTS", "")
    return sorted(_SITES)


def clear_faults():
    """Disarm every site and forget the cached env snapshot."""
    global _SITES, _ENV_SNAPSHOT
    with _LOCK:
        _SITES = {}
        _ENV_SNAPSHOT = os.environ.get("MXNET_TPU_FAULTS", "")


def faults_active():
    """True when at least one site is armed."""
    _sync_env()
    return bool(_SITES)


def _sync_env():
    # arm lazily from the env so subprocesses (launch.py workers, chaos
    # runs) inherit the spec with no code changes; a plain string compare
    # keeps the hot path (one dict lookup per seam call) cheap
    global _ENV_SNAPSHOT
    env = os.environ.get("MXNET_TPU_FAULTS", "")
    if env != _ENV_SNAPSHOT:
        configure_faults(env)


def fault_point(site):
    """Evaluate the named seam.  No-op unless the site is armed; armed
    sites draw from their own seeded RNG, so the k-th evaluation of a
    site fires identically across runs.  ``kind=error`` raises
    :class:`FaultInjected`; ``kind=delay`` sleeps (a simulated stall for
    timeout paths)."""
    _sync_env()
    s = _SITES.get(site)
    if s is None:
        return
    with _LOCK:
        s.calls += 1
        if s.calls <= s.after:
            return
        if s.times is not None and s.hits >= s.times:
            return
        if s.p < 1.0 and s.rng.random() >= s.p:
            return
        s.hits += 1
        hit, kind, delay = s.hits, s.kind, s.delay
    _FAULTS.labels(site=site).inc()
    _telemetry.flight.record("fault", site=site, hit=hit, fault_kind=kind)
    if kind == "delay":
        time.sleep(delay)
        return
    raise FaultInjected(site, hit)


def fault_stats():
    """{site: {"calls": n, "hits": m}} for every armed site."""
    with _LOCK:
        return {name: {"calls": s.calls, "hits": s.hits}
                for name, s in _SITES.items()}


# ------------------------------------------------------------- retry / backoff

class Deadline:
    """Wall-clock budget shared across retries.  ``seconds=None`` never
    expires."""

    def __init__(self, seconds):
        self.seconds = seconds
        self._expiry = None if seconds is None \
            else time.monotonic() + float(seconds)

    def remaining(self):
        """Seconds left (None = unbounded)."""
        if self._expiry is None:
            return None
        return max(0.0, self._expiry - time.monotonic())

    def expired(self):
        return self._expiry is not None and \
            time.monotonic() >= self._expiry


def backoff_delays(base=0.05, factor=2.0, max_delay=2.0, jitter=0.1,
                   seed=None):
    """Generator of exponential backoff delays ``base * factor**k``
    capped at ``max_delay``, each scaled by a uniform jitter in
    ``[1-jitter, 1+jitter]``.  A fixed ``seed`` makes the sequence
    deterministic (chaos runs record it; retries then replay
    identically)."""
    rng = random.Random(seed)
    delay = float(base)
    while True:
        if jitter:
            yield min(delay, max_delay) * \
                (1.0 + jitter * (2.0 * rng.random() - 1.0))
        else:
            yield min(delay, max_delay)
        delay = min(delay * factor, max_delay)


def retry_call(fn, args=(), kwargs=None, retries=3,
               exceptions=(Exception,), no_retry=(), base_delay=0.05,
               factor=2.0, max_delay=2.0, jitter=0.1, deadline=None,
               seed=None, on_retry=None, name=None):
    """Call ``fn(*args, **kwargs)``; on a listed exception retry up to
    ``retries`` more times with exponential backoff, never past
    ``deadline`` seconds overall.  ``no_retry`` exceptions re-raise
    immediately even when they also match ``exceptions`` (e.g. treat
    :class:`TimeoutError` as terminal while retrying its RuntimeError
    siblings).  ``on_retry(attempt, exc, delay)`` is invoked before
    each sleep.  Exhaustion (or deadline expiry) raises
    :class:`~mxnet_tpu.base.MXNetError` naming the call and chaining the
    last error."""
    kwargs = kwargs or {}
    what = name or getattr(fn, "__name__", repr(fn))
    dl = deadline if isinstance(deadline, Deadline) else Deadline(deadline)
    delays = backoff_delays(base_delay, factor, max_delay, jitter, seed)
    last = None
    for attempt in range(retries + 1):
        try:
            return fn(*args, **kwargs)
        except exceptions as e:
            if no_retry and isinstance(e, tuple(no_retry)):
                raise
            last = e
            if attempt >= retries:
                break
            delay = next(delays)
            rem = dl.remaining()
            if rem is not None:
                if rem <= 0:
                    break
                delay = min(delay, rem)
            _RETRIES.labels(site=what).inc()
            if on_retry is not None:
                on_retry(attempt + 1, e, delay)
            else:
                logging.warning("%s failed (%s: %s); retry %d/%d in "
                                "%.2fs", what, type(e).__name__, e,
                                attempt + 1, retries, delay)
            time.sleep(delay)
    raise MXNetError(
        "%s failed after %d attempt(s)%s: %s: %s"
        % (what, attempt + 1,
           " (deadline %.1fs expired)" % dl.seconds
           if dl.expired() and dl.seconds is not None else "",
           type(last).__name__, last)) from last


def retryable(**cfg):
    """Decorator form of :func:`retry_call`::

        @retryable(retries=2, exceptions=(IOError,), deadline=30)
        def fetch(): ...
    """
    def wrap(fn):
        import functools

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            return retry_call(fn, args=args, kwargs=kwargs, **cfg)
        return inner
    return wrap


def with_timeout(fn, timeout, name=None, args=(), kwargs=None):
    """Run ``fn`` in a worker thread and raise :class:`TimeoutError`
    after ``timeout`` seconds.  The worker is a daemon: a genuinely hung
    call (e.g. a collective against a dead peer) stays parked without
    blocking teardown.  ``timeout`` None/<=0 calls ``fn`` inline."""
    if timeout is None or timeout <= 0:
        return fn(*args, **(kwargs or {}))
    what = name or getattr(fn, "__name__", repr(fn))
    result = []
    error = []

    def runner():
        try:
            result.append(fn(*args, **(kwargs or {})))
        except BaseException as e:  # mxlint: allow-broad-except(stored and re-raised on the caller after join)
            error.append(e)

    t = threading.Thread(target=runner, daemon=True,
                         name="timeout:%s" % what)
    t.start()
    t.join(timeout)
    if t.is_alive():
        raise TimeoutError("%s did not complete within %.1fs"
                           % (what, timeout))
    if error:
        raise error[0]
    return result[0]


# --------------------------------------------------- atomic files + manifests

def atomic_write(path, write_fn, fault_site=None):
    """Crash-safe file write: ``write_fn(tmp_path)`` writes a sibling
    temp file, which is fsynced and atomically renamed over ``path`` —
    a reader never observes a partial file.  ``fault_site`` (e.g.
    ``"checkpoint.save"``) is evaluated BETWEEN the tmp write and the
    rename: the window a real crash leaves a stray tmp in.  An injected
    fault leaves the tmp behind (exactly the crash residue); any other
    error cleans it up and propagates."""
    tmp = "%s.tmp.%d" % (path, os.getpid())
    try:
        write_fn(tmp)
        fd = os.open(tmp, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except BaseException:  # mxlint: allow-broad-except(cleanup-and-reraise; the bare raise below propagates everything)
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    if fault_site is not None:
        fault_point(fault_site)
    os.replace(tmp, path)


def array_crc32(arr):
    """CRC32 of an array's raw bytes (C-contiguous copy if needed)."""
    import numpy as np
    a = np.ascontiguousarray(arr)
    return zlib.crc32(a.tobytes()) & 0xFFFFFFFF


def _file_crc32(path, chunk=1 << 20):
    crc = 0
    with open(path, "rb") as f:
        while True:
            buf = f.read(chunk)
            if not buf:
                break
            crc = zlib.crc32(buf, crc)
    return crc & 0xFFFFFFFF


def manifest_path(prefix, epoch):
    """Sidecar manifest path for a ``prefix-%04d.*`` checkpoint."""
    return "%s-%04d.manifest.json" % (prefix, epoch)


def write_manifest(prefix, epoch, files, arrays=None, meta=None):
    """Write the checkpoint manifest (atomically — it is the commit
    record: a checkpoint without a verifiable manifest is incomplete).

    ``files``: paths covered by the checkpoint; each is recorded with
    its size and whole-file CRC32.  ``arrays``: {name: array} whose
    per-array CRC32/shape/dtype are recorded so a loader can verify
    individual tensors.  ``meta``: JSON-able dict stored verbatim —
    elastic savers record their mesh descriptor under ``meta["mesh"]``
    (schema v2, ``parallel/reshard.py``; the manifest ``format`` bumps
    to 2 when a mesh descriptor is present, and v1 manifests keep
    loading — readers only consume the keys they know), and checkpoint
    paths record the tracked data iterator's position under
    ``meta["data_position"]`` (advisory; ``telemetry.ioview``).
    Returns the manifest path."""
    entry_files = {}
    for p in files:
        entry_files[os.path.basename(p)] = {
            "size": os.path.getsize(p),
            "crc32": _file_crc32(p),
        }
    entry_arrays = {}
    for name, arr in (arrays or {}).items():
        import numpy as np
        a = np.asarray(arr.asnumpy() if hasattr(arr, "asnumpy") else arr)
        entry_arrays[name] = {
            "crc32": array_crc32(a),
            "shape": list(a.shape),
            "dtype": str(a.dtype),
        }
    meta = dict(meta or {})
    doc = {
        "format": 2 if meta.get("mesh") else 1,
        "epoch": int(epoch),
        "files": entry_files,
        "arrays": entry_arrays,
        "meta": meta,
    }
    path = manifest_path(prefix, epoch)
    atomic_write(path, lambda tmp: _dump_json(tmp, doc))
    return path


def _dump_json(path, doc):
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)


def load_manifest(prefix, epoch):
    """Parse the manifest, or None when absent (pre-manifest
    checkpoints stay loadable).  A corrupt manifest raises
    :class:`~mxnet_tpu.base.MXNetError` naming the path."""
    path = manifest_path(prefix, epoch)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (ValueError, OSError) as e:
        raise MXNetError("corrupt checkpoint manifest %r: %s"
                         % (path, e)) from e


def verify_manifest(prefix, epoch, quick=False):
    """Verify every file the manifest covers.  Returns the manifest
    dict (None when no manifest exists — legacy checkpoint, nothing to
    verify).  Mismatch raises :class:`~mxnet_tpu.base.MXNetError`
    naming the offending file.

    ``quick=True`` checks existence + size only — the screening mode
    for checkpoint discovery over many epochs (a full CRC pass reads
    every retained byte); loaders then CRC-verify just the epoch they
    actually open."""
    doc = load_manifest(prefix, epoch)
    if doc is None:
        return None
    base = os.path.dirname(prefix)
    for fname, rec in doc.get("files", {}).items():
        path = os.path.join(base, fname) if base else fname
        if not os.path.exists(path):
            raise MXNetError(
                "checkpoint %s epoch %d is incomplete: %r listed in "
                "manifest but missing on disk" % (prefix, epoch, path))
        size = os.path.getsize(path)
        if size != rec["size"]:
            raise MXNetError(
                "checkpoint file %r is truncated/corrupt: size %d != "
                "manifest size %d" % (path, size, rec["size"]))
        if quick:
            continue
        crc = _file_crc32(path)
        if crc != rec["crc32"]:
            raise MXNetError(
                "checkpoint file %r failed CRC32 verification "
                "(0x%08x != manifest 0x%08x)" % (path, crc, rec["crc32"]))
    return doc
