"""Torch interop: run PyTorch modules/criterions as framework operators
and apply torch tensor functions to NDArrays.

Reference parity: ``plugin/torch`` (torch_module.cc wraps a Torch nn
module as an Operator whose weights/grads live in the surrounding graph;
torch_criterion.cc wraps a Torch loss as a loss head) and
``python/mxnet/torch.py`` (imperative ``mx.th.*`` tensor functions).
The reference bridges Lua Torch through luajit + the C API; the
TPU-native build bridges modern PyTorch (CPU) through the CustomOp host
-callback path — the same architectural seam the reference uses (torch
runs host-side, the surrounding graph stays compiled).

    import torch.nn as tnn
    net = mx.sym.TorchModule(data, module=tnn.Linear(64, 10))
    loss = mx.sym.TorchCriterion(net, label, criterion=tnn.CrossEntropyLoss())
"""
from __future__ import annotations

import numpy as np

from . import operator as _op
from . import ndarray as _nd
from .base import MXNetError

try:
    import torch as _torch
except ImportError:  # pragma: no cover - torch is baked into this image
    _torch = None


def _require_torch():
    if _torch is None:
        raise MXNetError("PyTorch is not available; the torch bridge "
                         "requires the CPU torch wheel")
    return _torch


# Registry of live modules handed across the CustomOp string boundary
# (CustomOp params are strings; modules can't be pickled through them
# safely, so they're kept here keyed by id).
_MODULES = {}
_CRITERIA = {}


class _TorchModuleOp(_op.CustomOp):
    """Forward/backward through a torch.nn.Module; module parameters are
    graph arguments (torch_param_i), so any framework optimizer trains
    them (torch_module-inl.h's weight/gradWeight mapping)."""

    def __init__(self, module):
        super().__init__()
        self.module = module
        self.params = list(module.parameters())

    def forward(self, is_train, req, in_data, out_data, aux):
        th = _require_torch()
        x = th.from_numpy(in_data[0].asnumpy().copy())
        with th.no_grad():
            for p, v in zip(self.params, in_data[1:]):
                p.copy_(th.from_numpy(v.asnumpy()))
        x.requires_grad_(is_train)
        out = self.module(x)
        self.assign(out_data[0], req[0], out.detach().numpy())

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        # backward may run on a fresh instance (host callbacks are
        # stateless across calls): rebuild the torch graph from in_data
        th = _require_torch()
        x = th.from_numpy(in_data[0].asnumpy().copy())
        with th.no_grad():
            for p, v in zip(self.params, in_data[1:]):
                p.copy_(th.from_numpy(v.asnumpy()))
        x.requires_grad_(True)
        out = self.module(x)
        go = th.from_numpy(out_grad[0].asnumpy().copy())
        for p in self.params:
            if p.grad is not None:
                p.grad = None
        grads = th.autograd.grad(out, [x] + self.params, grad_outputs=go,
                                 allow_unused=True)
        for i, g in enumerate(grads):
            val = (np.zeros(in_grad[i].shape, np.float32) if g is None
                   else g.numpy())
            self.assign(in_grad[i], req[i], val)


@_op.register("_torch_module")
class _TorchModuleProp(_op.CustomOpProp):
    def __init__(self, module_key):
        super().__init__(need_top_grad=True)
        try:
            self.module = _MODULES[str(module_key)]
        except KeyError:
            raise MXNetError(
                "TorchModule symbol refers to a live torch.nn.Module "
                "(key %r) that is not registered in this process.  Torch "
                "bridge symbols are NOT serializable: a graph saved with "
                "tojson()/save() or re-created in another process must "
                "rebuild the symbol with mx.sym.TorchModule(...) so the "
                "module object is re-registered." % str(module_key))
        self._params = list(self.module.parameters())

    def list_arguments(self):
        return ["data"] + ["torch_param_%d_weight" % i
                           for i in range(len(self._params))]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        th = _require_torch()
        with th.no_grad():
            out = self.module(th.zeros(*in_shape[0]))
        return ([tuple(in_shape[0])] +
                [tuple(p.shape) for p in self._params],
                [tuple(out.shape)], [])

    def create_operator(self, ctx, shapes, dtypes):
        return _TorchModuleOp(self.module)


class _TorchCriterionOp(_op.CustomOp):
    """Torch loss head: forward = scalar loss broadcast per sample,
    backward = d(loss)/d(input) (torch_criterion-inl.h)."""

    def __init__(self, criterion, label_dtype):
        super().__init__()
        self.criterion = criterion
        self.label_dtype = label_dtype

    def _label(self, th, arr):
        lab = th.from_numpy(arr.asnumpy().copy())
        if self.label_dtype == "long":
            lab = lab.long()
        return lab

    def forward(self, is_train, req, in_data, out_data, aux):
        th = _require_torch()
        x = th.from_numpy(in_data[0].asnumpy().copy())
        with th.no_grad():
            loss = self.criterion(x, self._label(th, in_data[1]))
        if loss.dim() > 0:
            # criterions configured with reduction='none' return a
            # per-sample vector; the op contract is a scalar loss
            # broadcast per sample (torch_criterion-inl.h), so reduce
            loss = loss.mean()
        n = in_data[0].shape[0]
        self.assign(out_data[0], req[0],
                    np.full((n,), float(loss), np.float32))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        # stateless: recompute the loss graph from in_data
        th = _require_torch()
        x = th.from_numpy(in_data[0].asnumpy().copy())
        x.requires_grad_(True)
        loss = self.criterion(x, self._label(th, in_data[1]))
        if loss.dim() > 0:
            loss = loss.mean()
        (gx,) = th.autograd.grad(loss, [x])
        self.assign(in_grad[0], req[0], gx.numpy())
        self.assign(in_grad[1], req[1],
                    np.zeros(in_grad[1].shape, np.float32))


@_op.register("_torch_criterion")
class _TorchCriterionProp(_op.CustomOpProp):
    def __init__(self, criterion_key, label_shape="", label_dtype="long"):
        super().__init__(need_top_grad=False)
        try:
            self.criterion = _CRITERIA[str(criterion_key)]
        except KeyError:
            raise MXNetError(
                "TorchCriterion symbol refers to a live torch criterion "
                "(key %r) not registered in this process; rebuild the "
                "symbol with mx.sym.TorchCriterion(...) — torch bridge "
                "symbols are not serializable." % str(criterion_key))
        self.label_dtype = str(label_dtype)

    def list_arguments(self):
        return ["data", "label"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        return ([tuple(in_shape[0]), tuple(in_shape[1])],
                [(in_shape[0][0],)], [])

    def create_operator(self, ctx, shapes, dtypes):
        return _TorchCriterionOp(self.criterion, self.label_dtype)


def torch_module_symbol(data, module, name="torch"):
    """Symbol wrapping a torch.nn.Module (mx.sym.TorchModule)."""
    from . import symbol as _sym
    _require_torch()
    key = str(id(module))
    _MODULES[key] = module
    return _sym.Custom(data=data, op_type="_torch_module",
                       module_key=key, name=name)


def torch_criterion_symbol(data, label, criterion, label_dtype="long",
                           name="torch_loss"):
    """Symbol wrapping a torch loss (mx.sym.TorchCriterion)."""
    from . import symbol as _sym
    _require_torch()
    key = str(id(criterion))
    _CRITERIA[key] = criterion
    return _sym.Custom(data=data, label=label,
                       op_type="_torch_criterion", criterion_key=key,
                       label_dtype=label_dtype, name=name)


# ------------------------------------------------- imperative mx.th.*
def _make_th_function(fname):
    def fn(*args, **kwargs):
        th = _require_torch()
        tfn = getattr(th, fname)
        targs = [th.from_numpy(a.asnumpy()) if isinstance(a, _nd.NDArray)
                 else a for a in args]
        out = tfn(*targs, **kwargs)
        if isinstance(out, tuple):
            return tuple(_nd.array(o.numpy()) for o in out)
        return _nd.array(out.numpy())
    fn.__name__ = fname
    fn.__doc__ = ("NDArray wrapper over torch.%s (reference mx.th.* "
                  "generated functions)" % fname)
    return fn


_TH_FUNCS = ["add", "mul", "div", "sub", "mm", "bmm", "exp", "log",
             "sqrt", "abs", "sigmoid", "tanh", "clamp", "sort", "topk",
             "cumsum", "cumprod", "softmax", "log_softmax", "norm",
             "var", "std", "median", "conv1d", "conv2d"]

for _f in _TH_FUNCS:
    if _torch is not None and callable(getattr(_torch, _f, None)):
        globals()[_f] = _make_th_function(_f)
