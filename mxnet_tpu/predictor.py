"""Standalone inference predictor.

Reference: ``include/mxnet/c_predict_api.h`` + ``src/c_api/c_predict_api.cc``
— the deployment-facing minimal API (create from symbol JSON + param bytes,
set input, forward, get output) that the amalgamation build ships.  Same
surface here, jit-compiled underneath.
"""
from __future__ import annotations

import logging

import numpy as np

from .base import MXNetError
from . import ndarray as nd
from . import symbol as sym_mod
from .context import cpu

__all__ = ["Predictor", "pad_batch"]


def pad_batch(value, batch):
    """Zero-pad ``value`` along axis 0 to ``batch`` rows.

    The shared pad half of the predict path's pad-and-slice contract:
    :meth:`Predictor.forward` pads partial batches up to its bound
    shape (so the compiled program's avals never change — zero
    retraces) and :meth:`Predictor.get_output` slices the pad rows
    back off; the serving batch ladder
    (:mod:`mxnet_tpu.serving.ladder`) uses the same helper to fill the
    tail of a coalesced batch up to the selected rung.  Padding is
    zeros: inference graphs are row-independent, so pad rows cost
    compute but never leak into real rows' outputs."""
    arr = np.asarray(value)
    if arr.ndim == 0:
        raise MXNetError("pad_batch needs a batched array, got a scalar")
    rows = arr.shape[0]
    if rows == batch:
        return arr
    if rows > batch:
        raise MXNetError("pad_batch: %d rows exceed the target batch %d"
                         % (rows, batch))
    pad = np.zeros((batch - rows,) + arr.shape[1:], dtype=arr.dtype)
    return np.concatenate([arr, pad], axis=0)


class Predictor:
    """Reference MXPredCreate / MXPredForward / MXPredGetOutput."""

    def __init__(self, symbol_json, param_bytes_or_dict, input_shapes,
                 ctx=None, output_names=None):
        """
        symbol_json: JSON string or path of the network (``*-symbol.json``).
        param_bytes_or_dict: path to ``*.params``, or {name: NDArray}.
        input_shapes: dict name -> shape.
        """
        if symbol_json.strip().startswith("{"):
            symbol = sym_mod.load_json(symbol_json)
        else:
            symbol = sym_mod.load(symbol_json)
        if output_names:
            internals = symbol.get_internals()
            outs = [internals[n if n.endswith("_output") else n + "_output"]
                    for n in output_names]
            symbol = sym_mod.Group(outs)
        self._symbol = symbol
        ctx = ctx or cpu()

        if isinstance(param_bytes_or_dict, str):
            loaded = nd.load(param_bytes_or_dict)
        elif isinstance(param_bytes_or_dict, (bytes, bytearray)):
            # raw .params content — the C predict API path
            # (MXPredCreate receives the file as a buffer)
            loaded = nd.load_buffer(bytes(param_bytes_or_dict))
        else:
            loaded = None
        if loaded is not None:
            if not isinstance(loaded, dict):
                raise MXNetError(
                    "params were saved as an unnamed list; the predictor "
                    "needs the name->array dict form (save with a dict)")
            params = {}
            for k, v in loaded.items():
                if ":" in k:
                    k = k.split(":", 1)[1]
                params[k] = v
        else:
            params = dict(param_bytes_or_dict)

        arg_shapes, _, aux_shapes = symbol.infer_shape(**input_shapes)
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        args = {}
        for name, shape in zip(arg_names, arg_shapes):
            if name in input_shapes:
                args[name] = nd.zeros(shape, ctx=ctx)
            elif name in params:
                args[name] = params[name]
            elif name.endswith("label"):
                # deployment symbols keep their loss heads; label inputs
                # are inert at inference.  NOTE: the reference
                # c_predict_api.cc:182-188 silently zero-fills EVERY
                # missing arg; restricting the fallback to label-named
                # args (and warning) keeps missing real weights a loud
                # error instead of silent garbage.
                logging.warning("Predictor: zero-filling inference-inert "
                                "input %r", name)
                args[name] = nd.zeros(shape, ctx=ctx)
            else:
                raise MXNetError("missing parameter %r" % name)
        aux = {}
        for name, shape in zip(aux_names, aux_shapes):
            if name in params:
                aux[name] = params[name]
            else:
                aux[name] = nd.zeros(shape, ctx=ctx)
        self._input_names = list(input_shapes)
        self._partial_rows = {}
        self._executor = symbol.bind(ctx, args, grad_req="null",
                                     aux_states=aux)

    def set_input(self, name, value):
        """Stage one named input.  A value whose batch dim (axis 0) is
        SMALLER than the bound shape is zero-padded up to it
        (:func:`pad_batch`) and the pad rows are sliced off every
        output by :meth:`get_output` — the compiled program keeps its
        bound avals, so partial batches never retrace or recompile (the
        executor dispatches through the AOT executable
        ``telemetry.memory.planned_executable`` cached on first use).
        A LARGER batch is a loud error pointing at :meth:`reshaped` /
        the serving batch ladder instead of a silent per-shape
        recompile."""
        if name not in self._input_names:
            raise MXNetError("unknown input %r" % name)
        arr = self._executor.arg_dict[name]
        bound = tuple(arr.shape)
        value = np.asarray(value)
        if value.ndim == len(bound) and value.shape != bound:
            if value.shape[1:] != bound[1:]:
                raise MXNetError(
                    "input %r: non-batch dims %r do not match the bound "
                    "shape %r — reshape the predictor (reshaped()) for "
                    "a different feature shape" % (name, value.shape,
                                                   bound))
            rows, cap = value.shape[0], bound[0]
            if rows > cap:
                raise MXNetError(
                    "input %r: batch %d exceeds the bound batch %d; a "
                    "bigger batch needs its own executable — use "
                    "reshaped({%r: %r}) for a second handle, or the "
                    "serving batch ladder (mxnet_tpu.serving) which "
                    "AOT-compiles a rung per batch size"
                    % (name, rows, cap, name, (rows,) + bound[1:]))
            value = pad_batch(value, cap)
            self._partial_rows[name] = rows
        else:
            # a full-shape restage clears the input's partial marker, so
            # slicing state can never leak across forwards
            self._partial_rows.pop(name, None)
        arr[:] = value

    def forward(self, **inputs):
        for k, v in inputs.items():
            self.set_input(k, v)
        self._executor.forward(is_train=False)
        return self

    def get_output(self, index=0):
        """Fetch one output; pad rows staged by a partial-batch
        :meth:`set_input` are sliced off (the slice half of
        pad-and-slice)."""
        out = self._executor.outputs[index].asnumpy()
        partial = getattr(self, "_partial_rows", None)
        rows = min(partial.values()) if partial else None
        if rows is not None and out.ndim and out.shape[0] >= rows:
            out = out[:rows]
        return out

    def reshape(self, input_shapes):
        # the C predict API reallocates freely on reshape
        # (c_predict_api.cc MXPredReshape), so growing inputs is
        # allowed; partial_shaping covers implied changes (an inert
        # label head's batch dim follows the data input)
        self._partial_rows = {}
        self._executor = self._executor.reshape(allow_up_sizing=True,
                                                partial_shaping=True,
                                                **input_shapes)
        return self

    def reshaped(self, input_shapes):
        """Return a NEW Predictor bound at ``input_shapes``, leaving this
        one untouched.

        Reference MXPredReshape (c_predict_api.cc:228-270) hands the caller
        a fresh handle backed by a new executor while the original handle
        keeps working at its original shapes (weights are shared); this is
        the method the native ABI calls so one handle per batch size works.
        """
        clone = object.__new__(Predictor)
        clone._symbol = self._symbol
        clone._partial_rows = {}
        # partial reshape keeps the full input set (reference allows
        # reshaping a subset of inputs; the others keep their shapes)
        clone._input_names = list(self._input_names)
        clone._executor = self._executor.reshape(allow_up_sizing=True,
                                                 partial_shaping=True,
                                                 **input_shapes)
        return clone
