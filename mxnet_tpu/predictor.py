"""Standalone inference predictor.

Reference: ``include/mxnet/c_predict_api.h`` + ``src/c_api/c_predict_api.cc``
— the deployment-facing minimal API (create from symbol JSON + param bytes,
set input, forward, get output) that the amalgamation build ships.  Same
surface here, jit-compiled underneath.
"""
from __future__ import annotations

import logging

import numpy as np

from .base import MXNetError
from . import ndarray as nd
from . import symbol as sym_mod
from .context import cpu

__all__ = ["Predictor"]


class Predictor:
    """Reference MXPredCreate / MXPredForward / MXPredGetOutput."""

    def __init__(self, symbol_json, param_bytes_or_dict, input_shapes,
                 ctx=None, output_names=None):
        """
        symbol_json: JSON string or path of the network (``*-symbol.json``).
        param_bytes_or_dict: path to ``*.params``, or {name: NDArray}.
        input_shapes: dict name -> shape.
        """
        if symbol_json.strip().startswith("{"):
            symbol = sym_mod.load_json(symbol_json)
        else:
            symbol = sym_mod.load(symbol_json)
        if output_names:
            internals = symbol.get_internals()
            outs = [internals[n if n.endswith("_output") else n + "_output"]
                    for n in output_names]
            symbol = sym_mod.Group(outs)
        self._symbol = symbol
        ctx = ctx or cpu()

        if isinstance(param_bytes_or_dict, str):
            loaded = nd.load(param_bytes_or_dict)
        elif isinstance(param_bytes_or_dict, (bytes, bytearray)):
            # raw .params content — the C predict API path
            # (MXPredCreate receives the file as a buffer)
            loaded = nd.load_buffer(bytes(param_bytes_or_dict))
        else:
            loaded = None
        if loaded is not None:
            if not isinstance(loaded, dict):
                raise MXNetError(
                    "params were saved as an unnamed list; the predictor "
                    "needs the name->array dict form (save with a dict)")
            params = {}
            for k, v in loaded.items():
                if ":" in k:
                    k = k.split(":", 1)[1]
                params[k] = v
        else:
            params = dict(param_bytes_or_dict)

        arg_shapes, _, aux_shapes = symbol.infer_shape(**input_shapes)
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        args = {}
        for name, shape in zip(arg_names, arg_shapes):
            if name in input_shapes:
                args[name] = nd.zeros(shape, ctx=ctx)
            elif name in params:
                args[name] = params[name]
            elif name.endswith("label"):
                # deployment symbols keep their loss heads; label inputs
                # are inert at inference.  NOTE: the reference
                # c_predict_api.cc:182-188 silently zero-fills EVERY
                # missing arg; restricting the fallback to label-named
                # args (and warning) keeps missing real weights a loud
                # error instead of silent garbage.
                logging.warning("Predictor: zero-filling inference-inert "
                                "input %r", name)
                args[name] = nd.zeros(shape, ctx=ctx)
            else:
                raise MXNetError("missing parameter %r" % name)
        aux = {}
        for name, shape in zip(aux_names, aux_shapes):
            if name in params:
                aux[name] = params[name]
            else:
                aux[name] = nd.zeros(shape, ctx=ctx)
        self._input_names = list(input_shapes)
        self._executor = symbol.bind(ctx, args, grad_req="null",
                                     aux_states=aux)

    def set_input(self, name, value):
        if name not in self._input_names:
            raise MXNetError("unknown input %r" % name)
        arr = self._executor.arg_dict[name]
        arr[:] = value

    def forward(self, **inputs):
        for k, v in inputs.items():
            self.set_input(k, v)
        self._executor.forward(is_train=False)
        return self

    def get_output(self, index=0):
        return self._executor.outputs[index].asnumpy()

    def reshape(self, input_shapes):
        # the C predict API reallocates freely on reshape
        # (c_predict_api.cc MXPredReshape), so growing inputs is
        # allowed; partial_shaping covers implied changes (an inert
        # label head's batch dim follows the data input)
        self._executor = self._executor.reshape(allow_up_sizing=True,
                                                partial_shaping=True,
                                                **input_shapes)
        return self

    def reshaped(self, input_shapes):
        """Return a NEW Predictor bound at ``input_shapes``, leaving this
        one untouched.

        Reference MXPredReshape (c_predict_api.cc:228-270) hands the caller
        a fresh handle backed by a new executor while the original handle
        keeps working at its original shapes (weights are shared); this is
        the method the native ABI calls so one handle per batch size works.
        """
        clone = object.__new__(Predictor)
        clone._symbol = self._symbol
        # partial reshape keeps the full input set (reference allows
        # reshaping a subset of inputs; the others keep their shapes)
        clone._input_names = list(self._input_names)
        clone._executor = self._executor.reshape(allow_up_sizing=True,
                                                 partial_shaping=True,
                                                 **input_shapes)
        return clone
