"""Exactly-once data plane: durable iterator state, elastic cursor
remap, and backpressure actuation.

Closes the sensor->actuator loop the observability layer opened: the
advisory ``data_position`` every checkpoint manifest records becomes a
versioned ``data_state`` entry that resume paths actually restore, the
per-rank data cursor survives a world-size change, and the io_top
bottleneck verdict tunes the pipeline instead of only naming it.

Three pieces (docs/api/io_resume.md):

* **durable iterator state** — every tier of the iterator stack
  (io.py / io_native.py / recordio.py / image.py) implements a
  ``state()``/``restore()`` contract: ``state()`` returns a JSON-able
  versioned dict describing the NEXT-UNDELIVERED sample (wrappers
  holding prefetched-but-undelivered batches report the state *before*
  those batches, not the inner reader's read-ahead position), and
  ``restore(state)`` puts a compatible iterator back so the remaining
  sample stream is identical.  :func:`restore_iterator` is the front
  door: it fires the ``io.resume`` chaos seam BEFORE any mutation and
  counts ``mxtpu_data_resume_total``.  Checkpoint manifests carry the
  entry via ``meta["data_state"]`` (written by ``model.save_checkpoint``
  and ``ShardedTrainer.save_checkpoint``); loaders stash it with
  :func:`note_loaded_state` and ``BaseModule.fit`` /
  ``ShardedTrainer.restore_data_iter`` consume it with
  :func:`apply_pending` — a SIGTERM/SIGKILL mid-epoch resumes at the
  exact next sample.

* **elastic cursor remap** — :class:`SampleLedger` derives every rank's
  sample stream from ONE deterministic global epoch permutation (keyed
  by seed+epoch, :func:`epoch_permutation`) with STRIDED rank
  assignment: rank ``r`` of ``W`` consumes permutation positions
  ``r, r+W, r+2W, ...``.  Lockstep rank cursors therefore consume a
  contiguous PREFIX of the permutation, so :func:`remap_state` can
  re-cut the cursor for any new world size exactly — no sample dropped,
  none double-seen (:class:`SampleAccountant` is the proof harness; the
  ``io.remap`` seam and ``mxtpu_data_remap_samples`` instrument the
  re-cut).  :class:`ShardedLedgerIter` is the iterator embodiment.

* **backpressure actuation** — :class:`BackpressureController` reads
  the ioview bottleneck classifier's verdict and nudges registered
  pipeline knobs (device prefetch depth via
  ``DevicePrefetchIter.set_depth``) at runtime, with hysteresis
  (``confirm`` consecutive same-verdict windows to act, ``cooldown``
  ticks between moves) and telemetry of every adjustment
  (``mxtpu_backpressure_adjust_total{knob,direction}`` + a
  ``backpressure_adjust`` flight event).

Env knobs: ``MXNET_TPU_DATA_RESUME`` (default on) gates manifest
``data_state`` write + restore; ``MXNET_TPU_BACKPRESSURE`` (default
off) auto-installs the controller in ``fit``.
"""
from __future__ import annotations

import logging
import threading

import numpy as np

from .base import MXNetError
from . import resilience
from . import telemetry
from .telemetry import ioview as _ioview

__all__ = [
    "STATE_VERSION", "enabled", "backpressure_enabled",
    "check_state", "iter_state", "restore_iterator",
    "epoch_permutation", "rank_stream", "remap_cursor", "remap_state",
    "SampleLedger", "ShardedLedgerIter", "SampleAccountant",
    "data_state_entry", "note_loaded_state", "pending_state",
    "clear_pending", "apply_pending",
    "BackpressureController", "maybe_controller",
]

STATE_VERSION = 1

_RESUMES = telemetry.counter("mxtpu_data_resume_total")
_REMAP_SAMPLES = telemetry.gauge("mxtpu_data_remap_samples")

_log = logging.getLogger(__name__)


def enabled():
    """MXNET_TPU_DATA_RESUME gate (default on): write ``data_state``
    into checkpoint manifests and restore it on resume."""
    from . import config
    return str(config.get("MXNET_TPU_DATA_RESUME", "1")) not in (
        "0", "false", "False")


def backpressure_enabled():
    """MXNET_TPU_BACKPRESSURE gate (default off): auto-install the
    controller over the training iterator in ``fit``."""
    from . import config
    return str(config.get("MXNET_TPU_BACKPRESSURE", "0")) in (
        "1", "true", "True")


# ------------------------------------------------------- state contract

def check_state(state, kind, version=STATE_VERSION):
    """Validate a ``state()`` dict against the expected kind tag and
    version ceiling; returns it.  Every ``restore()`` implementation
    calls this FIRST (validate-then-commit: a rejected state leaves the
    iterator untouched)."""
    if not isinstance(state, dict):
        raise MXNetError(
            "data state must be a dict from state(), got %r"
            % type(state).__name__)
    v = state.get("v")
    if not isinstance(v, int) or v < 1 or v > version:
        raise MXNetError(
            "data state version %r not supported (this build reads "
            "v<=%d)" % (v, version))
    if state.get("kind") != kind:
        raise MXNetError(
            "data state kind mismatch: state is %r, iterator expects "
            "%r — restore into the iterator class that produced the "
            "state" % (state.get("kind"), kind))
    return state


def iter_state(it):
    """``it.state()`` or None.  Never raises: state capture at save
    time is best-effort — a pipeline that cannot describe itself must
    not kill the checkpoint that asked."""
    fn = getattr(it, "state", None)
    if not callable(fn):
        return None
    try:
        st = fn()
    except Exception:  # mxlint: allow-broad-except(advisory state capture from arbitrary user iterators must never kill the checkpoint save that asked for it)
        return None
    return st if isinstance(st, dict) else None


def restore_iterator(it, state):
    """The restore front door: fire the ``io.resume`` chaos seam, then
    ``it.restore(state)``.

    The seam fires BEFORE any iterator mutation, and every tier's
    ``restore()`` validates before it commits — so an injected (or
    real) mid-restore fault surfaces as a descriptive
    :class:`~mxnet_tpu.base.MXNetError` with the iterator still
    restartable from the very same state.  ``state=None`` is a no-op
    (a stateless pipeline has nothing to restore)."""
    if state is None:
        return
    try:
        resilience.fault_point("io.resume")
    except resilience.FaultInjected as e:
        raise MXNetError(
            "data-state restore aborted by the io.resume seam before "
            "any iterator mutation — the iterator is unchanged and the "
            "same state can be restored again: %s" % e) from e
    fn = getattr(it, "restore", None)
    if not callable(fn):
        raise MXNetError(
            "%s has no restore(): the checkpoint carries a data_state "
            "entry but this iterator cannot consume it (resume with "
            "the iterator class that produced it, or set "
            "MXNET_TPU_DATA_RESUME=0)" % type(it).__name__)
    fn(state)
    _RESUMES.inc()
    from .telemetry import flight
    flight.record("data_resume", state_kind=state.get("kind"),
                  epoch=state.get("epoch"))
    # ride the launch.py run timeline too (same route as reshard
    # breadcrumbs); no-op without MXNET_TPU_TELEMETRY_JSONL
    telemetry.jsonl_event("data_resume", kind=state.get("kind"),
                          epoch=state.get("epoch"))


# -------------------------------------------------- global sample ledger

def epoch_permutation(seed, epoch, n):
    """The deterministic global sample order for one epoch: a
    permutation of ``range(n)`` keyed by (seed, epoch) alone — any
    process at any world size derives the identical order."""
    key = (int(seed) * 1000003 + int(epoch) * 9973 + 0x9e3779b9) \
        % (1 << 32)
    return np.random.RandomState(key).permutation(int(n))


def rank_stream(perm, rank, world):
    """Rank ``rank``-of-``world``'s sample ids: STRIDED positions
    ``rank, rank+world, ...`` of the epoch permutation.  Strided (not
    block) assignment is what makes lockstep cursors a contiguous
    global prefix — the invariant the elastic remap rests on."""
    if not 0 <= int(rank) < int(world):
        raise MXNetError("rank %r out of range for world %r"
                         % (rank, world))
    return perm[int(rank)::int(world)]


def remap_cursor(global_consumed, new_rank, new_world):
    """The new rank's local cursor: the count of its strided positions
    already inside the consumed prefix ``perm[:global_consumed]`` —
    i.e. the smallest ``k`` with ``new_rank + k*new_world >=
    global_consumed``."""
    g, r, w = int(global_consumed), int(new_rank), int(new_world)
    if g <= r:
        return 0
    return (g - r + w - 1) // w


class SampleLedger:
    """The deterministic global sample ledger for one dataset: per
    epoch, ONE permutation every process can derive, cut into per-rank
    strided streams.  Lockstep training (one batch per rank per step —
    the SPMD contract) means the union of all rank cursors is always
    the prefix ``perm[:cursor*world]``, so cursors remap exactly across
    world-size changes."""

    def __init__(self, num_samples, seed=0):
        self.num_samples = int(num_samples)
        self.seed = int(seed)
        self._cache = (None, None)   # (epoch, perm)

    def permutation(self, epoch):
        ep = int(epoch)
        if self._cache[0] != ep:
            self._cache = (ep, epoch_permutation(self.seed, ep,
                                                 self.num_samples))
        return self._cache[1]

    def rank_ids(self, epoch, rank, world):
        """This rank's full epoch stream of global sample ids."""
        return rank_stream(self.permutation(epoch), rank, world)

    def global_consumed(self, cursor, world):
        """Globally-consumed prefix length implied by lockstep rank
        cursors of ``cursor`` samples each (clamped at the tail, where
        short strides exhaust early)."""
        return min(int(cursor) * int(world), self.num_samples)

    def consumed_ids(self, epoch, cursor, world):
        """The set of sample ids consumed across ALL ranks at lockstep
        cursor ``cursor`` — the accounting harness's ground truth."""
        g = self.global_consumed(cursor, world)
        return self.permutation(epoch)[:g]


def remap_state(state, new_rank, new_world):
    """Re-cut a :class:`ShardedLedgerIter` state for a new world size.

    Pure function (the input dict is not mutated): validates, fires the
    ``io.remap`` chaos seam BEFORE computing anything, derives the
    globally-consumed prefix from the old lockstep cursor, and returns
    the state rank ``new_rank``-of-``new_world`` resumes from.  The
    no-drop/no-double guarantee is structural: old and new streams are
    strided cuts of the SAME permutation, split at the same prefix
    boundary."""
    check_state(state, "ledger")
    try:
        resilience.fault_point("io.remap")
    except resilience.FaultInjected as e:
        raise MXNetError(
            "elastic cursor remap aborted by the io.remap seam — no "
            "state was derived and the same remap can be retried: %s"
            % e) from e
    n = int(state["num_samples"])
    g = min(int(state["cursor"]) * int(state["world"]), n)
    new_cursor = remap_cursor(g, new_rank, new_world)
    _REMAP_SAMPLES.set(g)
    from .telemetry import flight
    flight.record("data_remap", old_world=int(state["world"]),
                  new_world=int(new_world), new_rank=int(new_rank),
                  global_consumed=g, epoch=int(state["epoch"]))
    telemetry.jsonl_event("data_remap", old_world=int(state["world"]),
                          new_world=int(new_world),
                          global_consumed=g)
    _log.info("elastic data remap: %d/%d samples consumed at world %d "
              "-> rank %d/%d resumes at local cursor %d",
              g, n, int(state["world"]), int(new_rank), int(new_world),
              new_cursor)
    out = dict(state)
    out.update(rank=int(new_rank), world=int(new_world),
               cursor=new_cursor)
    return out


class ShardedLedgerIter:
    """Deterministic data-parallel iterator over in-memory arrays,
    sharded through a :class:`SampleLedger`.

    Every batch carries its global sample ids in ``DataBatch.index``
    (real samples only — tail padding wraps data but never ids), so a
    consumed-id log plus :class:`SampleAccountant` can PROVE the
    exactly-once property end to end.  ``state()``/``restore()`` follow
    the durable-state contract; restoring a state saved at a different
    world size re-cuts the cursor through :func:`remap_state`."""

    def __init__(self, data, label=None, batch_size=32, seed=0,
                 rank=0, world=1, data_name="data",
                 label_name="softmax_label"):
        from .io import DataDesc, _init_data
        self.data = _init_data(data, allow_empty=False,
                               default_name=data_name)
        self.label = _init_data(label, allow_empty=True,
                                default_name=label_name)
        self.batch_size = int(batch_size)
        n = self.data[0][1].shape[0]
        for k, v in self.data + self.label:
            if v.shape[0] != n:
                raise MXNetError("array %r has %d samples, expected %d"
                                 % (k, v.shape[0], n))
        self.ledger = SampleLedger(n, seed=seed)
        self._rank = int(rank)
        self._world = int(world)
        self._epoch = 0
        self._cursor = 0             # samples this rank delivered
        self._ids = self.ledger.rank_ids(0, self._rank, self._world)
        self.provide_data = [
            DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])),
                     v.dtype) for k, v in self.data]
        self.provide_label = [
            DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])),
                     v.dtype) for k, v in self.label]

    def __iter__(self):
        return self

    def reset(self):
        self._epoch += 1
        self._cursor = 0
        self._ids = self.ledger.rank_ids(self._epoch, self._rank,
                                         self._world)

    def position(self):
        return {"epoch": self._epoch, "shard": self._rank,
                "num_shards": self._world, "offset": int(self._cursor)}

    def state(self):
        return {"v": STATE_VERSION, "kind": "ledger",
                "epoch": self._epoch, "cursor": int(self._cursor),
                "seed": self.ledger.seed, "rank": self._rank,
                "world": self._world,
                "num_samples": self.ledger.num_samples}

    def restore(self, state):
        check_state(state, "ledger")
        if int(state["num_samples"]) != self.ledger.num_samples or \
                int(state["seed"]) != self.ledger.seed:
            raise MXNetError(
                "ledger state mismatch: state has %s samples / seed "
                "%s, iterator has %d / %d — the ledger identity "
                "(dataset size + seed) must match for an exact resume"
                % (state["num_samples"], state["seed"],
                   self.ledger.num_samples, self.ledger.seed))
        if int(state["world"]) != self._world or \
                int(state["rank"]) != self._rank:
            state = remap_state(state, self._rank, self._world)
        epoch, cursor = int(state["epoch"]), int(state["cursor"])
        ids = self.ledger.rank_ids(epoch, self._rank, self._world)
        if cursor > len(ids):
            raise MXNetError(
                "ledger cursor %d beyond this rank's %d-sample epoch "
                "stream" % (cursor, len(ids)))
        self._epoch, self._cursor, self._ids = epoch, cursor, ids

    def next(self):
        from .io import DataBatch
        from .ndarray import array as nd_array
        ids = self._ids[self._cursor:self._cursor + self.batch_size]
        if len(ids) == 0:
            raise StopIteration
        pad = self.batch_size - len(ids)
        take = np.asarray(ids, dtype=np.int64)
        if pad:
            # wrap-pad the tail with real samples (their ids are NOT
            # re-reported: batch.index stays the real ids only)
            take = np.concatenate(
                [take, np.asarray(self._ids[:pad], dtype=np.int64)])
        batch = DataBatch(
            data=[nd_array(v[take]) for _, v in self.data],
            label=[nd_array(v[take]) for _, v in self.label],
            pad=pad, index=np.asarray(ids, dtype=np.int64),
            provide_data=self.provide_data,
            provide_label=self.provide_label)
        self._cursor += len(ids)
        return batch

    __next__ = next


class SampleAccountant:
    """The exactly-once proof harness: feed it every consumed sample id
    (across legs, ranks, and restarts of one epoch) and ask for the
    verdict — which ids were dropped, which were double-seen."""

    def __init__(self, num_samples):
        self.num_samples = int(num_samples)
        self._counts = {}

    def record(self, ids):
        for i in np.asarray(ids).reshape(-1):
            i = int(i)
            self._counts[i] = self._counts.get(i, 0) + 1

    def verdict(self):
        dropped = [i for i in range(self.num_samples)
                   if i not in self._counts]
        double = sorted(i for i, c in self._counts.items() if c > 1)
        alien = sorted(i for i in self._counts
                       if not 0 <= i < self.num_samples)
        return {"ok": not dropped and not double and not alien,
                "consumed": len(self._counts), "dropped": dropped,
                "double": double, "alien": alien}


# ------------------------------------------- manifest <-> fit plumbing

_pending_lock = threading.Lock()
_pending = [None]


def data_state_entry(it=None):
    """The checkpoint manifest's ``data_state`` value: a versioned
    wrapper around the tracked (or given) iterator's durable state and
    advisory position.  None when resume is disabled or the pipeline
    reports nothing — the manifest key is simply omitted then."""
    if not enabled():
        return None
    st = _ioview.current_state() if it is None else iter_state(it)
    pos = _ioview.current_position() if it is None else None
    if st is None and pos is None:
        return None
    return {"v": STATE_VERSION, "state": st, "position": pos}


def note_loaded_state(entry, source=None):
    """Stash the ``data_state`` entry a checkpoint loader found; the
    next :func:`apply_pending` (from ``fit`` or
    ``ShardedTrainer.restore_data_iter``) consumes it.  Malformed or
    future-versioned entries are logged and dropped — an old build
    resuming a new checkpoint degrades to the legacy start-of-epoch
    behavior instead of dying."""
    if entry is None or not enabled():
        return
    if not isinstance(entry, dict) or \
            not isinstance(entry.get("v"), int) or \
            entry["v"] > STATE_VERSION:
        _log.warning(
            "checkpoint %s carries a data_state entry this build "
            "cannot read (%r) — resuming from the start of the epoch",
            source or "", entry if not isinstance(entry, dict)
            else entry.get("v"))
        return
    with _pending_lock:
        _pending[0] = dict(entry, source=source)


def pending_state():
    """The stashed (not yet applied) manifest entry, or None."""
    with _pending_lock:
        return dict(_pending[0]) if _pending[0] is not None else None


def clear_pending():
    with _pending_lock:
        _pending[0] = None


def apply_pending(it):
    """Restore the stashed manifest ``data_state`` into ``it`` via
    :func:`restore_iterator`.  Returns the consumed entry, or None when
    nothing was pending / the entry carried no state.  A restore error
    propagates but LEAVES the entry pending, so a retry (or a clean
    restore after a chaos fault) can re-apply the same state."""
    entry = pending_state()
    if entry is None:
        return None
    st = entry.get("state")
    if st is None:
        clear_pending()
        return None
    restore_iterator(it, st)
    clear_pending()
    _log.info("resumed data iterator from checkpoint %s: %s",
              entry.get("source") or "", st)
    return entry


# ------------------------------------------------ backpressure control

class BackpressureController:
    """Close the bottleneck-verdict loop: producer-bound windows raise
    pipeline capacity knobs, consumer-bound windows lower them back.

    Hysteresis: a knob moves only after ``confirm`` CONSECUTIVE windows
    with the same non-balanced verdict, and then rests ``cooldown``
    ticks — one slow batch never thrashes the pipeline.  Every move is
    telemetered (``mxtpu_backpressure_adjust_total{knob,direction}``, a
    ``backpressure_adjust`` flight event, a log line) and kept on
    ``self.adjustments`` for harnesses."""

    def __init__(self, confirm=2, cooldown=2):
        self._knobs = []             # (name, get, set, lo, hi)
        self._streak = {"producer-bound": 0, "consumer-bound": 0}
        self._cool = 0
        self.confirm = int(confirm)
        self.cooldown = int(cooldown)
        self.adjustments = []

    def register(self, name, getter, setter, lo, hi):
        """Register a tunable int knob with its clamp range."""
        self._knobs.append((name, getter, setter, int(lo), int(hi)))
        return self

    def attach(self, it):
        """Walk the iterator wrapper chain and register every knob it
        exposes (today: ``DevicePrefetchIter`` staging depth).  Returns
        the number of knobs registered."""
        n = 0
        seen = set()
        stack = [it]
        while stack:
            obj = stack.pop()
            if id(obj) in seen or obj is None:
                continue
            seen.add(id(obj))
            if callable(getattr(obj, "set_depth", None)) and \
                    callable(getattr(obj, "depth", None)):
                hi = max(8, 4 * obj.depth())
                self.register("device_prefetch_depth", obj.depth,
                              obj.set_depth, 1, hi)
                n += 1
            for attr in ("_it", "data_iter", "_inner"):
                stack.append(getattr(obj, attr, None))
            stack.extend(getattr(obj, "iters", None) or [])
        return n

    def _move(self, direction, stage):
        delta = 1 if direction == "raise" else -1
        for name, get, set_, lo, hi in self._knobs:
            cur = int(get())
            new = min(hi, max(lo, cur + delta))
            if new == cur:
                continue
            set_(new)
            telemetry.counter("mxtpu_backpressure_adjust_total").labels(
                knob=name, direction=direction).inc()
            from .telemetry import flight
            flight.record("backpressure_adjust", knob=name,
                          direction=direction, value=new,
                          stage=stage or "")
            telemetry.jsonl_event("backpressure_adjust", knob=name,
                                  direction=direction, value=new,
                                  stage=stage or "")
            _log.info("backpressure: %s %s %d -> %d (verdict stage %s)",
                      direction, name, cur, new, stage)
            self.adjustments.append(
                {"knob": name, "direction": direction, "from": cur,
                 "to": new, "stage": stage})
            return True
        return False

    def tick(self, verdict=None, force=False):
        """One control step.  Reads the live classifier (its own
        window cadence — between windows the last verdict repeats and
        only FRESH verdicts feed the streaks) unless a verdict dict is
        passed in.  Returns the adjustment made, or None."""
        if verdict is None:
            last = _ioview.classify(force=force)
            if last is self._last_seen():
                return None          # no new window yet
            self._note_seen(last)
            verdict = last
        if self._cool > 0:
            self._cool -= 1
            return None
        kind = (verdict or {}).get("verdict")
        if kind not in self._streak:
            for k in self._streak:
                self._streak[k] = 0
            return None
        self._streak[kind] += 1
        for k in self._streak:
            if k != kind:
                self._streak[k] = 0
        if self._streak[kind] < self.confirm:
            return None
        moved = self._move(
            "raise" if kind == "producer-bound" else "lower",
            (verdict or {}).get("stage"))
        if moved:
            self._streak[kind] = 0
            self._cool = self.cooldown
            return self.adjustments[-1]
        return None

    # identity-compare the classifier's verdict dict to detect window
    # rotation: classify() returns the SAME object until a new window
    # commits, so a repeat never double-feeds the hysteresis streaks
    _seen = None

    def _last_seen(self):
        return self._seen

    def _note_seen(self, v):
        self._seen = v


def maybe_controller(it):
    """Install a :class:`BackpressureController` over ``it`` when
    MXNET_TPU_BACKPRESSURE is on and the chain exposes at least one
    knob; None otherwise.  The caller owns the tick cadence (``fit``
    ticks once per batch)."""
    if not backpressure_enabled():
        return None
    ctl = BackpressureController()
    if ctl.attach(it) == 0:
        _log.info("MXNET_TPU_BACKPRESSURE set but the iterator chain "
                  "exposes no tunable knob (no DevicePrefetchIter) — "
                  "controller not installed")
        return None
    return ctl
