"""Evaluation metrics.

Reference: ``python/mxnet/metric.py`` (1057 L) — EvalMetric registry updated
per batch by the Module training loop (`base_module.py:495`).
"""
from __future__ import annotations

import math

import numpy

from .base import MXNetError
from . import ndarray
from . import telemetry as _telemetry
from .ndarray import NDArray
from . import registry as _registry_mod

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "Perplexity", "MAE", "MSE", "RMSE", "CrossEntropy", "Loss",
           "CustomMetric", "np", "create"]


def check_label_shapes(labels, preds, shape=0):
    if shape == 0:
        label_shape, pred_shape = len(labels), len(preds)
    else:
        label_shape, pred_shape = labels.shape, preds.shape
    if label_shape != pred_shape:
        raise ValueError("Shape of labels {} does not match shape of "
                         "predictions {}".format(label_shape, pred_shape))


class EvalMetric:
    """Base evaluation metric (reference metric.py EvalMetric)."""

    def __init__(self, name, num=None):
        self.name = name
        self.num = num
        self.reset()

    def reset(self):
        if self.num is None:
            self.num_inst = 0
            self.sum_metric = 0.0
        else:
            self.num_inst = [0] * self.num
            self.sum_metric = [0.0] * self.num
        # non-finite batch values rejected by _accum since the last
        # reset (also counted into mxtpu_nonfinite_total{tensor=
        # "metric/<name>"} — telemetry.numerics surface)
        self.num_nonfinite = 0

    def update(self, labels, preds):
        raise NotImplementedError()

    def _accum(self, value, count=1):
        """Fold one batch statistic into the running average — UNLESS
        it is non-finite, in which case it is counted and surfaced
        (``mxtpu_nonfinite_total{tensor="metric/<name>"}``) instead of
        silently poisoning every later ``get()`` (one NaN batch used
        to turn the whole epoch's metric into NaN)."""
        value = float(value)
        if not math.isfinite(value):
            # getattr: a subclass overriding reset() without super()
            # must not turn the guard itself into an AttributeError
            self.num_nonfinite = getattr(self, "num_nonfinite", 0) + 1
            _telemetry.counter("mxtpu_nonfinite_total").labels(
                tensor="metric/%s" % self.name).inc()
            import logging
            logging.getLogger(__name__).warning(
                "metric %r: dropping non-finite update value %r "
                "(%d so far; see mxtpu_nonfinite_total)",
                self.name, value, self.num_nonfinite)
            return
        self.sum_metric += value
        self.num_inst += count

    def get(self):
        if self.num is None:
            if self.num_inst == 0:
                return (self.name, float("nan"))
            return (self.name, self.sum_metric / self.num_inst)
        names = ["%s_%d" % (self.name, i) for i in range(self.num)]
        values = [x / y if y != 0 else float("nan")
                  for x, y in zip(self.sum_metric, self.num_inst)]
        return (names, values)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def __str__(self):
        return "EvalMetric: {}".format(dict(self.get_name_value()))


_register = _registry_mod.get_register_func(EvalMetric, "metric")
_alias = _registry_mod.get_alias_func(EvalMetric, "metric")
_create = _registry_mod.get_create_func(EvalMetric, "metric")


def create(metric, num=None, **kwargs):
    """Create metric from name / callable / list (reference metric.create)."""
    if callable(metric):
        return CustomMetric(metric, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for child in metric:
            composite.add(create(child, num, **kwargs))
        return composite
    if num is not None:
        kwargs["num"] = num
    try:
        return _create(metric, **kwargs)
    except TypeError:
        kwargs.pop("num", None)
        return _create(metric, **kwargs)


class CompositeEvalMetric(EvalMetric):
    """Manage multiple metrics as one (reference CompositeEvalMetric)."""

    def __init__(self, metrics=None, **kwargs):
        super().__init__("composite", **kwargs)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        try:
            return self.metrics[index]
        except IndexError:
            return ValueError("Metric index {} is out of range 0 and {}"
                              .format(index, len(self.metrics)))

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        try:
            for metric in self.metrics:
                metric.reset()
        except AttributeError:
            pass

    def get(self):
        names, results = [], []
        for metric in self.metrics:
            name, result = metric.get()
            if isinstance(name, str):
                name = [name]
            if not isinstance(result, list):
                result = [result]
            names.extend(name)
            results.extend(result)
        return names, results


@_register
@_alias("acc")
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy"):
        super().__init__(name)
        self.axis = axis

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred_label in zip(labels, preds):
            pred = pred_label.asnumpy() if isinstance(pred_label, NDArray) \
                else numpy.asarray(pred_label)
            lab = label.asnumpy() if isinstance(label, NDArray) \
                else numpy.asarray(label)
            if pred.shape != lab.shape:
                pred = numpy.argmax(pred, axis=self.axis)
            pred = pred.astype("int32").flat
            lab = lab.astype("int32").flat
            check_label_shapes(numpy.array(lab), numpy.array(pred))
            self.sum_metric += (numpy.array(lab) == numpy.array(pred)).sum()
            self.num_inst += len(numpy.array(lab))


@_register
@_alias("top_k_accuracy", "top_k_acc")
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy"):
        super().__init__(name)
        self.top_k = top_k
        assert self.top_k > 1, "Please use Accuracy if top_k is no more than 1"
        self.name += "_%d" % self.top_k

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred_label in zip(labels, preds):
            pred = numpy.argsort(pred_label.asnumpy().astype("float32"), axis=1)
            lab = label.asnumpy().astype("int32")
            num_samples = pred.shape[0]
            num_dims = len(pred.shape)
            if num_dims == 1:
                self.sum_metric += (pred.flat == lab.flat).sum()
            elif num_dims == 2:
                num_classes = pred.shape[1]
                top_k = min(num_classes, self.top_k)
                for j in range(top_k):
                    self.sum_metric += (
                        pred[:, num_classes - 1 - j].flat == lab.flat).sum()
            self.num_inst += num_samples


@_register
class F1(EvalMetric):
    """Binary-classification F1 (reference metric.py F1)."""

    def __init__(self, name="f1"):
        super().__init__(name)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            pred = pred.asnumpy()
            label = label.asnumpy().astype("int32")
            pred_label = numpy.argmax(pred, axis=1)
            check_label_shapes(label, pred)
            if len(numpy.unique(label)) > 2:
                raise ValueError("F1 currently only supports binary "
                                 "classification.")
            true_pos = ((pred_label == 1) & (label == 1)).sum()
            false_pos = ((pred_label == 1) & (label == 0)).sum()
            false_neg = ((pred_label == 0) & (label == 1)).sum()
            precision = true_pos / (true_pos + false_pos) \
                if true_pos + false_pos > 0 else 0.0
            recall = true_pos / (true_pos + false_neg) \
                if true_pos + false_neg > 0 else 0.0
            f1_score = 0.0
            if precision + recall > 0:
                f1_score = 2 * precision * recall / (precision + recall)
            self._accum(f1_score)


@_register
class Perplexity(EvalMetric):
    """Reference metric.py Perplexity: exp(sum CE / n)."""

    def __init__(self, ignore_label=None, axis=-1, name="perplexity"):
        super().__init__(name)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        assert len(labels) == len(preds)
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            lab = label.asnumpy().astype("int32").reshape(-1)
            prob = pred.asnumpy().reshape(-1, pred.shape[-1] if self.axis == -1
                                          else pred.shape[self.axis])
            probs = prob[numpy.arange(lab.size), lab]
            if self.ignore_label is not None:
                ignore = (lab == self.ignore_label).astype(probs.dtype)
                num -= int(ignore.sum())
                probs = probs * (1 - ignore) + ignore
            loss -= numpy.sum(numpy.log(numpy.maximum(1e-10, probs)))
            num += lab.size
        # reference metric.py Perplexity accumulates raw (loss, count) and
        # applies exp once in get() — corpus perplexity over all tokens
        self._accum(loss, num)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, float(numpy.exp(self.sum_metric / self.num_inst)))


@_register
class MAE(EvalMetric):
    def __init__(self, name="mae"):
        super().__init__(name)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = label.asnumpy()
            pred = pred.asnumpy()
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            self._accum(numpy.abs(label - pred).mean())


@_register
class MSE(EvalMetric):
    def __init__(self, name="mse"):
        super().__init__(name)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = label.asnumpy()
            pred = pred.asnumpy()
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            self._accum(((label - pred) ** 2.0).mean())


@_register
class RMSE(EvalMetric):
    def __init__(self, name="rmse"):
        super().__init__(name)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = label.asnumpy()
            pred = pred.asnumpy()
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            self._accum(numpy.sqrt(((label - pred) ** 2.0).mean()))


@_register
@_alias("ce")
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-8, name="cross-entropy"):
        super().__init__(name)
        self.eps = eps

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = label.asnumpy()
            pred = pred.asnumpy()
            label = label.ravel()
            assert label.shape[0] == pred.shape[0]
            prob = pred[numpy.arange(label.shape[0]), numpy.int64(label)]
            self._accum((-numpy.log(prob + self.eps)).sum(),
                        label.shape[0])


@_register
class Loss(EvalMetric):
    """Average of the raw outputs (for MakeLoss-style nets)."""

    def __init__(self, name="loss"):
        super().__init__(name)

    def update(self, _, preds):
        for pred in preds:
            self._accum(ndarray.sum(pred).asnumpy().sum(), pred.size)


@_register
class Torch(Loss):
    def __init__(self, name="torch"):
        super().__init__(name)


@_register
class Caffe(Loss):
    def __init__(self, name="caffe"):
        super().__init__(name)


@_register
class CustomMetric(EvalMetric):
    """Wrap a python feval(label, pred) (reference CustomMetric)."""

    def __init__(self, feval, name=None, allow_extra_outputs=False):
        if name is None:
            name = feval.__name__
            if name.find("<") != -1:
                name = "custom(%s)" % name
        super().__init__(name)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds)
        for pred, label in zip(preds, labels):
            label = label.asnumpy()
            pred = pred.asnumpy()
            reval = self._feval(label, pred)
            if isinstance(reval, tuple):
                (sum_metric, num_inst) = reval
                self._accum(sum_metric, num_inst)
            else:
                self._accum(reval)


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Wrap a numpy feval into a CustomMetric (reference metric.np)."""
    def feval(label, pred):
        return numpy_feval(label, pred)
    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)
