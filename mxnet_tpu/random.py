"""Global PRNG state: ``mx.random.seed`` and key threading.

Reference: per-device stateful ``mshadow::Random<xpu>`` resource
(`src/resource.cc:136-186`, seeded via `mx.random.seed`).  JAX is functional:
we keep one root key per process, split on demand (SURVEY §7 'hard parts':
RNG).  Symbolic executors draw a fresh subkey per forward; imperative
stochastic ops draw via :func:`take_key`.
"""
from __future__ import annotations

import threading

__all__ = ["seed", "take_key", "uniform", "normal"]

_state = threading.local()
_DEFAULT_SEED = 0


def _key():
    import jax
    if not hasattr(_state, "key"):
        _state.key = jax.random.PRNGKey(_DEFAULT_SEED)
    return _state.key


def seed(seed_state):
    """Seed the global generator (reference MXRandomSeed, c_api.cc)."""
    import jax
    _state.key = jax.random.PRNGKey(int(seed_state))


def take_key():
    """Split off a fresh subkey (advances global state)."""
    import jax
    k, sub = jax.random.split(_key())
    _state.key = k
    return sub


# Convenience samplers mirroring mx.random.* (reference python/mxnet/random.py)
def uniform(low=0.0, high=1.0, shape=(1,), ctx=None, dtype="float32", out=None):
    from . import ndarray as nd
    return getattr(nd, "_random_uniform")(low=low, high=high, shape=shape,
                                          dtype=dtype, out=out)


def normal(loc=0.0, scale=1.0, shape=(1,), ctx=None, dtype="float32", out=None):
    from . import ndarray as nd
    return getattr(nd, "_random_normal")(loc=loc, scale=scale, shape=shape,
                                         dtype=dtype, out=out)
