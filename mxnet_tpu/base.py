"""Base types, dtype table and error classes.

TPU-native re-design of the reference's core type layer
(`include/mxnet/base.h`, `python/mxnet/base.py`).  There is no C ABI here:
the framework is a single Python package over JAX/XLA, so `base` only holds
the shared primitives every layer uses — dtype mapping, shape type, errors,
and the generic registry (reference: dmlc-core ``Registry`` role, SURVEY §2.2).
"""
from __future__ import annotations

import numpy as _np

__all__ = [
    "MXNetError", "TShape", "DTYPE_TO_NP", "NP_TO_DTYPE", "dtype_np",
    "dtype_id", "string_types", "numeric_types",
]


class MXNetError(RuntimeError):
    """Framework error type (reference: dmlc::Error surfaced via MXGetLastError)."""


string_types = (str,)
numeric_types = (float, int, _np.generic)

# Reference dtype ids (mshadow `kFloat32..kInt8` order used in saved params
# and the C API).  Kept numerically identical so checkpoint interop works.
DTYPE_ID_TO_NP = {
    0: _np.float32,
    1: _np.float64,
    2: _np.float16,
    3: _np.uint8,
    4: _np.int32,
    5: _np.int8,
    6: _np.int64,
    # TPU-native extension: bfloat16 (no reference id; appended after int64).
    7: "bfloat16",
}


def _bfloat16():
    import jax.numpy as jnp
    return jnp.bfloat16


def dtype_np(dtype):
    """Normalize a user dtype spec (str, np.dtype, id) to a numpy dtype."""
    if dtype is None:
        return _np.dtype(_np.float32)
    if isinstance(dtype, int) and not isinstance(dtype, _np.dtype):
        dtype = DTYPE_ID_TO_NP[dtype]
    if dtype == "bfloat16" or getattr(dtype, "__name__", None) == "bfloat16":
        return _np.dtype(_bfloat16())
    return _np.dtype(dtype)


def dtype_id(dtype):
    """Numpy dtype -> reference dtype id (for save format parity)."""
    d = dtype_np(dtype)
    for k, v in DTYPE_ID_TO_NP.items():
        if v == "bfloat16":
            if d.name == "bfloat16":
                return k
        elif _np.dtype(v) == d:
            return k
    raise MXNetError(f"unsupported dtype {dtype}")


# Convenience maps (strings only; bfloat16 resolved lazily)
DTYPE_TO_NP = {v if isinstance(v, str) else _np.dtype(v).name: v
               for v in DTYPE_ID_TO_NP.values()}
NP_TO_DTYPE = {}


class TShape(tuple):
    """Shape tuple (reference: nnvm TShape).  Plain tuple with helpers."""

    @property
    def ndim(self):
        return len(self)

    @property
    def size(self):
        s = 1
        for x in self:
            s *= int(x)
        return s


class _Registry:
    """Generic name->object registry (reference: dmlc Registry / python/mxnet/registry.py)."""

    def __init__(self, kind):
        self.kind = kind
        self._map = {}

    def register(self, obj, name=None, override=False):
        key = (name or getattr(obj, "__name__", None) or str(obj)).lower()
        if key in self._map and not override:
            import warnings
            warnings.warn(f"{self.kind} {key} already registered; overriding")
        self._map[key] = obj
        return obj

    def get(self, name):
        key = str(name).lower()
        if key not in self._map:
            raise MXNetError(f"unknown {self.kind}: {name}. "
                             f"known: {sorted(self._map)}")
        return self._map[key]

    def find(self, name):
        return self._map.get(str(name).lower())

    def names(self):
        return sorted(self._map)
