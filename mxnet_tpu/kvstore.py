"""KVStore: parameter synchronization facade.

Reference: ``include/mxnet/kvstore.h`` + ``src/kvstore/`` (SURVEY §5.8).
TPU-native design: the reference's two-level hierarchy (intra-node Comm
reduce/broadcast + inter-node ps-lite) is replaced by

* ``local`` / ``device``: in-process reduce across per-device arrays — XLA
  executes the sum; ``device`` and ``local`` coincide because jax.Arrays
  already live on device (the CPU-staging split of CommCPU vs CommDevice,
  `comm.h:60-385`, is moot on TPU).
* ``dist_sync`` / ``dist_async``: multi-host collectives over ICI/DCN via
  ``jax.distributed`` — see :mod:`mxnet_tpu.parallel`.  The ps-lite
  push/pull RPC protocol (`kvstore_dist.h`) is replaced wholesale by psum;
  sync semantics (sum over exactly-N workers) match the reference's server
  aggregation (`kvstore_dist_server.h:164-199`).

The user-facing API (init/push/pull/set_updater/rank/num_workers/barrier)
keeps the reference's shape so Module and user scripts port unchanged;
per-worker per-key push→pull ordering holds trivially (synchronous calls).
"""
from __future__ import annotations

import pickle

import numpy as np

from .base import MXNetError
from . import ndarray
from . import telemetry
from .ndarray import NDArray
from . import optimizer as opt

__all__ = ["KVStore", "create"]

_PUSH_BYTES = telemetry.counter("mxtpu_kvstore_push_bytes_total")
_PULL_BYTES = telemetry.counter("mxtpu_kvstore_pull_bytes_total")


def _nbytes(arr):
    """Size in bytes of one pushed/pulled array (traffic accounting)."""
    n = 1
    for d in arr.shape:
        n *= int(d)
    try:
        return n * np.dtype(arr.dtype).itemsize
    except TypeError:
        return n * 4


def _ctype_key_value(keys, vals):
    """Normalize (key(s), value(s)) into parallel flat lists."""
    if isinstance(keys, (int, str)):
        keys_flat = []
        vals_flat = []
        if isinstance(vals, NDArray):
            return [keys], [vals]
        for v in vals:
            keys_flat.append(keys)
            vals_flat.append(v)
        return keys_flat, vals_flat
    assert len(keys) == len(vals)
    keys_flat, vals_flat = [], []
    for k, v in zip(keys, vals):
        kf, vf = _ctype_key_value(k, v)
        keys_flat.extend(kf)
        vals_flat.extend(vf)
    return keys_flat, vals_flat


def _group_kv_pairs(keys, vals):
    """Group values by key preserving first-appearance order
    (reference GroupKVPairs, kvstore_local.h:92-118)."""
    uniq, grouped = [], {}
    for k, v in zip(keys, vals):
        if k not in grouped:
            uniq.append(k)
            grouped[k] = []
        grouped[k].append(v)
    return uniq, [grouped[k] for k in uniq]


class KVStore:
    """Single-process store (types 'local', 'device')."""

    def __init__(self, kv_type="local"):
        self._type = kv_type
        self._store = {}
        self._updater = None
        self._optimizer_states = None
        # label children bound once (push/pull run per parameter sync
        # per step — the hot-path pattern, see docs/api/telemetry.md)
        self._push_bytes = _PUSH_BYTES.labels(store=kv_type)
        self._pull_bytes = _PULL_BYTES.labels(store=kv_type)

    # ----------------------------------------------------------------- info
    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    def get_rank(self):
        return self.rank

    def get_group_size(self):
        return self.num_workers

    # ------------------------------------------------------------------ api
    def init(self, key, value):
        keys, vals = _ctype_key_value(key, value)
        for k, v in zip(keys, vals):
            if k in self._store:
                raise MXNetError("duplicate init of key %s" % str(k))
            self._store[k] = v.copy()

    def push(self, key, value, priority=0):
        keys, vals = _ctype_key_value(key, value)
        self._push_bytes.inc(sum(_nbytes(v) for v in vals))
        uniq, grouped = _group_kv_pairs(keys, vals)
        for k, group in zip(uniq, grouped):
            merged = group[0].copy()
            for other in group[1:]:
                merged += other
            if self._updater is not None:
                if k not in self._store:
                    raise MXNetError("key %s has not been inited" % str(k))
                self._updater(k, merged, self._store[k])
            else:
                self._store[k] = merged

    def pull(self, key, out=None, priority=0):
        assert out is not None
        keys, outs = _ctype_key_value(key, out)
        self._pull_bytes.inc(sum(_nbytes(o) for o in outs))
        for k, o in zip(keys, outs):
            if k not in self._store:
                raise MXNetError("key %s has not been inited" % str(k))
            o[:] = self._store[k]

    # ------------------------------------------------------------- updater
    def set_updater(self, updater):
        self._updater = updater

    _set_updater = set_updater

    def set_optimizer(self, optimizer):
        """Use ``optimizer`` for server-side updates.  Single-process:
        equivalent to a local updater (reference routes this through a
        pickled command to dist servers, kvstore.py:226-270)."""
        self._updater_obj = opt.get_updater(optimizer)
        self.set_updater(
            lambda key, grad, weight: self._updater_obj(key, grad, weight))

    # ---------------------------------------------------------- distributed
    def barrier(self):
        pass

    def send_command_to_servers(self, head, body):
        pass

    def get_num_dead_node(self, node_id, timeout=0):
        return 0

    # ------------------------------------------------------- optim states
    def save_optimizer_states(self, fname):
        assert getattr(self, "_updater_obj", None) is not None, \
            "Cannot save states for distributed training"
        with open(fname, "wb") as fout:
            fout.write(self._updater_obj.get_states())

    def load_optimizer_states(self, fname):
        assert getattr(self, "_updater_obj", None) is not None, \
            "Cannot load states for distributed training"
        with open(fname, "rb") as fin:
            self._updater_obj.set_states(fin.read())


def create(name="local"):
    """Create a KVStore (reference kvstore.cc:17-45 name dispatch).

    ``dist_sync``: jitted pytree AllReduce over jax.distributed
    (parallel/dist_kvstore.py).  ``dist_async`` under a launch.py job:
    the host-driven asynchronous parameter server
    (parallel/async_kvstore.py — per-push server-side updates, the
    reference kvstore_dist_server.h:200-208 contract); single-process
    ``dist_async`` falls through to the sync facade with its warning.
    """
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    if "async" in name:
        from . import config
        if (config.get_int("MXNET_TPU_NUM_PROCESSES") or 1) > 1:
            from .parallel.async_kvstore import AsyncKVStore
            return AsyncKVStore(name)
    if "dist" in name:
        from .parallel.dist_kvstore import DistKVStore
        return DistKVStore(name)
    return KVStore(name)
