"""Cost-model-guided whole-graph plan search (ROADMAP item 3).

`analysis.fusion` is a greedy fixed-pattern matcher: the first longest
chain wins, layout choice is purely local, and the Pallas-vs-XLA
lowering is a per-block heuristic the tuning cache can only veto.  The
costdb roofline (PR 7) *measures* the MFU gap those local choices leave
behind but nothing acts on it globally.  This module closes the loop
Relay/TVM-style (PAPERS.md: arXiv:1810.00952, arXiv:1802.04799):

* **search space** — one decision vector over the greedy plan's chain
  candidates: per-chain ``fuse``/``conv_bn``/``bn_act``/``off``
  (``fusion.CHAIN_CHOICES`` — splits the chains the greedy
  longest-chain-wins rule forecloses), per-region layout
  (``NCHW``/``NHWC``, with the explicit boundary relayouts
  ``fusion.apply_block`` inserts costed at peak bandwidth), and a
  per-block Pallas veto.  Chains are keyed by the greedy terminal's
  topo index, so a committed vector survives rebuilds whose auto-
  generated node names differ;
* **objective** — predicted step wall from the learned cost model
  (:mod:`mxnet_tpu.autotune.model`, arXiv:2008.01040) over analytic
  flops/bytes per unit (the same formulas the trace-time costdb notes
  use: ``fusion._note_block_cost`` for fused regions,
  ``analysis.perf.node_cost_estimate`` for the unfused heavies), with
  the roofline-attainable bound as the model-free fallback;
* **search** — deterministic beam search over single-decision
  neighbor moves, the greedy plan always seeded into the population,
  so the searched predicted wall can never regress the greedy one;
* **measurement** — the top-k candidates (plus greedy, always) are
  measured for real with :func:`mxnet_tpu.autotune.measure` on a
  traced forward+backward step of the actual graph, each candidate's
  decisions active at trace time;
* **commit** — the measured winner persists as a ``graph_plan`` entry
  in the ``mxtpu-tunecache/1`` tuning cache, keyed by graph digest
  (``fusion.graph_digest`` — structure, not names) + trace layout +
  mesh + backend.  ``Executor``/``ShardedTrainer`` consult the entry
  at bind time (:func:`committed_decisions`) and activate it around
  every trace, so a tuned plan is picked up on every later run with
  zero search cost — greedy on miss, exactly like kernel configs.

Driver: ``tools/plan_search.py`` (``--model resnet50 --budget N``).
Feedback loop: ``tools/perf_top.py --suggest`` emits ``plan`` rows for
worst-MFU blocks whose graph has an untuned/stale entry, and
``python -m mxnet_tpu.analysis --plan`` reports MXG010 predictions for
the *committed* plan rather than the default lowering.  Env:
``MXNET_TPU_PLAN_SEARCH`` (off|cache), ``MXNET_TPU_PLAN_BUDGET``,
``MXNET_TPU_PLAN_BEAM``.  See docs/api/plansearch.md.
"""
from __future__ import annotations

import json
import os
import threading

from . import fusion as _fusion

__all__ = [
    "OP", "plan_mode", "plan_budget", "plan_beam",
    "lookup_entry", "committed_decisions", "stats", "reset_stats",
    "predict_plan_wall", "chain_moves", "search_plan",
    "build_step_values", "measure_decisions", "search_and_commit",
]

#: the tuning-cache op name of a graph-level plan entry
OP = "graph_plan"

_MODES = ("off", "cache")


def plan_mode():
    """``MXNET_TPU_PLAN_SEARCH``: ``off`` (no bind-time lookups) |
    ``cache`` (default — consult the tuning cache at bind time, greedy
    on miss).  Unknown values read as ``cache``; searching never
    happens implicitly (it is an offline driver / CI action)."""
    v = os.environ.get("MXNET_TPU_PLAN_SEARCH", "cache").strip().lower()
    return v if v in _MODES else "cache"


def _env_int(name, default):
    try:
        return max(1, int(os.environ.get(name, "") or default))
    except ValueError:
        return default


def plan_budget():
    """``MXNET_TPU_PLAN_BUDGET``: max candidate plans the beam search
    scores with the cost model (default 64)."""
    return _env_int("MXNET_TPU_PLAN_BUDGET", 64)


def plan_beam():
    """``MXNET_TPU_PLAN_BEAM``: beam width (default 8)."""
    return _env_int("MXNET_TPU_PLAN_BEAM", 8)


# ------------------------------------------------------- cache lookup

_STATS_LOCK = threading.Lock()
_STATS = {"hits": 0, "misses": 0}


def reset_stats():
    """Zero the bind-time hit/miss counters (tests)."""
    with _STATS_LOCK:
        _STATS.update(hits=0, misses=0)


def stats():
    """Bind-time plan-lookup counters for this process."""
    with _STATS_LOCK:
        return dict(_STATS)


def lookup_entry(graph, layout, mesh=None):
    """Raw ``graph_plan`` tuning-cache entry for (graph digest, trace
    layout, mesh, backend), or None — no mode gate, no metrics."""
    from .. import autotune
    return autotune.lookup(OP, [], [], mesh=mesh,
                           extra={"graph": str(graph),
                                  "layout": str(layout)})


def committed_decisions(topo, entries, layout, mesh=None):
    """The bind-time consult: the committed plan's decision vector for
    this graph (``{}`` when the committed winner IS the greedy plan),
    or None on miss/off — the caller traces greedy either way, but a
    dict (even empty) means a cache entry owns the plan.  Emits
    ``mxtpu_plan_cache_{hit,miss}_total`` and a ``plan_lookup`` flight
    event carrying the graph digest + plan identity, so the dispatched
    plan is attributable in costdb/flight postmortems.  Never raises —
    a broken cache must not break a bind."""
    try:
        if plan_mode() == "off":
            return None
        graph = _fusion.graph_digest(topo, entries)
        entry = lookup_entry(graph, layout, mesh=mesh)
        hit = entry is not None
        decisions = None
        if hit:
            cfg = entry.get("config") or {}
            decisions = cfg.get("decisions")
            decisions = dict(decisions) if isinstance(decisions, dict) \
                else {}
        with _STATS_LOCK:
            _STATS["hits" if hit else "misses"] += 1
        try:
            from ..telemetry import counter, flight
            name = ("mxtpu_plan_cache_hit_total" if hit
                    else "mxtpu_plan_cache_miss_total")
            counter(name).inc()
            flight.record("plan_lookup", graph=graph, layout=str(layout),
                          hit=hit,
                          plan=_fusion.decisions_id(decisions)
                          if hit else None)
        except Exception:  # mxlint: allow-broad-except(lookup accounting is observability at bind time; a metric failure must not fail the bind)
            pass
        return decisions
    except MemoryError:  # pragma: no cover - never mask resource exhaustion
        raise
    except Exception:  # mxlint: allow-broad-except(the bind-time plan lookup is advisory; any failure reads as a plain miss and the trace falls back to the greedy plan)
        return None


# -------------------------------------------------------- the objective

def _size(shape):
    n = 1
    for d in shape:
        n *= int(d)
    return n


def _out_shape(node_shapes, node, idx=0):
    sh = node_shapes.get(id(node))
    if not sh or len(sh) <= idx:
        return None
    return tuple(int(d) for d in sh[idx])


def _in_shape(node_shapes, node, slot):
    src, idx = node.inputs[slot]
    return _out_shape(node_shapes, src, idx)


def _block_cost(blk, node_shapes, itemsize=4):
    """Analytic (flops, bytes) of one fused block at shape-inference
    time — the same formulas ``fusion._note_block_cost`` feeds the
    costdb at trace time, so the objective and the measured ground
    truth describe the same quantity.  A Pallas matmul-with-stats block
    saves the separate forward stats pass over its output (the kernel's
    whole point), so its traffic drops by one output read.  None when
    shapes are unresolved."""
    out = _out_shape(node_shapes, blk.terminal)
    if out is None:
        return None
    out_size = _size(out)
    if blk.kind == "bn_act":
        x = _in_shape(node_shapes, blk.bn, 0)
        if x is None:
            return None
        return (10.0 * out_size,
                float(itemsize) * (_size(x) + out_size))
    head = blk.conv if blk.conv is not None else blk.fc
    x = _in_shape(node_shapes, head, 0)
    w = _in_shape(node_shapes, head, 1)
    if x is None or w is None:
        return None
    n_out = int(head.attrs.get("num_filter")
                or head.attrs.get("num_hidden") or w[0])
    flops = 2.0 * out_size * _size(w) / max(1, n_out) + 10.0 * out_size
    bytes_ = float(itemsize) * (_size(x) + _size(w) + out_size)
    if blk.pallas:
        bytes_ -= float(itemsize) * out_size
    return flops, max(bytes_, float(itemsize))


def predict_plan_wall(topo, entries, plan, node_shapes, model=None,
                      backend=None):
    """Predicted step wall of one candidate plan: the cost model's
    prediction (roofline-attainable fallback when ``model`` is None or
    cannot predict) summed over every fused block and every unfused
    heavy node, plus the explicit boundary-relayout traffic of
    overridden-layout regions at peak bandwidth.  Returns ``(total_s,
    units)`` — one unit dict per costed block/node, the breakdown
    MXG010's ``--plan`` mode and the driver report render."""
    from ..telemetry import costdb
    from .perf import node_cost_estimate

    backend = backend or costdb.backend_name()
    pf = costdb.peak_flops(backend)
    pbw = costdb.peak_bandwidth(backend)
    units = []
    total = 0.0

    def predicted(flops, bytes_):
        att = costdb._attainable_s(flops, bytes_ or None, pf, pbw)
        pred = None
        if model is not None:
            pred = model.predict(flops=flops, bytes_accessed=bytes_,
                                 backend=backend)
        return (pred if pred is not None else att), att

    for node in topo:
        if node.is_variable or node.op is None:
            continue
        if id(node) in plan.skip:
            continue
        blk = plan.blocks.get(id(node))
        if blk is not None:
            cost = _block_cost(blk, node_shapes)
            if cost is None:
                continue
            flops, bytes_ = cost
            pred, att = predicted(flops, bytes_)
            relayout_s = 0.0
            if blk.kind != "fc_act" and blk.layout != plan.layout:
                x = _in_shape(node_shapes,
                              blk.conv or blk.bn, 0)
                out = _out_shape(node_shapes, blk.terminal)
                # apply_block's _relayout only transposes 4-d image
                # activations — a non-4d block pays nothing
                if x is not None and out is not None and pbw > 0 \
                        and len(x) == 4 and len(out) == 4:
                    # one transpose in, one out: read+write each
                    relayout_s = 2.0 * 4.0 * (_size(x) + _size(out)) \
                        / pbw
            if pred is not None:
                total += pred + relayout_s
                units.append({
                    "unit": "block", "name": blk.name,
                    "kind": blk.kind, "chain": blk.chain,
                    "layout": blk.layout, "pallas": bool(blk.pallas),
                    "flops": flops, "bytes": bytes_,
                    "attainable_s": att, "predicted_s": pred,
                    "relayout_s": relayout_s,
                })
            continue
        # unfused node: only the heavies the analytic estimator models
        out_shapes = []
        sh = node_shapes.get(id(node))
        if sh:
            out_shapes = [tuple(int(d) for d in s) for s in sh]
        in_shapes = []
        ok = True
        for slot in range(len(node.inputs)):
            s = _in_shape(node_shapes, node, slot)
            if s is None:
                ok = False
                break
            in_shapes.append(s)
        if not ok or not out_shapes:
            continue
        est = node_cost_estimate(node, in_shapes, out_shapes)
        if est is None:
            if node.op.name == "Activation":
                # the act a split/off decision pushes OUT of a fused
                # region: one extra elementwise pass (read + write)
                # over the activation — exactly the traffic fusing it
                # into the epilogue eliminates.  Without this term
                # every split scores tied-with-greedy and the
                # measurement budget fills with candidates that are
                # strictly worse in reality.
                out_size = _size(out_shapes[0])
                est = (float(out_size), 8.0 * out_size)
            else:
                continue
        flops, bytes_ = est
        pred, att = predicted(flops, bytes_)
        if pred is not None:
            total += pred
            units.append({
                "unit": "node", "name": node.name,
                "kind": node.op.name, "chain": None,
                "layout": None, "pallas": False,
                "flops": flops, "bytes": bytes_,
                "attainable_s": att, "predicted_s": pred,
                "relayout_s": 0.0,
            })
    return total, units


# ------------------------------------------------------------ search

def chain_moves(topo, entries, layout, is_train=True,
                node_shapes=None):
    """The single-decision neighbor moves of this graph's search space,
    derived from the greedy plan: per chain the non-greedy
    ``CHAIN_CHOICES``, a layout flip for image chains, and a Pallas
    veto where the greedy plan chose the Pallas leg.  With
    ``node_shapes``, layout flips are only offered for chains whose
    activation is actually 4-d (``apply_block`` transposes nothing
    else, so the move would be a no-op with phantom accounting).
    Returns ``(greedy_plan, moves)`` with each move a ``(category,
    chain_id, value)`` triple."""
    greedy = _fusion.plan_block_fusion(topo, entries, layout=layout,
                                      is_train=is_train, record=False,
                                      decisions={})
    moves = []
    other = "NCHW" if layout == "NHWC" else "NHWC"
    for blk in greedy.blocks.values():
        cid = blk.chain
        for choice in _fusion.CHAIN_CHOICES.get(blk.kind, ()):
            if choice != "fuse":
                moves.append(("chains", cid, choice))
        if blk.kind != "fc_act":
            x = None
            if node_shapes is not None:
                x = _in_shape(node_shapes, blk.conv or blk.bn, 0)
            if node_shapes is None or (x is not None and len(x) == 4):
                moves.append(("layouts", cid, other))
        if blk.pallas:
            moves.append(("pallas", cid, 0))
    return greedy, moves


def _with_move(decisions, cat, cid, val):
    """Decision vector with one move applied (re-applying the same
    value toggles it back off — the beam can retreat toward greedy)."""
    nd = {k: dict(v) for k, v in decisions.items()}
    cur = nd.get(cat, {}).get(cid)
    if cur == val:
        del nd[cat][cid]
        if not nd[cat]:
            del nd[cat]
    else:
        nd.setdefault(cat, {})[cid] = val
    return nd


def _canon(decisions):
    return json.dumps(decisions, sort_keys=True)


def search_plan(topo, entries, layout="NHWC", is_train=True,
                node_shapes=None, model=None, budget=None, beam=None):
    """Beam search over whole-graph plan decisions, scored by
    :func:`predict_plan_wall`.  The greedy plan (empty decision
    vector) is always seeded into the population, so the returned
    best candidate's predicted wall is <= the greedy plan's by
    construction.  Returns candidates sorted best-predicted-first:
    ``{"decisions", "plan_id", "predicted_s", "blocks", "units"}``."""
    if node_shapes is None:
        raise ValueError("search_plan needs node_shapes (use "
                         "analysis.infer_node_shapes)")
    budget = int(budget or plan_budget())
    beam = int(beam or plan_beam())
    _greedy_plan, moves = chain_moves(topo, entries, layout,
                                      is_train=is_train,
                                      node_shapes=node_shapes)
    evaluated = {}

    def score(decisions):
        key = _canon(decisions)
        if key in evaluated:
            return evaluated[key]
        plan = _fusion.plan_block_fusion(
            topo, entries, layout=layout, is_train=is_train,
            record=False, decisions=dict(decisions) if decisions
            else {})
        total, units = predict_plan_wall(topo, entries, plan,
                                         node_shapes, model=model)
        res = {"decisions": decisions,
               "plan_id": _fusion.decisions_id(decisions),
               "predicted_s": total, "blocks": len(plan.blocks),
               "units": units}
        evaluated[key] = res
        return res

    score({})
    frontier = [{}]
    while len(evaluated) < budget and moves:
        fresh = []
        for d in frontier:
            for (cat, cid, val) in moves:
                nd = _with_move(d, cat, cid, val)
                if _canon(nd) not in evaluated:
                    fresh.append(nd)
                    score(nd)
                    if len(evaluated) >= budget:
                        break
            if len(evaluated) >= budget:
                break
        if not fresh:
            break
        ranked = sorted(evaluated.values(),
                        key=lambda r: (r["predicted_s"], r["plan_id"]))
        new_frontier = [r["decisions"] for r in ranked[:beam]]
        if [_canon(d) for d in new_frontier] == \
                [_canon(d) for d in frontier]:
            break
        frontier = new_frontier
    return sorted(evaluated.values(),
                  key=lambda r: (r["predicted_s"], r["plan_id"]))


# -------------------------------------------------------- measurement

def build_step_values(symbol, data_shapes, layout="NHWC", seed=0):
    """Deterministic argument/aux value arrays for measuring a
    training step of ``symbol`` at ``data_shapes`` (reference NCHW
    global shapes; 4-d data inputs are transposed to NHWC when the
    trace layout asks, exactly like the trainer's ingest).  Returns
    ``(arg_nodes, aux_nodes, vals)`` with ``vals`` ordered args then
    aux — the layout :func:`measure_decisions`'s step fn expects."""
    import numpy as np
    from ..symbol import _classify_vars

    topo = symbol._topo()
    arg_nodes, aux_nodes = _classify_vars(topo)
    arg_shapes, _out, aux_shapes = symbol.infer_shape(**data_shapes)
    rng = np.random.RandomState(seed)
    vals = []
    for node, shape in zip(arg_nodes, arg_shapes):
        name = node.name
        if name in data_shapes and "label" in name:
            v = rng.randint(0, 2, shape).astype(np.float32)
        elif name in data_shapes:
            v = rng.uniform(-1, 1, shape).astype(np.float32)
            if layout == "NHWC" and len(shape) == 4:
                v = np.transpose(v, (0, 2, 3, 1)).copy()
        elif "gamma" in name or "var" in name:
            v = rng.uniform(0.5, 1.5, shape).astype(np.float32)
        else:
            v = (rng.uniform(-0.5, 0.5, shape) * 0.2).astype(np.float32)
        vals.append(v)
    # aux: moving mean ~0, moving var ~1 keeps BN numerics tame
    for node, shape in zip(aux_nodes, aux_shapes):
        if "var" in node.name:
            vals.append(np.ones(shape, np.float32))
        else:
            vals.append(np.zeros(shape, np.float32))
    return arg_nodes, aux_nodes, vals


def measure_decisions(symbol, data_shapes, decisions, layout="NHWC",
                      repeats=2, seed=0, values=None):
    """Measured wall seconds of ONE forward+backward training step of
    ``symbol`` with ``decisions`` active at trace time — the real A/B
    leg ``autotune.measure`` times (synchronized, min-of-N, compile
    excluded).  ``values``: reuse :func:`build_step_values` output so
    every candidate measures on identical inputs."""
    import jax
    import jax.numpy as jnp
    from .. import autotune
    from ..symbol import eval_graph
    from ..ops.nn import image_layout
    from ..ops.fused import block_fusion

    topo, entries = symbol._topo(), symbol._entries
    if values is None:
        values = build_step_values(symbol, data_shapes, layout=layout,
                                   seed=seed)
    arg_nodes, aux_nodes, vals = values
    var_ids = [id(n) for n in arg_nodes + aux_nodes]
    n_args = len(arg_nodes)
    head_is_loss = [bool(n.op is not None and n.op.is_loss)
                    for (n, _i) in entries]
    # labels are not differentiated (their central role is indexing)
    diff_idx = tuple(i for i, n in enumerate(arg_nodes)
                     if "label" not in n.name)
    decisions = dict(decisions) if decisions else {}

    def step(*all_vals):
        diff_vals = tuple(all_vals[i] for i in diff_idx)

        def f(diff):
            full = list(all_vals)
            for j, i in enumerate(diff_idx):
                full[i] = diff[j]
            var_values = dict(zip(var_ids, full))
            bsz = full[0].shape[0] if full and full[0].ndim else None
            with image_layout(layout), block_fusion(True), \
                    _fusion.plan_decisions(decisions):
                heads, _aux = eval_graph(
                    topo, entries, var_values, is_train=True,
                    key=jax.random.PRNGKey(0), batch_size=bsz)
            return heads

        heads, vjp = jax.vjp(f, diff_vals)
        cot = [jnp.ones_like(h) if il else jnp.zeros_like(h)
               for h, il in zip(heads, head_is_loss)]
        (grads,) = vjp(list(cot))
        return heads, grads

    return autotune.measure(step, tuple(vals), repeats=repeats)


# ------------------------------------------------- search-and-commit

def search_and_commit(symbol, data_shapes, layout="NHWC", model=None,
                      budget=None, beam=None, topk=3, repeats=2,
                      mesh=None, commit=True, cache=None, force=False,
                      measure=True, node_shapes=None, say=None):
    """The full loop the driver / ci_check / bench run: search, measure
    the top-k predicted candidates (greedy ALWAYS measured alongside —
    the committed winner can never be worse than greedy on the
    measured run), commit the winner to the tuning cache keyed by
    (graph digest, layout, mesh, backend).  A pre-existing entry short-
    circuits everything unless ``force`` (the all-hit second run is
    the CI contract).  Returns the report doc."""
    from .. import autotune
    from .verifier import infer_node_shapes

    say = say or (lambda s: None)
    topo, entries = symbol._topo(), symbol._entries
    graph = _fusion.graph_digest(topo, entries)
    doc = {"schema": "mxtpu-plansearch/1", "graph": graph,
           "layout": layout, "mesh": dict(mesh) if mesh else None,
           "cached": False, "searched": 0, "measured": 0}

    if cache is not None:
        existing = cache.lookup(OP, [], [], mesh=mesh,
                                extra={"graph": graph,
                                       "layout": str(layout)})
    else:
        existing = lookup_entry(graph, layout, mesh=mesh)
    if existing is not None and not force:
        cfg = existing.get("config") or {}
        say("plan_search: graph %s cached (plan %s, wall %.3g ms)"
            % (graph, cfg.get("plan_id"),
               1e3 * (existing.get("wall_s") or 0)))
        doc.update(cached=True, entry=existing,
                   plan_id=cfg.get("plan_id"),
                   predicted_s=cfg.get("predicted_s"),
                   greedy_predicted_s=cfg.get("greedy_predicted_s"),
                   wall_s=existing.get("wall_s"),
                   greedy_wall_s=existing.get("heuristic_wall_s"))
        return doc

    if node_shapes is None:
        _topo2, node_shapes = infer_node_shapes(symbol, data_shapes)
    ranked = search_plan(topo, entries, layout=layout,
                         node_shapes=node_shapes, model=model,
                         budget=budget, beam=beam)
    doc["searched"] = len(ranked)
    greedy = next(r for r in ranked if not r["decisions"])
    best_pred = ranked[0]
    say("plan_search: graph %s — %d candidate(s) scored; greedy "
        "predicted %.3g ms, best predicted %.3g ms (%s)"
        % (graph, len(ranked), 1e3 * greedy["predicted_s"],
           1e3 * best_pred["predicted_s"], best_pred["plan_id"]))

    # measurement set: greedy + the top-k predicted, RESTRICTED to
    # candidates the objective scores at least as well as greedy — a
    # predicted-worse plan is never committed (the CI contract:
    # committed predicted <= greedy predicted), so measuring one is
    # wasted budget
    bar = greedy["predicted_s"] * (1.0 + 1e-9)
    candidates, seen = [], set()
    for r in [greedy] + [r for r in ranked[:max(1, int(topk))]
                         if r["predicted_s"] <= bar]:
        key = _canon(r["decisions"])
        if key not in seen:
            seen.add(key)
            candidates.append(r)

    winner = best_pred
    greedy_wall = None
    if measure:
        values = build_step_values(symbol, data_shapes, layout=layout)
        measured = []
        for r in candidates:
            try:
                wall = measure_decisions(symbol, data_shapes,
                                         r["decisions"], layout=layout,
                                         repeats=repeats, values=values)
            except Exception as e:  # mxlint: allow-broad-except(a candidate plan that fails to trace/compile is simply not a winner; the search continues with the rest of the measured set)
                say("plan_search:   %-14s FAILED: %s"
                    % (r["plan_id"], str(e)[:120]))
                continue
            say("plan_search:   %-14s predicted %.3g ms measured "
                "%.3g ms" % (r["plan_id"], 1e3 * r["predicted_s"],
                             1e3 * wall))
            measured.append(dict(r, wall_s=wall))
        doc["measured"] = len(measured)
        if not measured:
            doc["error"] = "no candidate plan measured"
            return doc
        greedy_row = next((m for m in measured if not m["decisions"]),
                          None)
        if greedy_row is None:
            # without a measured greedy there is no A/B — committing a
            # searched plan here would void the "never worse than
            # greedy on the measured run" guarantee the entry carries
            doc["error"] = ("greedy leg failed to measure — nothing "
                            "committed")
            return doc
        greedy_wall = greedy_row["wall_s"]
        winner = min(measured, key=lambda m: m["wall_s"])
        doc["candidates"] = [
            {k: m[k] for k in ("plan_id", "predicted_s", "wall_s")}
            for m in measured]
    else:
        winner = dict(best_pred, wall_s=None)

    doc.update(plan_id=winner["plan_id"],
               predicted_s=winner["predicted_s"],
               greedy_predicted_s=greedy["predicted_s"],
               wall_s=winner.get("wall_s"), greedy_wall_s=greedy_wall)
    if commit:
        c = cache if cache is not None else autotune.CACHE
        entry = c.put(
            OP, [], [],
            config={"decisions": winner["decisions"],
                    "plan_id": winner["plan_id"],
                    "predicted_s": winner["predicted_s"],
                    "greedy_predicted_s": greedy["predicted_s"]},
            wall_s=winner.get("wall_s"), mesh=mesh,
            extra={"graph": graph, "layout": str(layout)},
            heuristic_config={"decisions": {}, "plan_id": "greedy"},
            heuristic_wall_s=greedy_wall,
            candidates=doc.get("measured") or doc["searched"],
            source="plan-search")
        doc["entry"] = entry
        say("plan_search: committed %s for graph %s (measured "
            "%.3g ms%s)"
            % (winner["plan_id"], graph,
               1e3 * (winner.get("wall_s") or 0),
               ", greedy %.3g ms" % (1e3 * greedy_wall)
               if greedy_wall else ""))
    return doc
