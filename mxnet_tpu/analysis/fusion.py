"""Block-granularity fusion + layout planning (ROADMAP item 1).

The Glow-style lowering pass (PAPERS.md: arXiv:1805.00907) that turns
the instrumentation of PRs 3-5 into throughput: conv->BN->ReLU and
matmul->bias->activation chains — the blocks that dominate ResNet-style
graphs — are pattern-matched over the Symbol DAG in topo order and each
match is emitted as ONE fused region (`mxnet_tpu.ops.fused`
``fused_block_*``: a Pallas matmul-with-stats kernel where eligible, a
single custom-vjp XLA region otherwise).  Because every region carries
a hand-written backward, training keeps one fused dispatch per block in
BOTH directions; the plan runs wherever :func:`mxnet_tpu.symbol.
eval_graph` traces — forward, the executor's vjp backward, and the
trainer's fused step.

**Layout planning.**  Each region boundary is pinned to an explicit
activation layout (the trace-time ``image_layout``, NHWC on the TPU
path).  Interior edges of a fused block — conv->BN, BN->act — no longer
cross a region boundary, so the materialization/relayout XLA would
schedule there disappears; when two fused blocks are adjacent (one's
terminal feeds the other's input) the plan additionally pins both sides
of the shared boundary to the same layout, eliminating the relayout
between them.  Both counts are reported as
``mxtpu_fusion_relayouts_eliminated_total``.

**Chains matched** (see docs/api/fusion.md for the full rule catalog):

=============  =====================================================
kind           pattern (every interior node single-consumer)
=============  =====================================================
conv_bn_act    Convolution(2-d) -> BatchNorm -> Activation(relu)
conv_bn        Convolution(2-d) -> BatchNorm (no relu consumer)
bn_act         BatchNorm -> Activation(relu), producer not a fusable
               conv (pre-activation ResNet is full of these)
fc_act         FullyConnected -> Activation(relu|sigmoid|tanh)
=============  =====================================================

BatchNorm nodes must use the reference channel axis (``axis=1``) and
not request ``output_mean_var``; ineligible candidates are recorded as
fallbacks (``mxtpu_fusion_fallback_total{reason=...}``) and evaluated
unfused — the pass degrades, never refuses a graph.

Enabled per-trace by ``ops.fused.block_fusion`` (the
``MXNET_FUSE_BLOCKS`` env default), wired through
``Executor`` (bind-time capture) and ``ShardedTrainer(fuse_blocks=...)``.
When the older conv1x1-only pass (``MXNET_FUSE_CONV_BN``) is also
active it keeps its claims; this pass fuses everything else.
"""
from __future__ import annotations

import hashlib
import json

__all__ = ["FusedBlock", "FusionPlan", "plan_block_fusion",
           "apply_block", "last_plan_summary", "FC_FUSABLE_ACTS",
           "graph_digest", "decisions_id", "plan_decisions",
           "active_decisions", "CHAIN_CHOICES"]

FC_FUSABLE_ACTS = ("relu", "sigmoid", "tanh")

#: per-chain-kind decision alternatives the plan search explores
#: (analysis.plansearch).  "fuse" is the greedy behavior; "conv_bn" /
#: "bn_act" split a conv_bn_act chain at its BN boundary; "off" leaves
#: the whole chain unfused.
CHAIN_CHOICES = {
    "conv_bn_act": ("fuse", "conv_bn", "bn_act", "off"),
    "conv_bn": ("fuse", "off"),
    "bn_act": ("fuse", "off"),
    "fc_act": ("fuse", "off"),
}

# summary of the most recent recorded plan (bench.py / fit.py surface
# it; plans are computed at trace time inside jit, so a module-level
# snapshot is the only host-side handle)
_LAST_SUMMARY = None

# the active plan-decision overrides (analysis.plansearch): tri-state
# like ops.fused's trace flags — None means "greedy", a dict is the
# searched decision vector a committed graph_plan cache entry carries.
# Executor/ShardedTrainer enter the context around every eval_graph
# trace so forward, backward, and the fused step lower identically.
_DECISIONS = {"v": None}


class plan_decisions:
    """Context manager activating a plan-decision vector for the traces
    inside it (``None``/``{}`` -> the greedy plan).  See
    docs/api/plansearch.md for the decision schema."""

    def __init__(self, decisions):
        self.decisions = decisions

    def __enter__(self):
        self._prev = _DECISIONS["v"]
        _DECISIONS["v"] = self.decisions
        return self

    def __exit__(self, *exc):
        _DECISIONS["v"] = self._prev


def active_decisions():
    """The decision vector the current trace context activated, or
    None (greedy)."""
    return _DECISIONS["v"]


def graph_digest(topo, entries):
    """Stable 12-hex identity of the graph STRUCTURE — op names, attrs,
    input wiring, and head entries; node *names* excluded, so two
    processes (or two builds in one process, whose auto-naming counters
    differ) constructing the same architecture share one digest.  The
    plan-search tuning-cache entries (``analysis.plansearch``) are
    keyed by it, together with mesh + backend."""
    idx = {id(n): i for i, n in enumerate(topo)}
    items = []
    for n in topo:
        if n.is_variable:
            items.append("var")
            continue
        items.append([
            n.op.name,
            sorted((str(k), repr(v)) for k, v in n.attrs.items()),
            [[idx[id(src)], int(i)] for (src, i) in n.inputs],
        ])
    items.append([[idx[id(n)], int(i)] for (n, i) in entries])
    blob = json.dumps(items, sort_keys=True, default=repr)
    return hashlib.sha1(blob.encode("utf-8")).hexdigest()[:12]


def decisions_id(decisions):
    """Short identity of one decision vector ("greedy" for the empty /
    absent one) — the plan identity costdb records and flight events
    carry."""
    if not decisions:
        return "greedy"
    blob = json.dumps(decisions, sort_keys=True, default=repr)
    return "plan-" + hashlib.sha1(blob.encode("utf-8")).hexdigest()[:10]


class FusedBlock:
    """One matched chain: the member nodes and how to emit them."""
    __slots__ = ("kind", "terminal", "conv", "bn", "fc", "act", "pallas",
                 "layout", "chain", "graph", "plan_id")

    def __init__(self, kind, terminal, conv=None, bn=None, fc=None,
                 act=None, pallas=False, layout="NCHW", chain=None,
                 graph=None, plan_id=None):
        self.kind = kind
        self.terminal = terminal      # the node whose value the region yields
        self.conv = conv
        self.bn = bn
        self.fc = fc
        self.act = act                # act_type string or None
        self.pallas = bool(pallas)
        self.layout = layout
        self.chain = chain            # stable chain id (greedy-terminal
        self.graph = graph            # topo index), graph digest, and
        self.plan_id = plan_id        # plan identity, for costdb/cache

    @property
    def name(self):
        return self.terminal.name

    def interior(self):
        """Member nodes other than the terminal (skipped at eval)."""
        members = [n for n in (self.conv, self.bn, self.fc)
                   if n is not None and n is not self.terminal]
        return members


class FusionPlan:
    """The pass output: blocks keyed by terminal node id, the interior
    node-id skip set, fallback records, and the layout plan."""

    def __init__(self, layout, is_train, decisions=None, graph=None):
        self.layout = layout
        self.is_train = bool(is_train)
        self.decisions = decisions    # plan-search overrides (or None)
        self.graph = graph            # graph digest (None when unhashed)
        self.plan_id = decisions_id(decisions)
        self.blocks = {}          # id(terminal) -> FusedBlock
        self.skip = set()         # interior node ids
        self.fallbacks = []       # (node_name, reason)
        self.interior_edges = 0   # relayout boundaries removed in-block
        self.adjacent_edges = 0   # same-layout block-to-block boundaries
        self.relayout_edges_added = 0  # explicit boundary transposes a
        self.overrides = 0             # layout override inserts (2/block)

    @property
    def relayouts_eliminated(self):
        return self.interior_edges + self.adjacent_edges

    def add(self, block):
        block.graph = self.graph
        block.plan_id = self.plan_id
        self.blocks[id(block.terminal)] = block
        interior = block.interior()
        for n in interior:
            self.skip.add(id(n))
        self.interior_edges += len(interior)
        if block.kind != "fc_act" and block.layout != self.layout:
            # an overridden-layout region transposes its input in and
            # its output back out (apply_block) — 2 explicit relayouts.
            # Plan-time accounting is shape-free, so this is an upper
            # bound: a non-4d activation transposes nothing (the
            # search never offers layout moves for those — plansearch.
            # chain_moves filters on the inferred shapes)
            self.relayout_edges_added += 2

    def fallback(self, node, reason):
        self.fallbacks.append((node.name, reason))

    def summary(self):
        kinds = {}
        for blk in self.blocks.values():
            kinds[blk.kind] = kinds.get(blk.kind, 0) + 1
        reasons = {}
        for _name, reason in self.fallbacks:
            reasons[reason] = reasons.get(reason, 0) + 1
        return {
            "layout": self.layout,
            "is_train": self.is_train,
            "blocks": len(self.blocks),
            "kinds": kinds,
            "pallas_blocks": sum(1 for b in self.blocks.values()
                                 if b.pallas),
            "relayouts_eliminated": self.relayouts_eliminated,
            "relayout_edges_added": self.relayout_edges_added,
            "fallbacks": reasons,
            "graph": self.graph,
            "plan_id": self.plan_id,
            "searched": bool(self.decisions),
            "overrides": self.overrides,
        }


def _consumers(topo, entries):
    """id(node) -> list of (consumer node, input slot); graph heads
    count as consumers (a head output must stay visible)."""
    out = {}
    for node in topo:
        for slot, (src, _idx) in enumerate(node.inputs):
            out.setdefault(id(src), []).append((node, slot))
    for (node, _i) in entries:
        out.setdefault(id(node), []).append((None, -1))
    return out


def _single_consumer(consumers, node):
    """The unique (consumer, slot) of ``node``, or None."""
    cs = consumers.get(id(node), ())
    if len(cs) != 1 or cs[0][0] is None:
        return None
    return cs[0]


def _is_op(node, name):
    return (not node.is_variable and node.op is not None
            and node.op.name == name)


def _bn_fusable(bn, plan):
    """BatchNorm eligibility shared by every BN-bearing chain."""
    if bn.attrs.get("output_mean_var"):
        plan.fallback(bn, "bn_output_mean_var")
        return False
    if int(bn.attrs.get("axis", 1)) != 1:
        plan.fallback(bn, "bn_axis")
        return False
    return True


def _conv_fusable(conv, layout, plan, claimed):
    """Convolution eligibility as the head of a conv_bn* chain."""
    if id(conv) in claimed:
        plan.fallback(conv, "claimed_by_other_pass")
        return False
    if len(tuple(conv.attrs.get("kernel") or ())) != 2:
        plan.fallback(conv, "conv_ndim")
        return False
    if conv.attrs.get("layout") and conv.attrs["layout"] != layout:
        plan.fallback(conv, "conv_layout_pinned")
        return False
    return True


def _pallas_eligible(blk, is_train):
    """Pallas eligibility of a (possibly decision-transformed) block:
    the matmul-with-stats kernel needs an eligible 1x1 conv head, NHWC
    region layout, and train-mode BN statistics."""
    if blk.conv is None or blk.bn is None \
            or blk.kind not in ("conv_bn", "conv_bn_act"):
        return False
    from ..ops import fused as _fused
    return bool(_fused._conv_eligible(blk.conv) and blk.layout == "NHWC"
                and is_train
                and not blk.bn.attrs.get("use_global_stats"))


def _apply_decision(blk, cid, decisions, plan, is_train):
    """Transform one greedy-matched block by the plan-search decision
    vector (``decisions``): per-chain fuse/split/off, per-region
    layout, and a per-block Pallas veto.  ``cid`` is the chain's
    stable id (the GREEDY terminal's topo index, as a string) — the
    key every committed ``graph_plan`` cache entry uses.  Returns the
    block to plan (possibly a shorter chain) or None (chain unfused).
    Unknown/ineligible choices read as "fuse" — a stale entry must
    degrade, never break a trace."""
    if not decisions:
        blk.chain = cid
        return blk
    choice = str((decisions.get("chains") or {}).get(cid, "fuse"))
    if choice not in CHAIN_CHOICES.get(blk.kind, ("fuse",)):
        choice = "fuse"
    if choice == "off":
        plan.overrides += 1
        return None
    if choice == "conv_bn" and blk.kind == "conv_bn_act":
        blk = FusedBlock("conv_bn", terminal=blk.bn, conv=blk.conv,
                         bn=blk.bn, act=None, layout=blk.layout)
        # the split block keeps the Pallas leg a naturally-matched
        # conv_bn chain would get — a split must not silently lose
        # the kernel that is its main perf lever
        blk.pallas = _pallas_eligible(blk, is_train)
        plan.overrides += 1
    elif choice == "bn_act" and blk.kind == "conv_bn_act":
        blk = FusedBlock("bn_act", terminal=blk.terminal, bn=blk.bn,
                         act=blk.act, layout=blk.layout)
        plan.overrides += 1
    layout = (decisions.get("layouts") or {}).get(cid)
    if layout in ("NCHW", "NHWC") and layout != blk.layout \
            and blk.kind != "fc_act":
        blk.layout = layout
        plan.overrides += 1
        # eligibility follows the REGION layout (an NHWC override in
        # an NCHW trace can open the Pallas leg; the reverse closes it)
        blk.pallas = _pallas_eligible(blk, is_train)
    veto = (decisions.get("pallas") or {}).get(cid)
    if veto is not None and not veto and blk.pallas:
        blk.pallas = False
        plan.overrides += 1
    blk.chain = cid
    return blk


def plan_block_fusion(topo, entries, layout="NCHW", is_train=True,
                      exclude=(), record=True, decisions=None):
    """Match fusable chains over ``topo`` and return a
    :class:`FusionPlan`.  ``exclude``: node ids already claimed by
    another trace-time pass (conv1x1+BN, stem s2d, dX elision) — chains
    touching them fall back.  ``record`` emits the ``mxtpu_fusion_*``
    metrics and a ``fusion_plan`` flight event (one per trace).
    ``decisions``: plan-search overrides (analysis.plansearch; default:
    the :class:`plan_decisions` context, i.e. the committed cache
    entry Executor/ShardedTrainer activated — None means greedy)."""
    if decisions is None:
        decisions = active_decisions()
    digest = graph_digest(topo, entries) if (record or decisions) \
        else None
    plan = FusionPlan(layout, is_train, decisions=decisions,
                      graph=digest)
    consumers = _consumers(topo, entries)
    claimed = set(exclude)
    topo_index = {id(n): i for i, n in enumerate(topo)}

    def conv_chain(bn, act_node, act_type):
        """Try conv->bn(->act); returns the block or None."""
        src, idx = bn.inputs[0]
        if not _is_op(src, "Convolution") or idx != 0:
            return None
        nxt = _single_consumer(consumers, src)
        if nxt is None or nxt[0] is not bn:
            plan.fallback(src, "conv_multi_consumer")
            return None
        if not _conv_fusable(src, layout, plan, claimed):
            return None
        blk = FusedBlock("conv_bn_act" if act_node is not None
                         else "conv_bn",
                         terminal=act_node if act_node is not None
                         else bn,
                         conv=src, bn=bn, act=act_type, layout=layout)
        blk.pallas = _pallas_eligible(blk, is_train)
        return blk

    for node in topo:
        if node.is_variable or node.op is None or id(node) in claimed:
            continue
        blk = None
        if _is_op(node, "Activation"):
            act_type = node.attrs.get("act_type", "relu")
            src, idx = node.inputs[0]
            if src.is_variable or src.op is None or idx != 0 \
                    or id(src) in claimed or id(src) in plan.skip \
                    or id(src) in plan.blocks:
                continue
            one = _single_consumer(consumers, src)
            if one is None or one[0] is not node:
                continue
            if _is_op(src, "BatchNorm") and act_type == "relu":
                if not _bn_fusable(src, plan):
                    continue
                blk = conv_chain(src, node, act_type)
                if blk is None:
                    blk = FusedBlock("bn_act", terminal=node, bn=src,
                                     act=act_type, layout=layout)
            elif _is_op(src, "FullyConnected") \
                    and act_type in FC_FUSABLE_ACTS:
                blk = FusedBlock("fc_act", terminal=node, fc=src,
                                 act=act_type, layout=layout)
            elif _is_op(src, "BatchNorm"):
                plan.fallback(node, "act_type")
        elif _is_op(node, "BatchNorm"):
            if id(node) in plan.skip or id(node) in plan.blocks:
                continue
            # BN whose single consumer is a fusable relu is deferred to
            # the Activation visit above (the longer chain wins)
            one = _single_consumer(consumers, node)
            if one is not None and _is_op(one[0], "Activation") \
                    and one[0].attrs.get("act_type") == "relu" \
                    and one[1] == 0:
                continue
            if not _bn_fusable(node, plan):
                continue
            blk = conv_chain(node, None, None)
        if blk is not None:
            # the chain id is the GREEDY terminal's topo position, so a
            # committed decision vector survives rebuilds whose auto-
            # generated node names differ
            blk = _apply_decision(blk, str(topo_index[id(node)]),
                                  decisions, plan, is_train)
        if blk is not None:
            # a block's members must not collide with earlier claims
            members = blk.interior() + [blk.terminal]
            if any(id(m) in plan.skip or id(m) in plan.blocks
                   for m in members):
                continue
            plan.add(blk)

    # layout plan: adjacent fused regions sharing an IMAGE-layout
    # boundary keep one pinned layout — no relayout between them.  The
    # credit needs image activations on BOTH sides: an fc_act block
    # neither carries an image layout out (its terminal is a 2-d
    # activation) nor reads one in (FullyConnected flattens its input,
    # paying that materialization regardless of any pinning), so FC
    # boundaries never count — crediting them overstated the
    # mxtpu_fusion_relayouts_eliminated_total metric.  Both sides must
    # also sit in the AMBIENT layout: an overridden-layout region
    # round-trips through the ambient layout at every boundary
    # (apply_block), so two adjacent NHWC-overridden regions in an
    # NCHW trace still pay their transposes — their boundary
    # eliminates nothing (relayout_edges_added counts what they pay).
    image_terminal = {tid: b.layout for tid, b in plan.blocks.items()
                      if b.kind != "fc_act"}
    for blk in plan.blocks.values():
        if blk.fc is not None or blk.layout != plan.layout:
            continue
        first = blk.conv or blk.bn
        src, _idx = first.inputs[0]
        if image_terminal.get(id(src)) == plan.layout:
            plan.adjacent_edges += 1

    if record:
        _record(plan)
    return plan


def _record(plan):
    """Emit the plan's metrics + flight event and snapshot the summary
    (runs at trace time — host-side python, once per compile)."""
    global _LAST_SUMMARY
    s = plan.summary()
    _LAST_SUMMARY = s
    try:
        from .. import telemetry
        from ..telemetry import flight
        telemetry.counter("mxtpu_fusion_plans_total").inc()
        for kind, n in s["kinds"].items():
            telemetry.counter("mxtpu_fusion_blocks_total").labels(
                kind=kind).inc(n)
        if s["relayouts_eliminated"]:
            telemetry.counter(
                "mxtpu_fusion_relayouts_eliminated_total").inc(
                s["relayouts_eliminated"])
        for reason, n in s["fallbacks"].items():
            telemetry.counter("mxtpu_fusion_fallback_total").labels(
                reason=reason).inc(n)
        flight.record("fusion_plan", **s)
    except MemoryError:  # pragma: no cover - observability must not kill a trace
        raise
    except Exception:  # mxlint: allow-broad-except(metric emission is observability; a telemetry failure must not fail the trace that is being fused)
        pass


def last_plan_summary():
    """Summary dict of the most recent recorded plan in this process
    (None before any fused trace).  See :meth:`FusionPlan.summary`."""
    return _LAST_SUMMARY


def _relayout(x, dst_layout):
    """Explicit boundary transpose into ``dst_layout`` for a 4-d image
    activation (the relayout edge an overridden-layout region pays —
    plan.relayout_edges_added counts them, and the plan-search
    objective costs them at peak bandwidth)."""
    if x is None or getattr(x, "ndim", 0) != 4:
        return x
    import jax.numpy as jnp
    return jnp.transpose(x, (0, 2, 3, 1) if dst_layout == "NHWC"
                         else (0, 3, 1, 2))


def apply_block(blk, vals, is_train):
    """Evaluate one planned block from the eval_graph value map.
    Returns (out, bn_node_or_None, [new_mm, new_mv] or None); the
    caller threads the BN aux updates exactly as the unfused op would.

    A block whose ``layout`` differs from the ambient trace layout (a
    plan-search per-region override) transposes its image activation
    into the region layout on entry and back on exit — the weight path
    is layout-independent (reference OIHW, dimension numbers derived
    inside the region).
    """
    from ..ops import fused as _fused
    from ..ops.nn import current_image_layout

    def val(node, slot):
        src, idx = node.inputs[slot]
        return vals[id(src)][idx]

    ambient = current_image_layout()

    if blk.kind in ("conv_bn_act", "conv_bn"):
        conv, bn = blk.conv, blk.bn
        x, w = val(conv, 0), val(conv, 1)
        b = None if conv.attrs.get("no_bias") else val(conv, 2)
        gamma, beta = val(bn, 1), val(bn, 2)
        mm, mv = val(bn, 3), val(bn, 4)
        if blk.layout != ambient:
            x = _relayout(x, blk.layout)
        pallas = _tuned_pallas(blk, x, w)
        out, new_mm, new_mv = _fused.fused_block_conv_bn_act(
            conv.attrs, bn.attrs, blk.layout, is_train, blk.act,
            pallas, x, w, b, gamma, beta, mm, mv)
        # the costdb signature records the DISPATCHED lowering — a
        # cache veto must be visible in the ground truth, not the
        # planner's pre-veto choice
        _note_block_cost(blk, out, x, w, pallas=pallas)
        _note_block_numerics(blk, out)
        if blk.layout != ambient:
            out = _relayout(out, ambient)
        return out, bn, [new_mm, new_mv]
    if blk.kind == "bn_act":
        bn = blk.bn
        x = val(bn, 0)
        if blk.layout != ambient:
            x = _relayout(x, blk.layout)
        ch = 3 if (blk.layout == "NHWC" and x.ndim == 4) else 1
        out, new_mm, new_mv = _fused.fused_block_bn_act(
            bn.attrs, ch, is_train, blk.act, x, val(bn, 1), val(bn, 2),
            val(bn, 3), val(bn, 4))
        _note_block_cost(blk, out, x, None)
        _note_block_numerics(blk, out)
        if blk.layout != ambient:
            out = _relayout(out, ambient)
        return out, bn, [new_mm, new_mv]
    if blk.kind == "fc_act":
        fc = blk.fc
        x, w = val(fc, 0), val(fc, 1)
        b = None if fc.attrs.get("no_bias") else val(fc, 2)
        out = _fused.fused_block_fc_act(fc.attrs, blk.act, x, w, b)
        _note_block_cost(blk, out, x, w)
        _note_block_numerics(blk, out)
        return out, None, None
    raise ValueError("unknown fused block kind %r" % (blk.kind,))


def _note_block_numerics(blk, out):
    """Feed the block's output into an active numerics collection
    window (telemetry.numerics.block_stats) — zero added trace work
    outside the trainer's sampled stats variant."""
    from ..telemetry import numerics as _numerics
    _numerics.note_block(blk.name, out)


def _tuned_pallas(blk, x, w):
    """The block's Pallas-vs-XLA lowering choice, tuning cache first
    (``mxnet_tpu.autotune.block_config``, keyed by kind + the traced
    activation/weight shapes): a committed ``{"pallas": 0}`` from a
    ``tools/autotune.py`` A/B turns the Pallas leg off for this shape.
    The cache can only VETO the Pallas route, never force it onto an
    ineligible block; the region's interior row-block split is tuned
    separately under the ``matmul_stats`` key it dispatches.  Never
    raises — any failure keeps the planner's choice."""
    if not blk.pallas:
        return False
    try:
        from .. import autotune
        cfg = autotune.block_config(
            blk.kind, [tuple(x.shape), tuple(w.shape)],
            [str(x.dtype), str(w.dtype)],
            extra={"layout": blk.layout, "act": blk.act or ""})
        if cfg and not cfg.get("pallas", True):
            return False
    except MemoryError:  # pragma: no cover - never mask resource exhaustion
        raise
    except Exception:  # mxlint: allow-broad-except(the tuning-cache lookup is advisory trace-time observability; a failure keeps the planner's lowering choice)
        pass
    return True


def _note_block_cost(blk, out, x, w, pallas=None):
    """Register the applied block as a pending cost-database signature
    (telemetry.costdb) with analytic flops/bytes estimates from the
    trace-time shapes — runs host-side inside the trace, once per
    compile.  The dispatch that owns this compile binds the signature
    and attributes measured wall time to it.  ``pallas``: the
    lowering actually dispatched (defaults to the planner's choice).
    Observability: any failure is swallowed, the trace must never pay
    for it."""
    if pallas is None:
        pallas = blk.pallas
    try:
        from ..telemetry import costdb
        import numpy as _np

        def _nbytes(a):
            return int(a.size) * _np.dtype(a.dtype).itemsize

        shapes = [tuple(x.shape)] + ([tuple(w.shape)]
                                     if w is not None else [])
        dtypes = [str(x.dtype)] + ([str(w.dtype)]
                                   if w is not None else [])
        if w is not None:
            # conv and FC share one formula: every output element costs
            # (w.size / n_out) MACs — C*R*S for a conv, the input width
            # for an FC — plus the ~10 flops/element BN/act epilogue.
            # n_out comes from the op attrs (num_filter / num_hidden),
            # not from a weight axis, so a native HWIO weight layout
            # cannot skew the estimate.
            node = blk.conv if blk.conv is not None else blk.fc
            n_out = int(node.attrs.get("num_filter")
                        or node.attrs.get("num_hidden")
                        or w.shape[0])
            flops = 2.0 * int(out.size) * int(w.size) / n_out \
                + 10.0 * int(out.size)
            bytes_ = _nbytes(x) + _nbytes(w) + _nbytes(out)
        else:
            # bn_act: pure elementwise normalize/scale/shift/act
            flops = 10.0 * int(out.size)
            bytes_ = _nbytes(x) + _nbytes(out)
        costdb.note_block(
            blk.name, blk.kind, shapes, dtypes, flops=flops,
            bytes_accessed=bytes_, layout=blk.layout,
            pallas=pallas, graph=blk.graph, plan=blk.plan_id)
    except MemoryError:  # pragma: no cover - never mask resource exhaustion
        raise
    except Exception:  # mxlint: allow-broad-except(cost-signature capture is observability inside a jit trace; any failure must not fail the compile)
        pass
