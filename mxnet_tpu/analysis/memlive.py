"""Static memory-liveness analyzer: bind-time peak-HBM prediction.

PR 4's :mod:`mxnet_tpu.telemetry.memory` budget checks only observe
*after* XLA compiles — an over-budget model pays a full trace+compile
before it learns it cannot run, and nothing can say *which* activations
to rematerialize or *which* optimizer slots to shard.  This pass does
the memory planning Glow performs during lowering (arXiv:1805.00907,
liveness intervals in view before codegen) with the analytic per-node
features the learned-TPU-cost-model line showed are accurate enough to
rank decisions (arXiv:2008.01040): a topological interval analysis over
the composed train step — forward activations, autodiff residuals,
backward cotangents, optimizer state — byte-accurate via the verifier's
shape pass, fusion-plan-aware (interior edges of a
:class:`~.fusion.FusedBlock` never materialize) and donation/sharding-
aware (donated state is updated in place; sharded dims divide by their
mesh axis size).

Timeline model (train): forward node ``i`` of ``N`` executes at ``t=i``;
its backward executes at ``t = 2N-1-i`` (reverse topo order); the
optimizer update runs at ``t = 2N``.  A residual saved for the backward
of its *earliest* forward consumer is therefore the longest-lived — the
classic reason remat targets early, cheap-to-recompute chains.

Rule catalog (emitted by :func:`check_memory`; all opt-in — plain
``verify_symbol`` runs none of them):

========  ========  ====================================================
rule      severity  meaning
========  ========  ====================================================
MXG017    error     predicted peak HBM exceeds the armed budget at bind
                    time — names the peak node and top live buffers,
                    before any compile
MXG018    warning   prediction drift: analytic peak vs the XLA
                    ``memory_analysis`` total outside
                    ``MXNET_TPU_MEMLIVE_TOL`` (keeps these formulas
                    honest the way MXG010 is calibrated)
MXG019    warning   remat candidate: residual-heavy fusion chain ranked
                    by bytes-freed-at-peak per recompute FLOP
MXG020    warning   ZeRO-shardable: replicated optimizer-state bytes a
                    ``reshard.py`` rule table could shard over the data
                    axis, with the projected per-rank saving
MXG021    warning   donation: a step input is dead after its first use
                    but not donated, so XLA cannot reuse its buffer
========  ========  ====================================================

Entry points: :func:`analyze` (the engine), :func:`check_memory` (rule
emission into a verifier :class:`~.verifier.Report`),
``verify_symbol(..., memory=...)`` / ``Symbol.verify(memory=...)``,
``python -m mxnet_tpu.analysis --memory`` and ``tools/mem_top.py``.
Predictions are pushed to
:func:`mxnet_tpu.telemetry.memory.note_static_prediction` so the budget
check and ``HbmOomError`` report both the static and the XLA peak from
one predictor.
"""
from __future__ import annotations

__all__ = ["Buffer", "LivenessAnalysis", "analyze", "analyze_memory",
           "check_memory", "record_prediction", "CATEGORIES",
           "memlive_tolerance"]

# per-category taxonomy of the watermark breakdown
CATEGORIES = ("params", "activations", "residuals", "optimizer",
              "workspace")

_ADVICE_CAP = 3        # MXG019/021 diagnostics emitted per report
_TOP_BUFFERS = 5       # live buffers named in MXG017 messages


def memlive_tolerance(default=0.25):
    """MXG018 relative drift tolerance (``MXNET_TPU_MEMLIVE_TOL``).

    The default is calibrated against the model zoo: forward-plan
    drift vs ``memory_analysis`` measures within +-12% on every zoo
    model (worst: resnext's grouped convs at -11.4%), so 25% flags
    real formula regressions without tripping on XLA's temp-buffer
    scheduling freedom."""
    import os
    raw = os.environ.get("MXNET_TPU_MEMLIVE_TOL", "").strip()
    if not raw:
        return float(default)
    return float(raw)


def _fmt_bytes(n):
    from ..telemetry.memory import _fmt_bytes as fmt
    return fmt(int(n))


class Buffer:
    """One materialized allocation with its liveness interval.

    ``start``/``end`` are inclusive timeline positions (see the module
    docstring for the schedule).  ``node`` is the defining node name,
    ``category`` one of :data:`CATEGORIES`, ``first_use`` the first
    consumer's timeline position (inputs only — the donation audit asks
    whether the interval closes right there), ``is_input`` marks step
    inputs (data/label variables).
    """
    __slots__ = ("name", "node", "category", "nbytes", "start", "end",
                 "shape", "dtype", "is_input", "first_use")

    def __init__(self, name, node, category, nbytes, start, end,
                 shape=None, dtype=None, is_input=False, first_use=None):
        self.name = name
        self.node = node
        self.category = category
        self.nbytes = int(nbytes)
        self.start = int(start)
        self.end = int(end)
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = str(dtype) if dtype is not None else None
        self.is_input = bool(is_input)
        self.first_use = first_use

    @property
    def span(self):
        return self.end - self.start + 1

    def as_dict(self):
        return {"name": self.name, "node": self.node,
                "category": self.category, "bytes": self.nbytes,
                "start": self.start, "end": self.end,
                "shape": list(self.shape) if self.shape else None,
                "dtype": self.dtype}

    def __repr__(self):
        return ("<Buffer %s %s [%d,%d] %s>"
                % (self.name, self.category, self.start, self.end,
                   _fmt_bytes(self.nbytes)))


class LivenessAnalysis:
    """Result of :func:`analyze`: buffers, intervals, and the peak."""

    def __init__(self, buffers, n_nodes, is_train, program=None,
                 mesh=None, n_slots=0, donate=frozenset(),
                 remat_chains=(), skipped_bytes=0, peak_names=None):
        self.buffers = list(buffers)
        self.n_nodes = int(n_nodes)
        self.is_train = bool(is_train)
        self.program = program
        self.mesh = dict(mesh or {})
        self.n_slots = int(n_slots)
        self.donate = frozenset(donate)
        self._remat_chains = list(remat_chains)
        self.skipped_bytes = int(skipped_bytes)  # never-materialized (fused)
        self._names = list(peak_names or ())     # topo node names
        self.peak_bytes = 0
        self.peak_pos = 0
        self.breakdown = {c: 0 for c in CATEGORIES}
        self.category_totals = {c: 0 for c in CATEGORIES}
        self._sweep()

    # ------------------------------------------------------------ peak

    def _sweep(self):
        """Event sweep over buffer intervals: running per-category sums,
        recording the watermark and its timeline position."""
        events = {}
        for b in self.buffers:
            self.category_totals[b.category] += b.nbytes
            events.setdefault(b.start, []).append((b.nbytes, b.category))
            events.setdefault(b.end + 1, []).append((-b.nbytes,
                                                     b.category))
        live = {c: 0 for c in CATEGORIES}
        total = 0
        for t in sorted(events):
            for delta, cat in events[t]:
                live[cat] += delta
                total += delta
            if total > self.peak_bytes:
                self.peak_bytes = total
                self.peak_pos = t
                self.breakdown = dict(live)

    @property
    def timeline_len(self):
        return (2 * self.n_nodes + 1) if self.is_train else self.n_nodes

    def node_at(self, t):
        """Underlying graph-node name for timeline position ``t`` (no
        phase decoration; None for the optimizer-update slot)."""
        n = self.n_nodes
        if self.is_train and t >= 2 * n:
            return None
        i = (2 * n - 1 - t) if (self.is_train and t >= n) else t
        if 0 <= i < len(self._names):
            return self._names[i]
        return None

    def describe_pos(self, t):
        """Human name for timeline position ``t`` with its phase."""
        n = self.n_nodes
        if self.is_train and t >= 2 * n:
            return "<optimizer update>"
        raw = self.node_at(t) or ("#%d" % t)
        if self.is_train and t >= n:
            return "bwd(%s)" % raw
        return raw

    @property
    def peak_node(self):
        return self.describe_pos(self.peak_pos)

    def live_at(self, t):
        return sorted((b for b in self.buffers if b.start <= t <= b.end),
                      key=lambda b: -b.nbytes)

    @property
    def live_at_peak(self):
        return self.live_at(self.peak_pos)

    # ---------------------------------------------------------- advice

    def residual_peak_pos(self):
        """Timeline position where the most residual bytes are live —
        where rematerialization frees the most (may differ from the
        global peak, e.g. when the watermark is in the update phase)."""
        events = {}
        for b in self.buffers:
            if b.category != "residuals":
                continue
            events.setdefault(b.start, []).append(b.nbytes)
            events.setdefault(b.end + 1, []).append(-b.nbytes)
        best_pos, best, live = self.peak_pos, 0, 0
        for t in sorted(events):
            live += sum(events[t])
            if live > best:
                best, best_pos = live, t
        return best_pos

    def remat_candidates(self):
        """Residual-heavy chains ranked by bytes-freed-at-peak per
        recompute FLOP (MXG019).  Each record:
        ``{node, members, bytes_freed, recompute_flops, score}``.
        Bytes-freed are measured at the residual watermark."""
        out = []
        peak = self.residual_peak_pos()
        owner = {}
        for b in self.buffers:
            if b.category == "residuals" and b.start <= peak <= b.end:
                owner.setdefault(b.node, []).append(b)
        for terminal, members, flops in self._remat_chains:
            freed = sum(b.nbytes for m in members
                        for b in owner.get(m, ()))
            if freed <= 0:
                continue
            out.append({"node": terminal, "members": list(members),
                        "bytes_freed": int(freed),
                        "recompute_flops": int(flops),
                        "score": freed / float(flops + 1)})
        out.sort(key=lambda r: (-r["score"], -r["bytes_freed"],
                                r["node"]))
        return out

    def zero_audit(self):
        """Replicated optimizer-state audit (MXG020): slots for params
        without a model-parallel rule are replicated over the data axis;
        sharding them ZeRO-style saves ``bytes * (1 - 1/data)``/rank."""
        data = int(self.mesh.get("data", 1) or 1)
        if not self.is_train or self.n_slots <= 0 or data <= 1:
            return []
        out = []
        for b in self.buffers:
            if b.category != "optimizer":
                continue
            saving = int(b.nbytes * (1.0 - 1.0 / data))
            if saving > 0:
                out.append({"param": b.node, "slot_bytes": b.nbytes,
                            "saving_per_rank": saving,
                            "data_size": data})
        out.sort(key=lambda r: (-r["saving_per_rank"], r["param"]))
        return out

    def donation_audit(self):
        """Step inputs dead after their first use but not donated
        (MXG021): ``{input, bytes, last_use}`` records."""
        out = []
        for b in self.buffers:
            if not b.is_input or b.name in self.donate:
                continue
            if b.first_use is None:
                continue
            # "dead after first use": the interval closes at the first
            # consumer — no later forward reader, no backward residual
            if b.end == b.first_use:
                out.append({"input": b.name, "bytes": b.nbytes,
                            "last_use": b.end})
        out.sort(key=lambda r: (-r["bytes"], r["input"]))
        return out

    def as_dict(self):
        return {
            "program": self.program,
            "is_train": self.is_train,
            "peak_bytes": int(self.peak_bytes),
            "peak_node": self.peak_node,
            "breakdown": {c: int(v) for c, v in self.breakdown.items()},
            "category_totals": {c: int(v)
                                for c, v in self.category_totals.items()},
            "skipped_bytes": int(self.skipped_bytes),
            "n_buffers": len(self.buffers),
        }


# --------------------------------------------------------------- engine

def _prod(shape):
    n = 1
    for d in shape:
        n *= int(d)
    return n


def _shard_div(shape, dim, size):
    """Bytes divisor for sharding ``dim`` of ``shape`` over ``size``
    ranks (1 when the dim does not divide evenly — stays replicated)."""
    if size <= 1 or not shape or dim is None or dim >= len(shape):
        return 1
    return size if int(shape[dim]) % size == 0 else 1


def analyze(sym, shapes=None, types=None, *, is_train=True, mesh=None,
            tp_rules=None, n_slots=0, donate=(), fuse=None,
            layout="NCHW", inputs=None, program=None,
            topo=None, structs=None):
    """Run the static liveness pass; returns a :class:`LivenessAnalysis`.

    ``shapes``/``types``: as ``verify_symbol`` (input name -> shape /
    dtype).  ``is_train`` models the full fwd+bwd+update schedule with
    residuals, cotangents and ``n_slots`` float32 optimizer slots per
    parameter.  ``mesh`` ({axis: size}) divides batch-sharded dims by
    the ``data`` axis size and ``tp_rules``-sharded parameter dims by
    the ``model`` axis size.  ``donate`` is a collection of donated
    input names, or True for the trainer convention (params + optimizer
    + aux donated, updated in place).  ``fuse``/``layout`` control the
    fusion plan (None follows the ``MXNET_FUSE_BLOCKS`` default);
    interior edges of fused blocks never materialize.  ``inputs`` names
    the step inputs (defaults to the keys of ``shapes``); everything
    else in ``list_arguments()`` is a parameter.  ``topo``/``structs``
    accept the verifier's already-traced shape pass to avoid re-running
    it.
    """
    from ..symbol import _classify_vars
    from .verifier import Report, _shape_pass, _topo_from_entries
    from .fusion import _consumers
    from .perf import node_cost_estimate

    shapes = dict(shapes or {})
    entries = sym._entries
    if topo is None:
        topo = _topo_from_entries(entries)
    if structs is None:
        _, structs = _shape_pass(sym, topo, shapes, dict(types or {}),
                                 Report())

    mesh = dict(mesh or {})
    tp_rules = dict(tp_rules or {})
    data_size = int(mesh.get("data", 1) or 1)
    model_size = int(mesh.get("model", 1) or 1)

    input_names = set(inputs) if inputs is not None else set(shapes)
    arg_nodes, aux_nodes = _classify_vars(topo)
    param_nodes = [v for v in arg_nodes if v.name not in input_names]
    input_nodes = [v for v in arg_nodes if v.name in input_names]

    if donate is True:
        donate_set = ({v.name for v in param_nodes}
                      | {v.name for v in aux_nodes})
        state_donated = True
    else:
        donate_set = set(donate or ())
        state_donated = bool(param_nodes) and all(
            v.name in donate_set for v in param_nodes)

    # fusion plan: interior edges never materialize
    skip, chains = set(), []
    if fuse is None:
        from .. import config as _config
        fuse = _config.get_bool("MXNET_FUSE_BLOCKS")
    if fuse:
        try:
            from .fusion import plan_block_fusion
            plan = plan_block_fusion(topo, entries, layout=layout,
                                     is_train=is_train)
            skip = set(plan.skip)
            for blk in plan.blocks.values():
                # FusedBlock.chain holds member NAMES (strings)
                chains.append((blk.name,
                               tuple(nd if isinstance(nd, str)
                                     else nd.name
                                     for nd in blk.chain)))
        except Exception:  # mxlint: allow-broad-except(fusion planning is advisory; an unplannable graph falls back to the unfused liveness model)
            skip, chains = set(), []

    pos = {id(nd): i for i, nd in enumerate(topo)}
    n = len(topo)
    end = (2 * n) if is_train else (n - 1)
    consumers = _consumers(topo, entries)

    def measure(node):
        """(nbytes, elems, shape, dtype) of a node's materialized
        outputs, sharding-aware; None when shapes are unresolved."""
        sts = structs.get(id(node))
        if not sts:
            return None
        nbytes = elems = 0
        shape0 = dtype0 = None
        for st in sts:
            shp = tuple(int(d) for d in st.shape)
            if shape0 is None:
                shape0, dtype0 = shp, st.dtype
            if node.is_variable:
                if node.name in tp_rules:
                    div = _shard_div(shp, tp_rules[node.name],
                                     model_size)
                elif node.name in input_names:
                    div = _shard_div(shp, 0, data_size)
                else:
                    div = 1  # replicated state
            else:
                div = _shard_div(shp, 0, data_size)  # batch-sharded
            e = _prod(shp) // div
            elems += e
            nbytes += e * st.dtype.itemsize
        return nbytes, elems, shape0, dtype0

    def flops_of(node):
        sts = structs.get(id(node)) or ()
        in_shapes = []
        for (src, oi) in node.inputs:
            s = structs.get(id(src))
            if s and oi < len(s):
                in_shapes.append(tuple(int(d) for d in s[oi].shape))
        out_shapes = [tuple(int(d) for d in st.shape) for st in sts]
        est = node_cost_estimate(node, in_shapes, out_shapes)
        return est[0] if est else 0

    buffers = []
    skipped_bytes = 0
    residual_owners = set()

    # ---- long-lived state: params, aux, optimizer slots
    for v in list(param_nodes) + list(aux_nodes):
        m = measure(v)
        if m is None:
            continue
        nbytes, elems, shp, dt = m
        buffers.append(Buffer(v.name, v.name, "params", nbytes, 0, end,
                              shp, dt))
        if is_train and n_slots > 0 and v in param_nodes:
            # slots are float32, sharded like the parameter they track
            buffers.append(Buffer(v.name + ".opt", v.name, "optimizer",
                                  elems * 4 * n_slots, 0, end, shp,
                                  "float32"))

    # ---- step inputs (data / labels)
    for v in input_nodes:
        m = measure(v)
        if m is None:
            continue
        nbytes, _elems, shp, dt = m
        cs = [c for (c, _s) in consumers.get(id(v), ()) if c is not None]
        if not cs:
            continue  # dead input — MXG003's finding, not a buffer
        first = min(pos[id(c)] for c in cs)
        last = max(pos[id(c)] for c in cs)
        if is_train:
            # inputs are residuals for the weight-gradient computation
            last = max(last, 2 * n - 1 - first)
        buffers.append(Buffer(v.name, v.name, "activations", nbytes,
                              0, last, shp, dt, is_input=True,
                              first_use=first))

    # ---- forward activations / residuals + backward cotangents
    for node in topo:
        if node.is_variable:
            continue
        m = measure(node)
        if m is None:
            continue
        nbytes, _elems, shp, dt = m
        if id(node) in skip:
            skipped_bytes += nbytes
            continue
        p = pos[id(node)]
        cons = consumers.get(id(node), ())
        op_cons = [c for (c, _s) in cons if c is not None]
        is_head = any(c is None for (c, _s) in cons)
        ends = [p]
        if op_cons:
            ends.append(max(pos[id(c)] for c in op_cons))
        if is_head:
            # head outputs persist to the end of the step
            ends.append(end)
        if is_train and op_cons:
            # saved for the backward of the earliest consumer
            ends.append(2 * n - 1 - min(pos[id(c)] for c in op_cons))
        last = max(ends)
        cat = ("residuals" if (is_train and last >= n and not is_head)
               else "activations")
        if cat == "residuals":
            residual_owners.add(node.name)
        buffers.append(Buffer(node.name, node.name, cat, nbytes, p,
                              last, shp, dt))

        if is_train:
            # cotangent of this output: born when the latest forward
            # consumer's backward runs (the earliest backward step),
            # consumed at this node's own backward
            t_own = 2 * n - 1 - p
            if op_cons:
                born = 2 * n - 1 - max(pos[id(c)] for c in op_cons)
            else:
                born = n  # loss head seeds the backward
            born = min(born, t_own)
            buffers.append(Buffer("d(%s)" % node.name, node.name,
                                  "workspace", nbytes, born, t_own,
                                  shp, dt))

    # ---- parameter gradients: accumulate over the backward, consumed
    # by the optimizer update
    if is_train:
        for v in param_nodes:
            m = measure(v)
            if m is None:
                continue
            nbytes, _elems, shp, dt = m
            cs = [c for (c, _s) in consumers.get(id(v), ())
                  if c is not None]
            if not cs:
                continue
            born = 2 * n - 1 - max(pos[id(c)] for c in cs)
            buffers.append(Buffer("d(%s)" % v.name, v.name, "workspace",
                                  nbytes, born, 2 * n, shp, dt))
        if not state_donated:
            # un-donated state: the update writes fresh output buffers
            # instead of reusing the inputs
            for v in param_nodes:
                m = measure(v)
                if m is None:
                    continue
                nbytes, elems, shp, dt = m
                buffers.append(Buffer(v.name + "'", v.name, "workspace",
                                      nbytes, 2 * n, 2 * n, shp, dt))
                if n_slots > 0:
                    buffers.append(Buffer(v.name + ".opt'", v.name,
                                          "workspace",
                                          elems * 4 * n_slots,
                                          2 * n, 2 * n, shp, "float32"))

    # ---- remat chains: fusion blocks when planned, else each
    # residual-owning op is its own single-member chain
    name2node = {nd.name: nd for nd in topo}
    remat_chains = []
    if chains:
        for terminal, members in chains:
            fl = sum(flops_of(name2node[mname]) for mname in members
                     if mname in name2node)
            remat_chains.append((terminal, members, fl))
    else:
        for mname in sorted(residual_owners):
            nd = name2node.get(mname)
            if nd is None:
                continue
            remat_chains.append((mname, (mname,), flops_of(nd)))

    return LivenessAnalysis(
        buffers, n, is_train, program=program, mesh=mesh,
        n_slots=n_slots, donate=donate_set, remat_chains=remat_chains,
        skipped_bytes=skipped_bytes,
        peak_names=[nd.name for nd in topo])


# ------------------------------------------------------------- reporting

def record_prediction(analysis, program=None):
    """Publish a prediction: CATALOG gauges, a ``memlive`` flight event,
    and the :mod:`~mxnet_tpu.telemetry.memory` static-prediction slot
    (so budget checks and ``HbmOomError`` report it)."""
    prog = program or analysis.program or "memlive"
    remats = analysis.remat_candidates()
    zeros = analysis.zero_audit()
    info = analysis.as_dict()
    info["program"] = prog
    info["remat_candidates"] = remats[:_ADVICE_CAP]
    info["zero_saving_per_rank"] = sum(z["saving_per_rank"]
                                       for z in zeros)
    try:
        from ..telemetry import flight, gauge
        g = gauge("mxtpu_predicted_peak_bytes")
        g.labels(program=prog, category="total").set(
            analysis.peak_bytes)
        for cat, val in analysis.breakdown.items():
            g.labels(program=prog, category=cat).set(val)
        gauge("mxtpu_remat_candidate_bytes").labels(program=prog).set(
            sum(r["bytes_freed"] for r in remats))
        flight.record("memlive", program=prog,
                      peak_bytes=int(analysis.peak_bytes),
                      peak_node=analysis.peak_node,
                      **{c: int(v)
                         for c, v in analysis.breakdown.items()})
    except Exception:  # mxlint: allow-broad-except(prediction accounting is observability; a metric failure must never mask the analysis)
        pass
    try:
        from ..telemetry import memory as _tmem
        _tmem.note_static_prediction(prog, info)
    except Exception:  # mxlint: allow-broad-except(same — the memory-module slot is advisory)
        pass
    return info


def check_memory(sym, shapes=None, types=None, report=None, *,
                 budget_bytes=None, plan_total=None, tol=None,
                 advice=True, record=False, program=None,
                 topo=None, structs=None, **opts):
    """Run :func:`analyze` and emit MXG017-021 into ``report``.

    ``budget_bytes``: peak budget for MXG017 (default: armed device
    budget ``device_capacity_bytes() * budget_fraction()`` when known,
    else the check is skipped).  ``plan_total``: an XLA
    ``MemoryPlan.total_bytes`` (or the plan itself) to drift-check
    against (MXG018) under ``tol`` / ``MXNET_TPU_MEMLIVE_TOL``.
    ``advice`` emits MXG019/020/021.  ``record`` publishes gauges, the
    ``memlive`` flight event and the static-prediction slot.  Remaining
    ``opts`` go to :func:`analyze`.  Returns the
    :class:`LivenessAnalysis` (the report carries the findings).
    """
    from .verifier import Report
    if report is None:
        report = Report()
    analysis = analyze(sym, shapes, types, program=program, topo=topo,
                       structs=structs, **opts)
    peak = analysis.peak_bytes
    peak_node_raw = analysis.node_at(analysis.peak_pos)

    if budget_bytes is None:
        try:
            from ..telemetry import memory as _tmem
            cap = _tmem.device_capacity_bytes()
            frac = _tmem.budget_fraction()
            if cap and frac > 0:
                budget_bytes = int(cap * frac)
        except Exception:  # mxlint: allow-broad-except(no budget signal means the MXG017 leg is simply not armed)
            budget_bytes = None

    if budget_bytes and peak > budget_bytes:
        top = ", ".join("%s (%s, %s)" % (b.name, b.category,
                                         _fmt_bytes(b.nbytes))
                        for b in analysis.live_at_peak[:_TOP_BUFFERS])
        bd = ", ".join("%s=%s" % (c, _fmt_bytes(v))
                       for c, v in analysis.breakdown.items() if v)
        report.add(
            "MXG017", "error",
            "predicted peak HBM %s at %s exceeds the memory budget %s "
            "(%.0f%%) before any compile; breakdown: %s; top live "
            "buffers: %s"
            % (_fmt_bytes(peak), analysis.peak_node,
               _fmt_bytes(budget_bytes), 100.0 * peak / budget_bytes,
               bd, top),
            node=peak_node_raw or analysis.peak_node,
            advice={"peak_bytes": int(peak),
                    "budget_bytes": int(budget_bytes),
                    "peak_node": analysis.peak_node,
                    "breakdown": {c: int(v) for c, v
                                  in analysis.breakdown.items()}})

    if plan_total is not None:
        total = getattr(plan_total, "total_bytes", plan_total)
        total = int(total)
        if total > 0:
            tolerance = memlive_tolerance() if tol is None else float(tol)
            drift = (peak - total) / float(total)
            try:
                from ..telemetry import gauge
                gauge("mxtpu_memlive_drift_ratio").labels(
                    program=program or "memlive").set(drift)
            except Exception:  # mxlint: allow-broad-except(drift gauge is observability only)
                pass
            if abs(drift) > tolerance:
                report.add(
                    "MXG018", "warning",
                    "static peak prediction %s drifts %.0f%% from the "
                    "XLA memory_analysis total %s (tolerance %.0f%%); "
                    "the liveness formulas need recalibration for this "
                    "graph shape"
                    % (_fmt_bytes(peak), 100.0 * drift,
                       _fmt_bytes(total), 100.0 * tolerance),
                    node=peak_node_raw,
                    advice={"static_peak_bytes": int(peak),
                            "plan_total_bytes": total,
                            "drift": drift, "tolerance": tolerance})

    if advice:
        for rec in analysis.remat_candidates()[:_ADVICE_CAP]:
            report.add(
                "MXG019", "warning",
                "remat candidate: chain %s frees %s at the predicted "
                "peak for ~%s recompute FLOPs (score %.3g bytes/FLOP); "
                "MXNET_BACKWARD_DO_MIRROR=1 or a jax.checkpoint over "
                "the chain trades this memory for compute"
                % (rec["node"], _fmt_bytes(rec["bytes_freed"]),
                   "{:,}".format(rec["recompute_flops"]),
                   rec["score"]),
                node=rec["node"], advice=dict(rec, kind="remat"))
        zeros = analysis.zero_audit()
        if zeros:
            total_saving = sum(z["saving_per_rank"] for z in zeros)
            total_slots = sum(z["slot_bytes"] for z in zeros)
            top = ", ".join("%s (%s)" % (z["param"],
                                         _fmt_bytes(z["slot_bytes"]))
                            for z in zeros[:_TOP_BUFFERS])
            report.add(
                "MXG020", "warning",
                "%s of optimizer state is replicated across the "
                "data axis (size %d); sharding it ZeRO-style via a "
                "reshard.py rule table would save %s per rank — "
                "largest slots: %s"
                % (_fmt_bytes(total_slots), zeros[0]["data_size"],
                   _fmt_bytes(total_saving), top),
                node=zeros[0]["param"],
                advice={"kind": "zero", "params": zeros,
                        "total_slot_bytes": int(total_slots),
                        "total_saving_per_rank": int(total_saving)})
        for rec in analysis.donation_audit()[:_ADVICE_CAP]:
            report.add(
                "MXG021", "warning",
                "step input %r (%s) is dead after its first use at "
                "t=%d but not donated; donating it would let XLA reuse "
                "the buffer for the step's outputs"
                % (rec["input"], _fmt_bytes(rec["bytes"]),
                   rec["last_use"]),
                node=rec["input"], advice=dict(rec, kind="donate"))

    if record:
        record_prediction(analysis, program=program)
    return analysis


# package-level alias: the generic name ``analyze`` stays local to this
# module; ``mxnet_tpu.analysis.analyze_memory`` is the public spelling
analyze_memory = analyze
