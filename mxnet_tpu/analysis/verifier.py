"""Static graph verifier: pre-compile defect detection for Symbol graphs.

The reference surfaces shape/dtype mismatches only at bind/execute time
(``GraphExecutor::Init`` runs InferShape/InferType and throws mid-bind);
the TPU build additionally pays an XLA compile before the first error can
appear.  This pass walks the DAG *abstractly* — per-node
``jax.eval_shape`` over each op's registered fcompute — so every defect
is caught before any device time is spent and is attributed to the
offending node, in the spirit of the typed, verifiable IR passes of TVM
(arXiv:1802.04799) and Relay (arXiv:1810.00952).

Check catalog (rule IDs are stable; docs/api/analysis.md documents them):

========  ========  ====================================================
rule      severity  meaning
========  ========  ====================================================
MXG001    error     cycle in the graph (names the nodes on the cycle)
MXG002    error     duplicate node name (name-keyed binding would alias)
MXG003    warning   dead node / unused input (unreachable from any head,
                    or a head variable no op consumes)
MXG004    error     op with parameter inputs but no param-shape rule in
                    ``ops.shapes`` and no explicit ``__shape__``
MXG005    error     shape/attr inconsistency — the op's fcompute rejects
                    its input shapes (message carries the op error)
MXG006    warning   implicit dtype promotion (mixed float widths feeding
                    one op) or unresolvable input dtypes
MXG007    error     sharded-graph coverage: a shardable parameter gets no
                    rule from ``parallel.tp_rules`` and carries no
                    explicit ``__tp__ = 'replicate'`` annotation
MXG008    error     registry self-check finding (alias/hook/rule drift)
MXG009    warning   shape underdetermined — a rule exists but could not
                    produce the parameter's shape from what is known
MXG010    warning   predicted-slow node: the learned cost model
                    (``mxnet_tpu.autotune``) predicts a wall time more
                    than ``slow_factor`` x the node's roofline-
                    attainable time (opt-in: runs only when a
                    ``cost_model`` is supplied; see :mod:`.perf`)
MXG017    error     predicted peak HBM exceeds the armed memory budget
                    at bind time, before any compile (opt-in via
                    ``memory=``; see :mod:`.memlive`)
MXG018    warning   static-peak vs XLA ``memory_analysis`` drift beyond
                    ``MXNET_TPU_MEMLIVE_TOL`` (:mod:`.memlive`)
MXG019    warning   remat candidate: residual-heavy chain ranked by
                    bytes-freed-at-peak per recompute FLOP
MXG020    warning   ZeRO-shardable replicated optimizer state with the
                    projected per-rank saving
MXG021    warning   step input dead after first use but not donated
========  ========  ====================================================

MXG011-016 (distributed/SPMD) live in :mod:`.spmd`; MXG017-021 (memory
liveness, all opt-in via ``memory=``) in :mod:`.memlive`.

Entry points: :func:`verify_symbol` (the engine), :meth:`Symbol.verify`,
``Symbol.bind(..., strict=True)``, :func:`verify_json` (adds real
unreachable-node detection over the serialized layout), and
``python -m mxnet_tpu.analysis``.
"""
from __future__ import annotations

import json

from ..base import MXNetError

__all__ = ["Diagnostic", "Report", "verify_symbol", "verify_json",
           "verify_model", "infer_node_shapes"]

_SEVERITIES = ("error", "warning")


class Diagnostic:
    """One verifier finding, attributed to a node where possible."""
    __slots__ = ("rule", "severity", "node", "op", "message", "advice")

    def __init__(self, rule, severity, message, node=None, op=None,
                 advice=None):
        assert severity in _SEVERITIES, severity
        self.rule = rule
        self.severity = severity
        self.message = message
        self.node = node          # offending node name (str | None)
        self.op = op              # op name (str | None)
        self.advice = advice      # machine-readable payload (dict | None)

    def as_dict(self):
        """JSON-ready form (``python -m mxnet_tpu.analysis --json``)."""
        d = {"rule": self.rule, "severity": self.severity,
             "node": self.node, "op": self.op, "message": self.message}
        if self.advice is not None:
            d["advice"] = self.advice
        return d

    def __repr__(self):
        return "<Diagnostic %s %s>" % (self.rule, self.node or "<graph>")

    def __str__(self):
        where = self.node or "<graph>"
        if self.op:
            where += " (op %s)" % self.op
        return "%s [%s] %s: %s" % (self.rule, self.severity, where,
                                   self.message)


class Report:
    """Verification result: an ordered list of diagnostics."""

    def __init__(self, diagnostics=()):
        self.diagnostics = list(diagnostics)

    @property
    def errors(self):
        return [d for d in self.diagnostics if d.severity == "error"]

    @property
    def warnings(self):
        return [d for d in self.diagnostics if d.severity == "warning"]

    @property
    def ok(self):
        return not self.errors

    def __bool__(self):
        return self.ok

    def __iter__(self):
        return iter(self.diagnostics)

    def __len__(self):
        return len(self.diagnostics)

    def __str__(self):
        if not self.diagnostics:
            return "verify: OK (no findings)"
        lines = ["verify: %d error(s), %d warning(s)"
                 % (len(self.errors), len(self.warnings))]
        lines.extend("  " + str(d) for d in self.diagnostics)
        return "\n".join(lines)

    def add(self, *args, **kwargs):
        d = Diagnostic(*args, **kwargs)
        self.diagnostics.append(d)
        try:
            from ..telemetry import counter
            counter("mxtpu_verify_findings_total").labels(
                rule=d.rule).inc()
        except Exception:  # mxlint: allow-broad-except(finding accounting is observability; a metric failure must never mask the diagnostic itself)
            pass

    def raise_if_errors(self, context=""):
        if self.ok:
            return self
        head = "graph verification failed"
        if context:
            head += " (%s)" % context
        raise MXNetError(head + ":\n" + "\n".join(
            "  " + str(d) for d in self.errors))


# ------------------------------------------------------------ graph walking

def _collect_nodes(entries):
    """Every node reachable from ``entries`` — tolerates cycles (unlike
    Symbol._topo, which assumes a DAG and would not terminate)."""
    nodes, seen = [], set()
    stack = [n for (n, _i) in entries]
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        seen.add(id(node))
        nodes.append(node)
        stack.extend(src for (src, _i) in node.inputs)
    return nodes


def _find_cycle(entries):
    """Iterative three-color DFS; returns the node list of one cycle, or
    None.  Runs before any topo-order work — a cycle makes Symbol._topo
    spin forever, so this check gates everything else."""
    WHITE, GRAY, BLACK = 0, 1, 2
    color, parent = {}, {}
    for root, _i in entries:
        if color.get(id(root), WHITE) != WHITE:
            continue
        stack = [(root, iter([s for (s, _) in root.inputs]))]
        color[id(root)] = GRAY
        while stack:
            node, it = stack[-1]
            advanced = False
            for src in it:
                c = color.get(id(src), WHITE)
                if c == GRAY:
                    # walk parent chain back to src to name the cycle
                    cyc, cur = [node], node
                    while id(cur) != id(src):
                        cur = parent[id(cur)]
                        cyc.append(cur)
                    return list(reversed(cyc))
                if c == WHITE:
                    color[id(src)] = GRAY
                    parent[id(src)] = node
                    stack.append((src, iter([s for (s, _) in src.inputs])))
                    advanced = True
                    break
            if not advanced:
                color[id(node)] = BLACK
                stack.pop()
    return None


def _topo_from_entries(entries):
    from ..symbol import _topo_order
    return _topo_order(entries)


# -------------------------------------------------------------- the checks

def _check_duplicate_names(nodes, report):
    by_name = {}
    for n in nodes:
        by_name.setdefault(n.name, []).append(n)
    for name, group in sorted(by_name.items()):
        if len(group) > 1:
            kinds = ["variable" if n.is_variable else n.op.name
                     for n in group]
            report.add("MXG002", "error",
                       "%d distinct nodes share the name %r (%s); "
                       "name-keyed binding (arg_dict, checkpoints) would "
                       "silently alias them" % (len(group), name,
                                                ", ".join(kinds)),
                       node=name)


def _check_dead_entries(entries, nodes, report):
    """Head variables nothing consumes, and duplicate head entries."""
    consumed = set()
    for n in nodes:
        for (src, _i) in n.inputs:
            consumed.add(id(src))
    seen_entries = set()
    for node, idx in entries:
        if (id(node), idx) in seen_entries:
            report.add("MXG003", "warning",
                       "output %r is listed more than once in the heads"
                       % node.output_names()[idx], node=node.name)
        seen_entries.add((id(node), idx))
        if node.is_variable and id(node) not in consumed:
            report.add("MXG003", "warning",
                       "input variable %r is consumed by no operator and "
                       "is returned unchanged (dead input)" % node.name,
                       node=node.name)


def _var_dtype(node, type_overrides):
    import numpy as np
    if node.name in type_overrides:
        return np.dtype(type_overrides[node.name]).name
    return node.raw_attr.get("__dtype__", "float32")


def _auto_param_names(node):
    """The auto-created parameter/aux variable inputs of an op node:
    variables named ``<node>_<slot>`` (the Symbol._create convention)."""
    names = node.arg_names() + node.aux_names()
    out = []
    for slot, (src, _i) in zip(names, node.inputs):
        if src.is_variable and src.name == "%s_%s" % (node.name, slot):
            out.append((slot, src))
    return out


def _shape_pass(sym, topo, known_shapes, type_overrides, report):
    """Per-node abstract interpretation.

    Walks topo order keeping a ``jax.ShapeDtypeStruct`` tuple per node.
    Param-shape hooks run just-in-time at each consumer op, exactly as
    Symbol.infer_shape does, but a failure is localized to the node that
    raised instead of aborting the whole inference.  Returns
    ``({var_name: shape}, {id(node): tuple(ShapeDtypeStruct)})`` — the
    resolved variable shapes feed the TP pass, the per-node structs
    feed MXG010 (:mod:`.perf`) and the autotuner's zoo mode.
    """
    import jax
    import jax.numpy as jnp
    from ..ops import shapes as _shapes
    from ..ops.registry import OpContext, apply_op

    structs = {}          # id(node) -> tuple(ShapeDtypeStruct) | None
    var_shapes = {}       # id(var-node) -> shape
    var_reported = set()  # variables already attributed to a diagnostic
    resolved = {}         # var_name -> shape (the return value)

    # seed variable shapes: explicit kwargs first, then __shape__ attrs
    batch_size = None
    for node in topo:
        if not node.is_variable:
            continue
        shp = None
        if node.name in known_shapes:
            shp = tuple(known_shapes[node.name])
        elif "__shape__" in node.raw_attr:
            shp = tuple(json.loads(node.raw_attr["__shape__"]))
        if shp is not None:
            var_shapes[id(node)] = shp
            if batch_size is None and len(shp) > 0:
                batch_size = int(shp[0])

    def var_struct(node):
        shp = var_shapes.get(id(node))
        if shp is None:
            return None
        return (jax.ShapeDtypeStruct(tuple(shp),
                                     jnp.dtype(_var_dtype(node,
                                                          type_overrides))),)

    for node in topo:
        if node.is_variable:
            structs[id(node)] = var_struct(node)
            if structs[id(node)] is not None:
                resolved[node.name] = tuple(var_shapes[id(node)])
            continue

        slot_names = node.arg_names() + node.aux_names()

        # just-in-time param-shape hook: fill variable inputs whose shape
        # is still unknown from the shapes known so far
        hook = _shapes.get_param_shapes(node.op.name)
        unknown_vars = [(nm, src) for nm, (src, _i)
                        in zip(slot_names, node.inputs)
                        if src.is_variable and id(src) not in var_shapes]
        if hook is not None and unknown_vars:
            known_in = {}
            for nm, (src, _i) in zip(slot_names, node.inputs):
                st = structs.get(id(src))
                if st is not None and len(st) > _i:
                    known_in[nm] = tuple(st[_i].shape)
                elif src.is_variable and id(src) in var_shapes:
                    known_in[nm] = tuple(var_shapes[id(src)])
            try:
                inferred = hook(node.attrs, known_in)
            except Exception as e:  # mxlint: allow-broad-except(a hook runs user code e.g. CustomOpProp.infer_shape; any failure becomes a diagnostic)
                report.add("MXG005", "error",
                           "param-shape rule for op %s raised: %s"
                           % (node.op.name, e),
                           node=node.name, op=node.op.name)
                inferred = {}
            for nm, src in unknown_vars:
                if nm in inferred:
                    var_shapes[id(src)] = tuple(inferred[nm])
                    structs[id(src)] = var_struct(src)
                    resolved[src.name] = tuple(inferred[nm])

        # attribute still-unknown variable inputs
        missing = [(nm, src) for nm, src in unknown_vars
                   if id(src) not in var_shapes
                   and id(src) not in var_reported]
        auto_params = {nm for nm, _src in _auto_param_names(node)}
        if missing:
            for nm, src in missing:
                var_reported.add(id(src))
            auto_missing = [nm for nm, _s in missing if nm in auto_params]
            if hook is None and auto_missing:
                report.add(
                    "MXG004", "error",
                    "op %s auto-created parameter input(s) %s but has no "
                    "param-shape rule registered in ops.shapes and no "
                    "explicit __shape__; their shapes cannot be inferred"
                    % (node.op.name, auto_missing),
                    node=node.name, op=node.op.name)
            else:
                report.add(
                    "MXG009", "warning",
                    "shapes of input(s) %s of op %s are underdetermined "
                    "(provide them via infer kwargs or __shape__)"
                    % ([nm for nm, _s in missing], node.op.name),
                    node=node.name, op=node.op.name)

        # gather input structs; skip eval if anything upstream is unknown
        in_structs = []
        unknown_input = False
        for (src, idx) in node.inputs:
            st = structs.get(id(src))
            if st is None or len(st) <= idx:
                unknown_input = True
                break
            in_structs.append(st[idx])
        if unknown_input:
            structs[id(node)] = None
            continue

        # dtype-promotion audit: mixed float widths feeding one op.
        # issubdtype (not .kind == 'f') so bfloat16 — an ml_dtypes
        # extension type with kind 'V', and THE TPU compute dtype —
        # is covered.
        f_dtypes = sorted({jnp.dtype(st.dtype).name for st in in_structs
                           if jnp.issubdtype(st.dtype, jnp.floating)})
        if len(f_dtypes) > 1:
            report.add("MXG006", "warning",
                       "inputs of op %s mix float dtypes %s; XLA will "
                       "promote implicitly (check intended precision)"
                       % (node.op.name, f_dtypes),
                       node=node.name, op=node.op.name)

        # deferred batch dims in source-op shapes (RNN begin_state zeros)
        node_attrs = node.attrs
        shp = node_attrs.get("shape")
        if (not node.inputs and isinstance(shp, (tuple, list))
                and any(s == 0 for s in shp)):
            if batch_size is None:
                report.add("MXG005", "error",
                           "source op %s has a deferred (0) dim in shape "
                           "%s but no input shape fixes the batch size"
                           % (node.op.name, tuple(shp)),
                           node=node.name, op=node.op.name)
                structs[id(node)] = None
                continue
            node_attrs = dict(node_attrs)
            node_attrs["shape"] = tuple(batch_size if s == 0 else int(s)
                                        for s in shp)

        octx = OpContext(is_train=False, key=None)
        op = node.op

        def fn(*ins, _op=op, _attrs=node_attrs, _octx=octx):
            return apply_op(_op, _attrs, _octx, *ins)

        try:
            outs = jax.eval_shape(fn, *in_structs)
        except Exception as e:  # mxlint: allow-broad-except(fcompute tracing raises arbitrary exception types; each becomes a node diagnostic)
            msg = str(e).strip().splitlines()
            report.add("MXG005", "error",
                       "op %s rejects input shapes %s: %s"
                       % (node.op.name,
                          [tuple(st.shape) for st in in_structs],
                          msg[0] if msg else repr(e)),
                       node=node.name, op=node.op.name)
            structs[id(node)] = None
            continue
        if not isinstance(outs, (tuple, list)):
            outs = (outs,)
        structs[id(node)] = tuple(outs)
    return resolved, structs


def _check_tp_coverage(topo, arg_shapes, tp_size, report):
    """Sharded-graph coverage: every shardable parameter must either get
    a rule from ``derive_tp_rules`` or carry an explicit replicate
    annotation (``__tp__ = 'replicate'`` on the owning op node or the
    parameter variable)."""
    from ..parallel.tp_rules import derive_tp_rules, _weight_of
    rules = derive_tp_rules(topo, arg_shapes, tp_size)
    for node in topo:
        if node.is_variable or node.op is None:
            continue
        if node.op.name not in ("FullyConnected", "Convolution"):
            continue
        w, _b = _weight_of(node)
        if w is None or w in rules:
            continue
        ann = node.raw_attr.get("__tp__")
        if ann is None:
            for (src, _i) in node.inputs:
                if src.is_variable and src.name == w:
                    ann = src.raw_attr.get("__tp__")
                    break
        if ann == "replicate":
            continue
        if ann is not None:
            report.add("MXG007", "error",
                       "op %s has unknown __tp__ annotation %r (expected "
                       "'replicate')" % (node.op.name, ann),
                       node=node.name, op=node.op.name)
            continue
        shp = arg_shapes.get(w)
        report.add(
            "MXG007", "error",
            "parameter %r of op %s (shape %s) gets no tensor-parallel "
            "rule for tp_size=%d and carries no explicit "
            "__tp__='replicate' annotation; it would be silently "
            "replicated on every model shard"
            % (w, node.op.name, shp, tp_size),
            node=node.name, op=node.op.name)


def _registry_diagnostics(report):
    from ..ops import registry as _registry
    for problem in _registry.selfcheck():
        report.add("MXG008", "error", problem)


# ------------------------------------------------------------- entry points

def verify_symbol(sym, shapes=None, types=None, tp_size=1,
                  check_registry=False, report=None, cost_model=None,
                  slow_factor=3.0, plan=False, plan_layout="NCHW",
                  mesh=None, parallel=None, memory=None):
    """Verify a Symbol graph; returns a :class:`Report`.

    ``shapes``: {input_name: shape} (same keys as ``infer_shape`` kwargs;
    optional — without them only structural checks and __shape__-seeded
    inference run).  ``types``: {input_name: dtype}.  ``tp_size`` > 1
    additionally runs the sharding-coverage check against
    ``parallel.tp_rules``.  ``check_registry`` folds the op-registry
    self-check into the report.  ``cost_model`` (a fitted
    ``mxnet_tpu.autotune.CostModel`` or a saved-model path)
    additionally runs MXG010: nodes whose predicted wall exceeds their
    roofline-attainable time by more than ``slow_factor`` are named
    before any compile (:mod:`.perf`).  ``plan=True`` switches MXG010
    to plan mode: predictions for the COMMITTED fusion/layout plan
    (the ``graph_plan`` tuning-cache entry at ``plan_layout``; greedy
    on miss) instead of the default per-node lowering.  ``mesh``
    ({axis: size} descriptor) additionally runs the distributed-
    correctness pass (:mod:`.spmd`, MXG011-016) with ``parallel`` — a
    :func:`.spmd.build_config` dict describing the composed step.
    ``memory`` (True or a dict of :func:`.memlive.check_memory`
    options) additionally runs the static memory-liveness pass
    (MXG017-021), reusing this call's shape pass; like MXG010 it is
    opt-in and never runs on a plain verify.
    """
    report = report if report is not None else Report()
    shapes = dict(shapes or {})
    types = dict(types or {})

    if check_registry:
        _registry_diagnostics(report)

    entries = sym._entries
    cycle = _find_cycle(entries)
    if cycle is not None:
        report.add("MXG001", "error",
                   "graph contains a cycle through nodes [%s]; no "
                   "execution order exists"
                   % " -> ".join(n.name for n in cycle),
                   node=cycle[0].name)
        # everything below needs a topo order — stop here
        return report

    nodes = _collect_nodes(entries)
    _check_duplicate_names(nodes, report)
    _check_dead_entries(entries, nodes, report)

    topo = _topo_from_entries(entries)
    arg_shapes, structs = _shape_pass(sym, topo, shapes, types, report)

    if tp_size and tp_size > 1:
        _check_tp_coverage(topo, arg_shapes, tp_size, report)
    if mesh:
        from . import spmd as _spmd
        cfg = parallel if parallel is not None else _spmd.build_config()
        if not cfg.get("data_shapes") and shapes:
            cfg = dict(cfg)
            cfg["data_shapes"] = {k: v for k, v in shapes.items()
                                  if not k.endswith("_label")}
            cfg["label_shapes"] = {k: v for k, v in shapes.items()
                                   if k.endswith("_label")}
        # hand the pass the per-node shapes _shape_pass already traced
        # — re-inferring would run jax.eval_shape over the whole graph
        # a second time
        node_shapes = {}
        for nid, sts in structs.items():
            if sts is None:
                continue
            for i, st in enumerate(sts):
                node_shapes[(nid, i)] = tuple(int(d) for d in st.shape)
        _spmd.verify_spmd(sym, mesh, cfg, report=report,
                          shapes=node_shapes, arg_shapes=arg_shapes)
    if cost_model is not None:
        if plan:
            from .perf import check_predicted_plan
            check_predicted_plan(topo, sym._entries, structs,
                                 cost_model, factor=slow_factor,
                                 report=report, layout=plan_layout)
        else:
            from .perf import check_predicted_slow
            check_predicted_slow(topo, structs, cost_model,
                                 factor=slow_factor, report=report)
    if memory:
        from . import memlive as _memlive
        mopts = dict(memory) if isinstance(memory, dict) else {}
        if mesh and "mesh" not in mopts:
            mopts["mesh"] = dict(mesh)
        # hand over this call's shape pass — memlive would otherwise
        # re-trace the whole graph
        _memlive.check_memory(sym, shapes, types, report=report,
                              topo=topo, structs=structs, **mopts)
    return report


def infer_node_shapes(sym, shapes=None, types=None):
    """Per-node output shapes via the verifier's abstract-
    interpretation pass, without diagnostics: ``(topo,
    {id(node): tuple(shape tuples)})``.  Nodes whose shapes could not
    be resolved are absent.  Feeds the autotuner's zoo-model mode
    (``tools/autotune.py --model``)."""
    entries = sym._entries
    topo = _topo_from_entries(entries)
    scratch = Report()
    _resolved, structs = _shape_pass(sym, topo, dict(shapes or {}),
                                     dict(types or {}), scratch)
    out = {}
    for nid, sts in structs.items():
        if sts is None:
            continue
        out[nid] = tuple(tuple(int(d) for d in st.shape) for st in sts)
    return topo, out


def verify_json(json_str, shapes=None, types=None, tp_size=1,
                check_registry=False, cost_model=None,
                slow_factor=3.0, plan=False, plan_layout="NCHW",
                mesh=None, parallel=None, memory=None):
    """Verify a serialized symbol (the reference JSON graph layout).

    Runs every :func:`verify_symbol` check *plus* true dead-node
    detection: nodes present in the file but unreachable from any head —
    the defect class hand-edited or generator-produced checkpoints hit,
    which an in-memory Symbol cannot represent (it only holds what its
    heads reach).
    """
    from .. import symbol as _symbol
    report = Report()
    try:
        data = json.loads(json_str)
        raw_nodes = data.get("nodes", [])
        heads = [h[0] for h in data.get("heads", [])]

        # reachability over the flat node table
        reachable, stack = set(), list(heads)
        while stack:
            i = stack.pop()
            if i in reachable or i >= len(raw_nodes):
                continue
            reachable.add(i)
            stack.extend(inp[0] for inp in raw_nodes[i].get("inputs", []))
        for i, entry in enumerate(raw_nodes):
            if i not in reachable:
                report.add("MXG003", "warning",
                           "node %r (op %s) is unreachable from every "
                           "head (dead node)"
                           % (entry.get("name", "#%d" % i),
                              entry.get("op", "?")),
                           node=entry.get("name"))
    except (ValueError, TypeError, AttributeError, KeyError,
            IndexError) as e:
        # not the reference JSON layout at all — one diagnostic, not a
        # traceback (the CLI contract)
        report.add("MXG005", "error",
                   "graph does not parse as the symbol JSON layout: "
                   "%s" % e)
        return report

    try:
        sym = _symbol.load_json(json_str)
    except (MXNetError, ValueError, TypeError, KeyError, IndexError) as e:
        report.add("MXG005", "error",
                   "graph does not deserialize: %s" % e)
        return report
    return verify_symbol(sym, shapes=shapes, types=types, tp_size=tp_size,
                         check_registry=check_registry, report=report,
                         cost_model=cost_model, slow_factor=slow_factor,
                         plan=plan, plan_layout=plan_layout,
                         mesh=mesh, parallel=parallel, memory=memory)


# default verification inputs per model-zoo entry: (data kwargs)
_MODEL_SHAPES = {
    "mlp": {"data": (2, 784)},
    "lenet": {"data": (2, 1, 28, 28)},
}
_DEFAULT_IMAGE = {"data": (2, 3, 224, 224)}


def verify_model(name, batch=2, tp_size=1, num_classes=10,
                 cost_model=None, slow_factor=3.0, plan=False,
                 plan_layout="NCHW", mesh=None, parallel=None,
                 memory=None, **model_kwargs):
    """Build a model-zoo symbol and verify it with its canonical input
    shape.  Returns (symbol, Report).  ``cost_model`` additionally
    runs the MXG010 predicted-slow check (:mod:`.perf`); ``plan=True``
    switches it to committed-plan mode; ``mesh``/``parallel`` run the
    distributed-correctness pass (:mod:`.spmd`)."""
    from .. import models
    net = models.get_model(name, num_classes=num_classes, **model_kwargs)
    shapes = dict(_MODEL_SHAPES.get(name, _DEFAULT_IMAGE))
    shapes = {k: (batch,) + tuple(v[1:]) for k, v in shapes.items()}
    shapes["softmax_label"] = (batch,)
    return net, verify_symbol(net, shapes=shapes, tp_size=tp_size,
                              cost_model=cost_model,
                              slow_factor=slow_factor, plan=plan,
                              plan_layout=plan_layout,
                              mesh=mesh, parallel=parallel,
                              memory=memory)
