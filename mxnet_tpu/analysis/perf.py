"""MXG010 — predicted-slow graph nodes, named before any compile.

The static half of the learned-cost-model loop (ROADMAP item 2): the
verifier's abstract interpretation already knows every node's input and
output shapes, so each heavy node gets an analytic flops/bytes estimate
(the same formulas ``analysis.fusion`` and ``ops/pallas_kernels`` feed
the cost database), a roofline-attainable lower bound against the
costdb peak tables, and a wall-time *prediction* from a fitted
:class:`mxnet_tpu.autotune.CostModel`.  A node whose predicted wall
exceeds ``factor`` x its attainable time is reported as **MXG010**
(warning) with both numbers — so a graph that the accumulated ground
truth says will run far off the roofline is named before any device
time is spent.

Opt-in: the check runs only when a cost model is supplied —
``verify_symbol(..., cost_model=...)``, ``python -m mxnet_tpu.analysis
--cost-model model.json``, or ``tools/autotune.py``'s CI hook.  A
model fitted on a different backend's records predicts that backend's
walls; fit and check against the same peak table
(``MXNET_TPU_PEAK_FLOPS``/``MXNET_TPU_PEAK_BW`` pin it).
"""
from __future__ import annotations

__all__ = ["node_cost_estimate", "check_predicted_slow",
           "check_predicted_plan"]

#: ops the analytic estimator covers; everything else is skipped (an
#: elementwise op's wall is noise next to the convs/GEMMs MXG010 hunts)
_HEAVY_OPS = ("Convolution", "FullyConnected", "BatchNorm",
              "_contrib_FlashAttention", "_contrib_RingAttention")


def _nbytes(shape, itemsize=4):
    n = itemsize
    for d in shape:
        n *= int(d)
    return n


def node_cost_estimate(node, in_shapes, out_shapes, itemsize=4):
    """Analytic ``(flops, bytes_accessed)`` for one op node, or None
    when the op is not modeled.  Formulas mirror the trace-time costdb
    estimates (``fusion._note_block_cost``, ``pallas_kernels.
    _note_kernel_cost``) so the static prediction and the measured
    record describe the same quantity."""
    op = node.op.name
    if op not in _HEAVY_OPS or not in_shapes or not out_shapes:
        return None
    out = out_shapes[0]
    out_size = 1
    for d in out:
        out_size *= int(d)
    io_bytes = sum(_nbytes(s, itemsize) for s in in_shapes) \
        + _nbytes(out, itemsize)
    if op in ("Convolution", "FullyConnected"):
        if len(in_shapes) < 2:
            return None
        w = in_shapes[1]
        w_size = 1
        for d in w:
            w_size *= int(d)
        n_out = int(node.attrs.get("num_filter")
                    or node.attrs.get("num_hidden") or w[0])
        flops = 2.0 * out_size * w_size / max(1, n_out)
        return flops, float(io_bytes)
    if op == "BatchNorm":
        return 10.0 * out_size, float(io_bytes)
    # flash/ring attention over (B, T, H, D): 2 matmuls of
    # 2*T*T*D MACs each per (batch, head)
    q = in_shapes[0]
    if len(q) != 4:
        return None
    b, t, h, d = (int(x) for x in q)
    return 4.0 * b * h * t * t * d, float(io_bytes)


def check_predicted_slow(topo, structs, cost_model, factor=3.0,
                         report=None):
    """Run MXG010 over a verified graph: for each modeled node with
    resolved shapes, predict its wall with ``cost_model`` (a
    ``mxnet_tpu.autotune.CostModel`` or saved-model path) and flag it
    when ``predicted > factor * attainable``.  Appends to ``report``
    (or a fresh one) and returns it."""
    from ..autotune import model as _model
    from ..telemetry import costdb
    from .verifier import Report

    report = report if report is not None else Report()
    model = _model.load_model(cost_model)
    factor = float(factor)
    backend = costdb.backend_name()
    pf, pbw = costdb.peak_flops(backend), costdb.peak_bandwidth(backend)

    for node in topo:
        if node.is_variable or node.op is None:
            continue
        sts = structs.get(id(node))
        if not sts:
            continue
        in_sts = []
        missing = False
        for (src, idx) in node.inputs:
            st = structs.get(id(src))
            if st is None or len(st) <= idx:
                missing = True
                break
            in_sts.append(st[idx])
        if missing:
            continue
        itemsize = max([getattr(getattr(st, "dtype", None), "itemsize",
                                4) or 4 for st in sts] or [4])
        est = node_cost_estimate(
            node, [tuple(st.shape) for st in in_sts],
            [tuple(st.shape) for st in sts], itemsize=itemsize)
        if est is None:
            continue
        flops, bytes_ = est
        attainable = costdb._attainable_s(flops, bytes_ or None, pf,
                                          pbw)
        predicted = model.predict(flops=flops, bytes_accessed=bytes_,
                                  backend=backend)
        if not attainable or not predicted:
            continue
        if predicted > factor * attainable:
            report.add(
                "MXG010", "warning",
                "cost model predicts %.3g ms against a roofline-"
                "attainable %.3g ms (%.1fx > the %.1fx budget); this "
                "node is expected to run far off the roofline — "
                "candidate for tuning (tools/autotune.py) or fusion"
                % (predicted * 1e3, attainable * 1e3,
                   predicted / attainable, factor),
                node=node.name, op=node.op.name)
    return report


def check_predicted_plan(topo, entries, structs, cost_model, factor=3.0,
                         report=None, layout="NCHW", mesh=None):
    """MXG010 ``--plan`` mode: predictions for the **committed** plan
    rather than the default lowering.  The graph's ``graph_plan``
    tuning-cache entry (``analysis.plansearch``; greedy plan on miss)
    is built exactly as bind time would dispatch it, every costed
    unit — fused blocks with their analytic flops/bytes, unfused
    heavies, explicit boundary relayouts of overridden-layout regions
    — is predicted with ``cost_model``, and units whose predicted wall
    exceeds ``factor`` x their roofline-attainable time are reported
    with the plan identity alongside, so a slow prediction names the
    plan that owns it."""
    from ..autotune import model as _model
    from . import fusion as _fusion
    from . import plansearch as _plansearch
    from .verifier import Report

    report = report if report is not None else Report()
    model = _model.load_model(cost_model)
    factor = float(factor)
    decisions = _plansearch.committed_decisions(topo, entries, layout,
                                                mesh=mesh)
    plan = _fusion.plan_block_fusion(topo, entries, layout=layout,
                                     record=False,
                                     decisions=dict(decisions)
                                     if decisions else {})
    node_shapes = {}
    for nid, sts in structs.items():
        if sts is None:
            continue
        node_shapes[nid] = tuple(tuple(int(d) for d in st.shape)
                                 for st in sts)
    _total, units = _plansearch.predict_plan_wall(
        topo, entries, plan, node_shapes, model=model)
    source = "searched" if decisions else "greedy"
    for u in units:
        att = u["attainable_s"]
        predicted = (u["predicted_s"] or 0.0) + (u["relayout_s"] or 0.0)
        if not att or not predicted:
            continue
        if predicted > factor * att:
            report.add(
                "MXG010", "warning",
                "committed plan %s (%s): cost model predicts %.3g ms "
                "against a roofline-attainable %.3g ms (%.1fx > the "
                "%.1fx budget) for this %s — candidate for plan "
                "re-search (tools/plan_search.py) or kernel tuning"
                % (plan.plan_id, source, predicted * 1e3, att * 1e3,
                   predicted / att, factor, u["unit"]),
                node=u["name"], op=u["kind"])
    return report
