"""mxnet_tpu.analysis — static verification before any device time.

Two engines (ISSUE 2; see docs/api/analysis.md for the full catalog):

* the **graph verifier** (:mod:`.verifier`): per-node abstract
  interpretation of a Symbol DAG — shape/dtype consistency against the
  op registry's fcompute contracts, missing param-shape rules, dead
  nodes/unused inputs, duplicate names, cycles, and tensor-parallel
  sharding coverage against ``parallel.tp_rules``.  Exposed as
  ``Symbol.verify()``, ``bind(..., strict=True)`` and the
  ``python -m mxnet_tpu.analysis`` CLI.
* the **TPU-hazard source linter** (``tools/mxlint.py``, stdlib-only so
  it runs without jax installed): broad excepts, host syncs inside
  jitted code, jit recompile hazards, captured-state mutation under
  ``@jit``, missing ``donate_argnums`` on train steps, collectives
  under rank-conditioned branches (MXL006).  Re-exported here via
  :func:`load_mxlint` for tests and ``tools/ci_check.py``.
* the **distributed-correctness pass** (:mod:`.spmd`, MXG011-016):
  abstract interpretation of the composed parallel step (pipeline x
  tensor x sequence x MoE x kvstore) against a mesh descriptor —
  cross-rank collective matching, rank-divergent control flow,
  pipeline partition validity, sharding-spec composition,
  donation/aliasing audit, and forward/backward collective duality.
  Exposed as ``verify_symbol(mesh=..., parallel=...)``,
  ``ShardedTrainer(strict=True)`` / ``MXNET_TPU_STRICT_BIND=1`` and
  the CLI's ``--mesh/--pipeline/--sequence`` flags.
* the **static memory-liveness analyzer** (:mod:`.memlive`,
  MXG017-021): bind-time liveness intervals over the composed train
  step — predicted peak-HBM watermark with a per-category breakdown,
  budget gating before any compile, remat-candidate ranking,
  ZeRO-shardable optimizer-state audit, and a donation audit.
  Exposed as ``verify_symbol(memory=...)`` / ``Symbol.verify``,
  budget-armed strict binds, the CLI's ``--memory`` flag and
  ``tools/mem_top.py``.
"""
from __future__ import annotations

import os

from ..base import MXNetError
from .verifier import (Diagnostic, Report, verify_symbol, verify_json,
                       verify_model, infer_node_shapes)
from . import fusion
from . import memlive
from . import perf
from . import plansearch
from . import spmd
from .fusion import plan_block_fusion, last_plan_summary
from .memlive import LivenessAnalysis, analyze_memory, check_memory
from .perf import check_predicted_slow
from .spmd import verify_spmd, build_config

__all__ = ["Diagnostic", "Report", "verify_symbol", "verify_json",
           "verify_model", "infer_node_shapes", "load_mxlint",
           "registry_selfcheck", "fusion", "memlive", "perf",
           "plansearch", "spmd", "plan_block_fusion",
           "last_plan_summary", "check_predicted_slow", "verify_spmd",
           "build_config", "LivenessAnalysis", "analyze_memory",
           "check_memory"]


def registry_selfcheck():
    """Run the op-registry self-check; returns a list of problem strings
    (see :func:`mxnet_tpu.ops.registry.selfcheck`)."""
    from ..ops import registry as _registry
    return _registry.selfcheck()


_MXLINT_CACHE = None


def load_mxlint():
    """Import the standalone linter in ``tools/mxlint.py``.

    The linter is deliberately NOT a package submodule: it must run with
    zero third-party deps (no jax), and importing anything under
    ``mxnet_tpu`` executes the package __init__ which pulls in jax.
    Loading it by file path keeps one implementation serving the CLI
    and the tests.  (tools/ci_check.py carries its own copy of this
    loader on purpose — its lint stage must work even when the jax
    import is broken, so it cannot go through this package.)
    """
    global _MXLINT_CACHE
    if _MXLINT_CACHE is not None:
        return _MXLINT_CACHE
    import importlib.util
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    path = os.path.join(repo_root, "tools", "mxlint.py")
    if not os.path.exists(path):
        raise MXNetError("tools/mxlint.py not found at %r (linting "
                         "requires a source checkout)" % path)
    spec = importlib.util.spec_from_file_location("mxlint", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    _MXLINT_CACHE = mod
    return mod
