"""CLI for the static analysis subsystem.

Usage::

    # verify serialized symbols (the reference -symbol.json layout)
    python -m mxnet_tpu.analysis graph.json [--data 32,3,224,224] [--tp 8]

    # verify model-zoo entries with their canonical input shapes
    python -m mxnet_tpu.analysis --model resnet50 --model mlp [--tp 8]
    python -m mxnet_tpu.analysis --model all

    # MXG010 for the COMMITTED fusion/layout plan (plansearch cache
    # entry under MXNET_TPU_TUNE_CACHE; greedy on miss)
    python -m mxnet_tpu.analysis --model resnet50 --cost-model m.json \
        --plan [--layout NHWC]

    # run the TPU-hazard source linter (tools/mxlint.py rules)
    python -m mxnet_tpu.analysis --lint mxnet_tpu/ tools/ examples/

    # registry self-check only
    python -m mxnet_tpu.analysis --registry

Exit status 1 when any error-severity diagnostic (or lint finding) is
reported; warnings alone exit 0 unless ``--strict-warnings``.
"""
from __future__ import annotations

import argparse
import os
import sys


def _parse_shape(s):
    return tuple(int(x) for x in s.replace("(", "").replace(")", "")
                 .split(",") if x.strip())


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.analysis",
        description="static graph verifier + TPU-hazard linter")
    ap.add_argument("json", nargs="*",
                    help="serialized symbol JSON files to verify")
    ap.add_argument("--model", action="append", default=[],
                    help="model-zoo entry to verify ('all' for every "
                         "model); repeatable")
    ap.add_argument("--data", default=None,
                    help="data shape for JSON graphs, e.g. 32,3,224,224")
    ap.add_argument("--label", default=None,
                    help="label shape for JSON graphs (default: batch)")
    ap.add_argument("--tp", type=int, default=1,
                    help="verify tensor-parallel sharding coverage for "
                         "this model-axis size")
    ap.add_argument("--batch", type=int, default=2,
                    help="batch size for --model verification")
    ap.add_argument("--registry", action="store_true",
                    help="run the op-registry self-check")
    ap.add_argument("--lint", nargs="*", metavar="PATH", default=None,
                    help="run the mxlint source linter over PATHs "
                         "(default: mxnet_tpu/ tools/ examples/)")
    ap.add_argument("--strict-warnings", action="store_true",
                    help="exit 1 on warnings too")
    ap.add_argument("--cost-model", default=None, metavar="PATH",
                    help="fitted mxnet_tpu.autotune cost model "
                         "(mxtpu-costmodel/1 JSON); enables MXG010 "
                         "predicted-slow node detection")
    ap.add_argument("--slow-factor", type=float, default=3.0,
                    help="MXG010 threshold: flag nodes predicted "
                         "slower than this multiple of their "
                         "roofline-attainable time (default 3.0)")
    ap.add_argument("--plan", action="store_true",
                    help="MXG010 plan mode (needs --cost-model): "
                         "predict the COMMITTED fusion/layout plan — "
                         "the graph_plan tuning-cache entry under "
                         "MXNET_TPU_TUNE_CACHE, greedy on miss — "
                         "instead of the default per-node lowering")
    ap.add_argument("--layout", default="NCHW",
                    choices=("NCHW", "NHWC"),
                    help="trace layout the --plan lookup is keyed by "
                         "(default NCHW)")
    args = ap.parse_args(argv)

    if args.plan and not args.cost_model:
        ap.error("--plan needs --cost-model (the MXG010 predictor)")

    if not (args.json or args.model or args.registry
            or args.lint is not None):
        ap.error("nothing to do: give JSON files, --model, --registry "
                 "or --lint")

    from . import (Report, load_mxlint, registry_selfcheck, verify_json,
                   verify_model)

    failed = warned = False

    if args.registry:
        problems = registry_selfcheck()
        for p in problems:
            print("MXG008 [error] <registry>: %s" % p)
        print("registry selfcheck: %d problem(s)" % len(problems))
        failed = failed or bool(problems)

    models = args.model
    if "all" in models:
        from .. import models as _zoo
        models = list(_zoo._MODELS)
    for name in models:
        _net, report = verify_model(name, batch=args.batch,
                                    tp_size=args.tp,
                                    cost_model=args.cost_model,
                                    slow_factor=args.slow_factor,
                                    plan=args.plan,
                                    plan_layout=args.layout)
        print("model %-20s %s" % (name, report))
        failed = failed or not report.ok
        warned = warned or bool(report.warnings)

    for path in args.json:
        with open(path) as f:
            js = f.read()
        shapes = {}
        if args.data:
            shapes["data"] = _parse_shape(args.data)
            shapes["softmax_label"] = (_parse_shape(args.label)
                                       if args.label
                                       else (shapes["data"][0],))
        report = verify_json(js, shapes=shapes or None, tp_size=args.tp,
                             cost_model=args.cost_model,
                             slow_factor=args.slow_factor,
                             plan=args.plan, plan_layout=args.layout)
        print("%s: %s" % (path, report))
        failed = failed or not report.ok
        warned = warned or bool(report.warnings)

    if args.lint is not None:
        mxlint = load_mxlint()
        paths = args.lint
        if not paths:
            root = os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
            paths = [os.path.join(root, d)
                     for d in mxlint.DEFAULT_LINT_DIRS]
        findings = mxlint.lint_paths(paths)
        for f in findings:
            print(f)
        print("mxlint: %d finding(s)" % len(findings))
        failed = failed or bool(findings)

    return 1 if (failed or (warned and args.strict_warnings)) else 0


if __name__ == "__main__":
    sys.exit(main())
