"""CLI for the static analysis subsystem.

Usage::

    # verify serialized symbols (the reference -symbol.json layout)
    python -m mxnet_tpu.analysis graph.json [--data 32,3,224,224] [--tp 8]

    # verify model-zoo entries with their canonical input shapes
    python -m mxnet_tpu.analysis --model resnet50 --model mlp [--tp 8]
    python -m mxnet_tpu.analysis --model all

    # MXG010 for the COMMITTED fusion/layout plan (plansearch cache
    # entry under MXNET_TPU_TUNE_CACHE; greedy on miss)
    python -m mxnet_tpu.analysis --model resnet50 --cost-model m.json \
        --plan [--layout NHWC]

    # run the TPU-hazard source linter (tools/mxlint.py rules)
    python -m mxnet_tpu.analysis --lint mxnet_tpu/ tools/ examples/

    # distributed-correctness pass (MXG011-016) for a composed
    # parallel configuration
    python -m mxnet_tpu.analysis --model mlp --mesh data=2,pipe=2 \
        --pipeline 2 [--microbatches 4]
    python -m mxnet_tpu.analysis --model mlp --mesh data=2,model=4 \
        --sequence [--seq-axis model] [--kv-push]

    # static memory-liveness pass (MXG017-021): predicted peak HBM,
    # remat/ZeRO/donation advice, optional budget gate
    python -m mxnet_tpu.analysis --model resnet50 --memory \
        [--opt-slots 2] [--mem-budget BYTES] [--mem-tol 0.6] [--eval]

    # machine-readable diagnostics (schema mxtpu-analysis/1): every
    # rule family MXG001-021 + lint findings as JSON on stdout
    python -m mxnet_tpu.analysis --model mlp --memory --json

    # registry self-check only
    python -m mxnet_tpu.analysis --registry

Exit status 1 when any error-severity diagnostic (or lint finding) is
reported; warnings alone exit 0 unless ``--strict-warnings``.
"""
from __future__ import annotations

import argparse
import os
import sys


def _parse_shape(s):
    return tuple(int(x) for x in s.replace("(", "").replace(")", "")
                 .split(",") if x.strip())


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.analysis",
        description="static graph verifier + TPU-hazard linter")
    ap.add_argument("json", nargs="*",
                    help="serialized symbol JSON files to verify")
    ap.add_argument("--model", action="append", default=[],
                    help="model-zoo entry to verify ('all' for every "
                         "model); repeatable")
    ap.add_argument("--data", default=None,
                    help="data shape for JSON graphs, e.g. 32,3,224,224")
    ap.add_argument("--label", default=None,
                    help="label shape for JSON graphs (default: batch)")
    ap.add_argument("--tp", type=int, default=1,
                    help="verify tensor-parallel sharding coverage for "
                         "this model-axis size")
    ap.add_argument("--batch", type=int, default=None,
                    help="batch size for --model verification "
                         "(default 2, rounded up to dp x microbatches "
                         "under --pipeline; an EXPLICIT value is "
                         "verified as given)")
    ap.add_argument("--registry", action="store_true",
                    help="run the op-registry self-check")
    ap.add_argument("--lint", nargs="*", metavar="PATH", default=None,
                    help="run the mxlint source linter over PATHs "
                         "(default: mxnet_tpu/ tools/ examples/)")
    ap.add_argument("--strict-warnings", action="store_true",
                    help="exit 1 on warnings too")
    ap.add_argument("--cost-model", default=None, metavar="PATH",
                    help="fitted mxnet_tpu.autotune cost model "
                         "(mxtpu-costmodel/1 JSON); enables MXG010 "
                         "predicted-slow node detection")
    ap.add_argument("--slow-factor", type=float, default=3.0,
                    help="MXG010 threshold: flag nodes predicted "
                         "slower than this multiple of their "
                         "roofline-attainable time (default 3.0)")
    ap.add_argument("--plan", action="store_true",
                    help="MXG010 plan mode (needs --cost-model): "
                         "predict the COMMITTED fusion/layout plan — "
                         "the graph_plan tuning-cache entry under "
                         "MXNET_TPU_TUNE_CACHE, greedy on miss — "
                         "instead of the default per-node lowering")
    ap.add_argument("--layout", default="NCHW",
                    choices=("NCHW", "NHWC"),
                    help="trace layout the --plan lookup is keyed by "
                         "(default NCHW)")
    ap.add_argument("--mesh", default=None, metavar="AXES",
                    help="mesh descriptor 'axis=size,axis=size' (e.g. "
                         "data=2,pipe=2); enables the distributed-"
                         "correctness pass (MXG011-016)")
    ap.add_argument("--pipeline", type=int, default=1, metavar="N",
                    help="verify an N-stage pipeline partition of the "
                         "graph (needs --mesh with a pipe axis)")
    ap.add_argument("--microbatches", type=int, default=None,
                    help="pipeline microbatch count (default 2x stages)")
    ap.add_argument("--sequence", action="store_true",
                    help="verify the sequence-parallel (ring attention) "
                         "composition over --seq-axis")
    ap.add_argument("--seq-axis", default="model",
                    help="mesh axis carrying sequence shards "
                         "(default model)")
    ap.add_argument("--kv-push", action="store_true",
                    help="include the DistKVStore push collective in "
                         "the verified schedule")
    ap.add_argument("--memory", action="store_true",
                    help="run the static memory-liveness pass "
                         "(MXG017-021): predicted peak HBM with "
                         "per-category breakdown, remat/ZeRO/donation "
                         "advice, and the budget gate when one is "
                         "armed (analysis.memlive)")
    ap.add_argument("--eval", dest="mem_eval", action="store_true",
                    help="--memory models the inference schedule "
                         "instead of the default fwd+bwd+update step")
    ap.add_argument("--opt-slots", type=int, default=2, metavar="N",
                    help="--memory: float32 optimizer slots per "
                         "parameter (default 2, the Adam layout; SGD "
                         "momentum uses 1, plain SGD 0)")
    ap.add_argument("--mem-budget", type=int, default=None,
                    metavar="BYTES",
                    help="--memory: explicit MXG017 peak budget in "
                         "bytes (default: device capacity x "
                         "MXNET_TPU_MEMORY_BUDGET when known)")
    ap.add_argument("--mem-tol", type=float, default=None,
                    help="--memory: MXG018 drift tolerance override "
                         "(default MXNET_TPU_MEMLIVE_TOL)")
    ap.add_argument("--json", dest="json_out", action="store_true",
                    help="emit machine-readable diagnostics (schema "
                         "mxtpu-analysis/1) on stdout instead of text")
    args = ap.parse_args(argv)

    if args.plan and not args.cost_model:
        ap.error("--plan needs --cost-model (the MXG010 predictor)")
    if (args.pipeline > 1 or args.sequence or args.kv_push) \
            and not args.mesh:
        ap.error("--pipeline/--sequence/--kv-push need --mesh "
                 "(the distributed pass verifies against a mesh "
                 "descriptor)")

    if not (args.json or args.model or args.registry
            or args.lint is not None):
        ap.error("nothing to do: give JSON files, --model, --registry "
                 "or --lint")

    from . import (Report, build_config, load_mxlint,
                   registry_selfcheck, verify_json, verify_model)

    mesh_axes = None
    parallel_cfg = None
    if args.mesh:
        from ..parallel.reshard import parse_axes
        try:
            mesh_axes = parse_axes(args.mesh)
        except ValueError as e:
            ap.error(str(e))
        parallel_cfg = build_config(
            pipeline_stages=args.pipeline,
            pipeline_microbatches=args.microbatches,
            sequence_parallel=args.sequence, seq_axis=args.seq_axis,
            kv_push=args.kv_push, tp_size=mesh_axes.get("model", 1))

    batch = args.batch if args.batch is not None else 2
    if args.batch is None and parallel_cfg \
            and parallel_cfg["pipeline_stages"] > 1:
        # the default batch must divide dp x microbatches or every
        # --pipeline run would false-flag MXG013; an explicit --batch
        # is the user's to get wrong (that IS the divisibility check)
        denom = mesh_axes.get("data", 1) * \
            parallel_cfg["pipeline_microbatches"]
        batch = max(batch, denom)
        batch += (-batch) % denom

    failed = warned = False
    out_doc = None
    if args.json_out:
        out_doc = {"schema": "mxtpu-analysis/1", "targets": [],
                   "registry_problems": [], "lint": []}

    def mem_opts(program):
        """check_memory options for one target (None: --memory off)."""
        if not args.memory:
            return None
        return {"is_train": not args.mem_eval,
                "n_slots": 0 if args.mem_eval else args.opt_slots,
                "mesh": mesh_axes,
                "budget_bytes": args.mem_budget,
                "advice": True, "record": True,
                "program": program}

    def mem_summary(program):
        if not args.memory:
            return None
        from ..telemetry.memory import static_prediction
        return static_prediction(program)

    def fmt_peak(info):
        from ..telemetry.memory import _fmt_bytes
        bd = ", ".join("%s=%s" % (c, _fmt_bytes(v))
                       for c, v in (info.get("breakdown") or {}).items()
                       if v)
        return ("  predicted peak %s at %s (%s)"
                % (_fmt_bytes(info.get("peak_bytes", 0)),
                   info.get("peak_node", "?"), bd or "empty"))

    if args.registry:
        problems = registry_selfcheck()
        if out_doc is not None:
            out_doc["registry_problems"] = list(problems)
        else:
            for p in problems:
                print("MXG008 [error] <registry>: %s" % p)
            print("registry selfcheck: %d problem(s)" % len(problems))
        failed = failed or bool(problems)

    models = args.model
    if "all" in models:
        from .. import models as _zoo
        models = list(_zoo._MODELS)
    for name in models:
        program = "model:%s" % name
        _net, report = verify_model(name, batch=batch,
                                    tp_size=args.tp,
                                    cost_model=args.cost_model,
                                    slow_factor=args.slow_factor,
                                    plan=args.plan,
                                    plan_layout=args.layout,
                                    mesh=mesh_axes,
                                    parallel=parallel_cfg,
                                    memory=mem_opts(program))
        info = mem_summary(program)
        if out_doc is not None:
            rec = {"target": name, "kind": "model", "ok": report.ok,
                   "diagnostics": [d.as_dict() for d in report]}
            if info:
                rec["memory"] = info
            out_doc["targets"].append(rec)
        else:
            print("model %-20s %s" % (name, report))
            if info:
                print(fmt_peak(info))
        failed = failed or not report.ok
        warned = warned or bool(report.warnings)

    for path in args.json:
        with open(path) as f:
            js = f.read()
        shapes = {}
        if args.data:
            shapes["data"] = _parse_shape(args.data)
            shapes["softmax_label"] = (_parse_shape(args.label)
                                       if args.label
                                       else (shapes["data"][0],))
        program = "graph:%s" % os.path.basename(path)
        report = verify_json(js, shapes=shapes or None, tp_size=args.tp,
                             cost_model=args.cost_model,
                             slow_factor=args.slow_factor,
                             plan=args.plan, plan_layout=args.layout,
                             mesh=mesh_axes, parallel=parallel_cfg,
                             memory=mem_opts(program))
        info = mem_summary(program)
        if out_doc is not None:
            rec = {"target": path, "kind": "json", "ok": report.ok,
                   "diagnostics": [d.as_dict() for d in report]}
            if info:
                rec["memory"] = info
            out_doc["targets"].append(rec)
        else:
            print("%s: %s" % (path, report))
            if info:
                print(fmt_peak(info))
        failed = failed or not report.ok
        warned = warned or bool(report.warnings)

    if args.lint is not None:
        mxlint = load_mxlint()
        paths = args.lint
        if not paths:
            root = os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
            paths = [os.path.join(root, d)
                     for d in mxlint.DEFAULT_LINT_DIRS]
        findings = mxlint.lint_paths(paths)
        if out_doc is not None:
            out_doc["lint"] = [
                {"path": f.path, "line": f.line, "rule": f.rule,
                 "message": f.message} for f in findings]
        else:
            for f in findings:
                print(f)
            print("mxlint: %d finding(s)" % len(findings))
        failed = failed or bool(findings)

    code = 1 if (failed or (warned and args.strict_warnings)) else 0
    if out_doc is not None:
        import json as _json
        out_doc["ok"] = code == 0
        print(_json.dumps(out_doc, indent=2, sort_keys=False,
                          default=str))
    return code


if __name__ == "__main__":
    sys.exit(main())
