"""Distributed-correctness verifier: static SPMD/collective analysis.

The single-graph verifier (:mod:`.verifier`, MXG001-010) checks one
device's program; the defects that actually kill multi-rank runs live
BETWEEN ranks — a collective one rank issues and another does not, a
ppermute whose payload shape differs across the ring, a pipeline stage
plan a fused block straddles, a rule table naming a mesh axis that does
not exist.  Every one of those surfaces at runtime as a fleet-wide hang
or a silent numeric skew; none of them needs a device to be *detected*.
This pass verifies a (graph, mesh descriptor, parallel config) triple at
bind time and from the CLI, in the spirit of Relay's whole-program
checks on a typed IR (arXiv:1810.00952) and Glow's per-node lowering
verification (arXiv:1805.00907).

Rule catalog (stable IDs; docs/api/analysis.md is the reference):

========  ========  ====================================================
rule      severity  meaning
========  ========  ====================================================
MXG011    error     collective matching: the abstractly-interpreted
                    composed step (plain dp, pipeline, sequence/ring,
                    MoE, DistKVStore push — monolithic or the bucketed
                    overlap schedule of parallel/overlap.py) must issue
                    the SAME ordered collective sequence — matching
                    (op, axis, shape, dtype) — on every rank; a
                    divergence (including a rank-reordered bucket
                    launch) is the static shadow of a multiprocess hang
MXG012    error     rank-divergent control flow: a collective under
                    control flow conditioned on the rank
                    (``lax.cond`` on ``axis_index`` in a jaxpr; the
                    source-level twin is mxlint MXL006)
MXG013    error     pipeline partition validity: stage boundaries must
                    cover the topo exactly once, no fused block or
                    chain may straddle a stage, per-stage shapes must
                    be consistent with the microbatch schedule
MXG014    error     sharding-spec composition: tp_rules x reshard rule
                    tables x sequence-axis specs must be mutually
                    consistent, and every axis named must exist in the
                    mesh with divisible sizes
MXG015    error     donation/aliasing audit: a donated buffer group
                    referenced after donation across the step/pipeline
                    boundary (warning when the reader is the
                    documented post-update numerics replay)
MXG016    error     collective-in-gradient parity: the backward
                    collective sequence must be the dual of the
                    forward one (ppermute -> inverse-perm ppermute,
                    all_gather -> reduce_scatter; ring attention's bwd
                    must mirror its fwd schedule — the real lowering
                    is traced and checked, see check_ring_duality)
========  ========  ====================================================

Entry points: :func:`verify_spmd` (the engine, also reachable through
``verify_symbol(mesh=..., parallel=...)`` / ``Symbol.verify``),
``ShardedTrainer(..., strict=True)`` / ``MXNET_TPU_STRICT_BIND=1``,
``python -m mxnet_tpu.analysis --mesh ... --pipeline ... --sequence``,
and ``tools/ci_check.py`` stage 13.  Low-level checkers
(:func:`check_schedules`, :func:`check_pipeline_partition`,
:func:`check_gradient_parity`, :func:`collectives_in_jaxpr`) are public
so tests and tools can feed seeded-defect fixtures directly.
"""
from __future__ import annotations

import itertools

from ..base import MXNetError

__all__ = [
    "CollectiveEvent", "build_config", "rank_grid",
    "collective_schedule", "check_schedules", "check_rank_divergence",
    "collectives_in_jaxpr", "check_pipeline_partition",
    "check_sharding_composition", "check_donation", "dual_event",
    "check_gradient_parity", "check_ring_duality", "verify_step_fn",
    "verify_spmd", "verify_trainer_config",
]

#: jax primitives that move data across ranks (jaxpr-level scan set)
COLLECTIVE_PRIMITIVES = frozenset({
    "psum", "ppermute", "pbroadcast", "all_gather", "all_to_all",
    "pmax", "pmin", "reduce_scatter", "psum_scatter", "pgather",
})

class CollectiveEvent:
    """One abstract collective: what a rank issues, in program order."""
    __slots__ = ("op", "axis", "shape", "dtype", "node", "phase", "perm",
                 "payload")

    def __init__(self, op, axis, shape=(), dtype="float32", node=None,
                 phase="fwd", perm=None, payload=None):
        self.op = op            # psum | ppermute | allreduce | barrier...
        self.axis = axis        # mesh axis name the collective runs over
        self.shape = tuple(int(d) for d in shape)
        self.dtype = str(dtype)
        self.node = node        # graph node / site name for diagnostics
        self.phase = phase      # fwd | bwd
        self.perm = tuple(tuple(p) for p in perm) if perm else None
        # operand IDENTITY beyond shape/dtype — a bucketed kv allreduce
        # carries a keyed pytree, so two equal-sized buckets are NOT
        # interchangeable: rank A reducing bucket 0 against rank B's
        # bucket 1 corrupts both silently (shapes match, no deadlock)
        self.payload = payload

    def key(self):
        """The cross-rank matching key: two ranks deadlock-free only
        when their event streams agree on this tuple, element-wise."""
        return (self.op, self.axis, self.shape, self.dtype, self.payload)

    def __repr__(self):
        return "<%s %s/%s %s %s%s>" % (
            self.phase, self.op, self.axis, self.shape, self.dtype,
            " @" + self.node if self.node else "")


def build_config(pipeline_stages=1, pipeline_microbatches=None,
                 sequence_parallel=False, seq_axis="model",
                 batch_axis="data", tp_size=1, tp_rules=None,
                 reshard_rules=None, kv_push=False, kv_push_ranks=None,
                 kv_buckets=None, kv_bucket_order=None,
                 moe_experts=0, moe_axis="expert", data_shapes=None,
                 label_shapes=None, dtype="float32", donate=None,
                 post_step_reads=None, numerics_provenance=False):
    """Normalize a parallel config dict for :func:`verify_spmd`.

    Mirrors the ``ShardedTrainer`` constructor surface so a bind-time
    caller can hand its own arguments over verbatim; every key has a
    safe default so CLI/fixture callers specify only what they compose.
    ``kv_push_ranks``: None = every rank pushes (the DistKVStore
    contract); a subset is the classic desync defect MXG011 exists for.
    ``kv_buckets``: the BUCKETED push schedule (parallel/overlap.py,
    docs/api/overlap.md) — a list of per-bucket element counts; with it
    the kv push models as one sampled barrier followed by one allreduce
    per bucket instead of the legacy barrier-then-monolithic-allreduce.
    ``kv_bucket_order``: None = every rank launches the plan order (the
    overlap layer's cross-rank determinism invariant); a
    ``{rank_id: [bucket indices]}`` dict seeds per-rank launch orders —
    a rank-divergent order is exactly the reordering defect MXG011 must
    name (mismatched collectives deadlock or corrupt the reduce).
    """
    if kv_bucket_order is not None:
        kv_bucket_order = {int(r): [int(i) for i in order]
                           for r, order in dict(kv_bucket_order).items()}
    return {
        "pipeline_stages": int(pipeline_stages),
        "pipeline_microbatches": (int(pipeline_microbatches)
                                  if pipeline_microbatches
                                  else 2 * int(pipeline_stages)
                                  if int(pipeline_stages) > 1 else 1),
        "sequence_parallel": bool(sequence_parallel),
        "seq_axis": seq_axis,
        "batch_axis": batch_axis,
        "tp_size": int(tp_size),
        "tp_rules": dict(tp_rules or {}),
        "reshard_rules": reshard_rules,
        "kv_push": bool(kv_push),
        "kv_push_ranks": (None if kv_push_ranks is None
                          else sorted(int(r) for r in kv_push_ranks)),
        "kv_buckets": (None if kv_buckets is None
                       else [int(n) for n in kv_buckets]),
        "kv_bucket_order": kv_bucket_order,
        "moe_experts": int(moe_experts),
        "moe_axis": moe_axis,
        "data_shapes": dict(data_shapes or {}),
        "label_shapes": dict(label_shapes or {}),
        "dtype": str(dtype),
        "donate": list(donate if donate is not None
                       else ("params", "opt_state", "aux")),
        "post_step_reads": list(post_step_reads or []),
        "numerics_provenance": bool(numerics_provenance),
        "fuse_blocks": False,
    }


def rank_grid(mesh_axes):
    """All rank coordinates of a mesh descriptor: list of
    ``{axis: index}`` dicts, one per device, row-major in axis order."""
    axes = {str(k): int(v) for k, v in (mesh_axes or {}).items()}
    names = list(axes)
    out = []
    for coords in itertools.product(*(range(axes[n]) for n in names)):
        out.append(dict(zip(names, coords)))
    return out or [{}]


def _rank_id(coord, mesh_axes):
    rid = 0
    for name, size in mesh_axes.items():
        rid = rid * int(size) + int(coord.get(name, 0))
    return rid


# ------------------------------------------------- schedule construction

def _ring_events(node_name, axis, n, t_total, q_shape, dtype, coord):
    """Fwd events of one ring-attention op on one rank.

    Payload shapes are PER-RANK: a sequence dim the ring size does not
    divide leaves neighbor ranks holding different K/V block shapes —
    the ppermute then mismatches between sender and receiver, which is
    exactly the deadlock shape MXG011 flags (jax would also refuse the
    sharding, but only after a compile on every rank)."""
    idx = int(coord.get(axis, 0))
    base, rem = divmod(int(t_total), n)
    t_local = base + (1 if idx < rem else 0)
    blk = (q_shape[0], t_local) + tuple(q_shape[2:])
    perm = tuple((i, (i + 1) % n) for i in range(n))
    fwd = []
    for _step in range(n):
        for _kv in ("k", "v"):
            fwd.append(CollectiveEvent("ppermute", axis, blk, dtype,
                                       node=node_name, perm=perm))
    return fwd


def _pipeline_events(n_pp, m_micro, bu, buf_w, dtype,
                     batch_axis="data", pipe_axis="pipe"):
    """Fwd events of the GPipe hetero schedule on one rank: (M + N - 1)
    ticks each ppermute one (B_u, W) boundary buffer, then the loss
    psums over pipe and the batch axis."""
    fwd = []
    ticks = m_micro + n_pp - 1
    perm = tuple((i, (i + 1) % n_pp) for i in range(n_pp))
    buf = (bu, buf_w)
    for t in range(ticks):
        fwd.append(CollectiveEvent("ppermute", pipe_axis, buf, dtype,
                                   node="pipeline.tick%d" % t, perm=perm))
    fwd.append(CollectiveEvent("psum", pipe_axis, (1,), "float32",
                               node="pipeline.loss"))
    fwd.append(CollectiveEvent("psum", batch_axis, (1,), "float32",
                               node="pipeline.loss"))
    return fwd


def collective_schedule(sym, mesh_axes, config, shapes=None):
    """Abstractly interpret the composed step per rank.

    Returns ``{rank_id: {"fwd": [events], "bwd": [events]}}`` — the
    ordered collective sequence each rank of ``mesh_axes`` issues for
    one training step of ``sym`` under ``config``.  ``sym`` may be None
    for config-only schedules (kvstore/MoE fixtures).  Shapes feed the
    per-rank payload computation; without them structural events carry
    empty shapes (still order/axis/dtype-checked).
    """
    axes = {str(k): int(v) for k, v in (mesh_axes or {}).items()}
    cfg = dict(config or {})
    dtype = cfg.get("dtype", "float32")
    dp = axes.get(cfg.get("batch_axis", "data"), 1)
    n_pp = int(cfg.get("pipeline_stages", 1))
    m_micro = int(cfg.get("pipeline_microbatches", 1))

    ring_nodes = []
    topo = []
    if sym is not None:
        node_shapes = dict(shapes or {})
        topo = [n for n in sym._topo() if not n.is_variable]
        for n in topo:
            if n.op is not None and n.op.name == "_contrib_RingAttention":
                q_shape = None
                src, idx = n.inputs[0]
                q_shape = node_shapes.get((id(src), idx))
                ring_nodes.append((n, q_shape))

    schedules = {}
    for coord in rank_grid(axes):
        rid = _rank_id(coord, axes)
        fwd = []

        # sequence/ring attention (one ring per RingAttention node)
        if cfg.get("sequence_parallel") and ring_nodes:
            axis = cfg.get("seq_axis", "model")
            n_ring = axes.get(axis, 1)
            if n_ring > 1:
                for node, q_shape in ring_nodes:
                    if q_shape is None:
                        q_shape = (0, 0, 0, 0)
                    fwd.extend(_ring_events(
                        node.name, axis, n_ring,
                        q_shape[1] if len(q_shape) > 1 else 0,
                        q_shape, dtype, coord))

        # pipeline schedule
        if n_pp > 1:
            dname = next(iter(cfg.get("data_shapes") or {}), None)
            gbatch = (cfg["data_shapes"][dname][0]
                      if dname else m_micro * dp)
            # per-rank microbatch rows: a global batch dp*M does not
            # divide leaves ranks disagreeing on the buffer shape
            denom = dp * m_micro
            base, rem = divmod(int(gbatch), denom)
            slot = int(coord.get(cfg.get("batch_axis", "data"), 0))
            bu = base + (1 if slot < rem else 0)
            buf_w = cfg.get("pipeline_buffer_width", 0)
            fwd.extend(_pipeline_events(
                n_pp, m_micro, bu, buf_w, dtype,
                batch_axis=cfg.get("batch_axis", "data"),
                pipe_axis="pipe"))

        # MoE all-to-alls (dispatch + combine) over the expert axis
        if cfg.get("moe_experts", 0) > 1 and \
                axes.get(cfg.get("moe_axis", "expert"), 1) > 1:
            for site in ("moe.dispatch", "moe.combine"):
                fwd.append(CollectiveEvent("all_to_all",
                                           cfg.get("moe_axis", "expert"),
                                           (), dtype, node=site))

        # the backward phase is the reversed dual of the WHOLE forward
        # sequence (jax's transpose replays the program in reverse), so
        # it is derived once — per-construct concatenation would get
        # the cross-construct ordering wrong with >1 ring in the graph
        bwd = [dual_event(ev) for ev in reversed(fwd)]

        if n_pp <= 1 and dp > 1:
            # plain dp: the gradient psum over the batch axis (one
            # logical event — XLA fuses the per-param psums, and the
            # matching property is per-axis, not per-buffer)
            bwd.append(CollectiveEvent("psum",
                                       cfg.get("batch_axis", "data"),
                                       (), "float32", node="grads",
                                       phase="bwd"))

        # DistKVStore push: every rank or a configured subset (the
        # subset IS the defect).  Legacy path: barrier + one monolithic
        # allreduce.  Bucketed path (kv_buckets, parallel/overlap.py):
        # one sampled barrier at the first bucket boundary, then one
        # allreduce per bucket in this rank's launch order — the
        # overlap invariant says that order is the shared plan order on
        # every rank; a seeded kv_bucket_order divergence models the
        # reordering defect, and the differing payload shapes make
        # check_schedules name the first mismatched bucket
        if cfg.get("kv_push"):
            push_ranks = cfg.get("kv_push_ranks")
            if push_ranks is None or rid in push_ranks:
                buckets = cfg.get("kv_buckets")
                if buckets:
                    order = list(range(len(buckets)))
                    per_rank = cfg.get("kv_bucket_order") or {}
                    order = per_rank.get(rid, order)
                    bwd.append(CollectiveEvent(
                        "barrier", "world", (), "float32",
                        node="kv.bucket_skew", phase="bwd"))
                    for bi in order:
                        bwd.append(CollectiveEvent(
                            "allreduce", "world",
                            (int(buckets[bi]),), "float32",
                            node="kv.bucket%d" % bi, phase="bwd",
                            payload="bucket%d" % bi))
                else:
                    bwd.append(CollectiveEvent("barrier", "world", (),
                                               "float32", node="kv.push",
                                               phase="bwd"))
                    bwd.append(CollectiveEvent("allreduce", "world", (),
                                               "float32", node="kv.push",
                                               phase="bwd"))

        schedules[rid] = {"fwd": fwd, "bwd": bwd, "coord": coord}
    return schedules


# ----------------------------------------------------------- the checks

def check_schedules(schedules, mesh_axes, report):
    """MXG011: every rank must issue the same ordered (op, axis, shape,
    dtype) sequence, and every referenced axis must exist in the mesh."""
    axes = {str(k) for k in (mesh_axes or {})} | {"world"}
    ranks = sorted(schedules)
    if not ranks:
        return
    for phase in ("fwd", "bwd"):
        for rid in ranks:
            for ev in schedules[rid][phase]:
                if ev.axis not in axes:
                    report.add(
                        "MXG011", "error",
                        "rank %d issues %s over mesh axis %r which the "
                        "mesh does not have (axes: %s)"
                        % (rid, ev.op, ev.axis,
                           sorted(a for a in axes if a != "world")),
                        node=ev.node)
                    return
        ref_rid = ranks[0]
        ref = [ev.key() for ev in schedules[ref_rid][phase]]
        for rid in ranks[1:]:
            seq = [ev.key() for ev in schedules[rid][phase]]
            if seq == ref:
                continue
            # name the first divergence precisely
            i = 0
            while i < min(len(ref), len(seq)) and ref[i] == seq[i]:
                i += 1
            if i >= len(seq):
                ev = schedules[ref_rid][phase][i]
                report.add(
                    "MXG011", "error",
                    "%s collective #%d %s(axis=%r, shape=%s, dtype=%s) "
                    "is issued by rank %d but NOT by rank %d — the "
                    "issuing ranks block forever (deadlock)"
                    % (phase, i, ev.op, ev.axis, ev.shape, ev.dtype,
                       ref_rid, rid),
                    node=ev.node)
            elif i >= len(ref):
                ev = schedules[rid][phase][i]
                report.add(
                    "MXG011", "error",
                    "%s collective #%d %s(axis=%r, shape=%s, dtype=%s) "
                    "is issued by rank %d but NOT by rank %d — the "
                    "issuing ranks block forever (deadlock)"
                    % (phase, i, ev.op, ev.axis, ev.shape, ev.dtype,
                       rid, ref_rid),
                    node=ev.node)
            else:
                a = schedules[ref_rid][phase][i]
                b = schedules[rid][phase][i]
                # equal-shape events can still mismatch on operand
                # identity (equal-sized kv buckets in divergent launch
                # order) — name the payloads so the diagnostic is not
                # an identical-vs-identical read
                pay = ""
                if a.payload is not None or b.payload is not None:
                    pay = (" with payload %r vs %r (same-shaped operands"
                           " are NOT interchangeable — the reduce mixes"
                           " different gradient buckets silently)"
                           % (a.payload, b.payload))
                report.add(
                    "MXG011", "error",
                    "%s collective #%d diverges across ranks: rank %d "
                    "issues %s(axis=%r, shape=%s, dtype=%s) while rank "
                    "%d issues %s(axis=%r, shape=%s, dtype=%s)%s — "
                    "mismatched collectives desync or corrupt the ring"
                    % (phase, i,
                       ref_rid, a.op, a.axis, a.shape, a.dtype,
                       rid, b.op, b.axis, b.shape, b.dtype, pay),
                    node=a.node or b.node)
            return   # first divergence only; the rest is noise


def collectives_in_jaxpr(jaxpr):
    """Flatten every collective primitive equation in a (closed) jaxpr,
    recursing into call/scan/cond/shard_map/custom-vjp sub-jaxprs.
    Returns a list of ``(prim_name, params)`` in trace order."""
    out = []
    core = getattr(jaxpr, "jaxpr", jaxpr)

    def walk(jx):
        for eqn in jx.eqns:
            name = eqn.primitive.name
            if name in COLLECTIVE_PRIMITIVES:
                out.append((name, dict(eqn.params)))
            for sub in _sub_jaxprs(eqn):
                walk(sub)
    walk(core)
    return out


def _sub_jaxprs(eqn):
    subs = []
    for v in eqn.params.values():
        vs = v if isinstance(v, (list, tuple)) else (v,)
        for item in vs:
            core_j = getattr(item, "jaxpr", None)
            if core_j is not None and hasattr(core_j, "eqns"):
                subs.append(core_j)
            elif hasattr(item, "eqns"):
                subs.append(item)
    return subs


def check_rank_divergence(jaxpr, report, where="<step>"):
    """MXG012 (jaxpr level): a ``cond``/``switch`` whose predicate is
    data-dependent on ``axis_index`` and whose branches contain a
    collective.  Rank-divergent control flow around a collective is the
    SPMD divergence class: the branch only SOME ranks take blocks on
    peers that never enter it."""
    core = getattr(jaxpr, "jaxpr", jaxpr)
    tainted = set()

    def branch_collectives(eqn):
        found = []
        for sub in _sub_jaxprs(eqn):
            for eqn2 in sub.eqns:
                if eqn2.primitive.name in COLLECTIVE_PRIMITIVES:
                    found.append(eqn2.primitive.name)
                for s2 in _sub_jaxprs(eqn2):
                    stack = [s2]
                    while stack:
                        j = stack.pop()
                        for e3 in j.eqns:
                            if e3.primitive.name in COLLECTIVE_PRIMITIVES:
                                found.append(e3.primitive.name)
                            stack.extend(_sub_jaxprs(e3))
        return found

    def walk(jx, taint):
        for eqn in jx.eqns:
            name = eqn.primitive.name
            in_tainted = any(getattr(v, "count", None) is not None
                             and id(v) in taint for v in eqn.invars)
            if name == "axis_index":
                for v in eqn.outvars:
                    taint.add(id(v))
                continue
            if name in ("cond", "switch"):
                pred = eqn.invars[0]
                if id(pred) in taint:
                    colls = branch_collectives(eqn)
                    if colls:
                        report.add(
                            "MXG012", "error",
                            "%s: collective(s) %s inside a branch "
                            "conditioned on axis_index — only some "
                            "ranks enter the branch, the rest never "
                            "reach the collective (SPMD divergence)"
                            % (where, sorted(set(colls))),
                            node=where)
                        return True
            if in_tainted:
                for v in eqn.outvars:
                    taint.add(id(v))
            for sub in _sub_jaxprs(eqn):
                # map taint across the call boundary: sub-jaxpr invars
                # bind the TAIL of the eqn's operands (scan/pjit/
                # shard_map bind 1:1; cond drops the leading predicate)
                # — without this, a rank-conditioned collective inside
                # any scan/jit/remat body is invisible
                n_in = len(sub.invars)
                if n_in and len(eqn.invars) >= n_in:
                    for outer, inner in zip(eqn.invars[-n_in:],
                                            sub.invars):
                        if getattr(outer, "count", None) is not None \
                                and id(outer) in taint:
                            taint.add(id(inner))
                if walk(sub, taint):
                    return True
        return False

    walk(core, tainted)


def check_pipeline_partition(sym, mesh_axes, config, report,
                             stages=None, shapes=None):
    """MXG013: stage plan validity for ``config['pipeline_stages']``.

    With ``stages`` (a ``plan_pipeline_stages``-shaped list) the given
    plan is audited; otherwise the trainer's planner runs — with the
    trainer's own boundary legality rule — and its refusals become
    diagnostics.  Checks: (a) the plan covers the topo exactly once, in
    contiguous topo order; (b) no fused chain from ``analysis.fusion``
    straddles a stage boundary when the config requests block fusion
    (stage bodies never fuse — the PR 6 seeded-partial contract — so a
    fused-pipeline config is checked as the contradiction it is); (c)
    the global batch is divisible by dp x microbatches and every
    explicit stage boundary's leading dim is the batch row dim."""
    from ..parallel.pipeline import plan_pipeline_stages

    n_pp = int(config.get("pipeline_stages", 1))
    if n_pp <= 1:
        return
    axes = {str(k): int(v) for k, v in (mesh_axes or {}).items()}
    if axes.get("pipe", 1) != n_pp:
        report.add("MXG013", "error",
                   "pipeline_stages=%d but the mesh 'pipe' axis has "
                   "size %d (axes: %s); one stage per pipe index is "
                   "the schedule's contract"
                   % (n_pp, axes.get("pipe", 1), dict(axes)))
        return
    topo = sym._topo()
    op_nodes = [n for n in topo if not n.is_variable]
    batch_names = set(config.get("data_shapes") or {}) | \
        set(config.get("label_shapes") or {})
    dshapes = config.get("data_shapes") or {}
    dname = next(iter(dshapes), None)
    gbatch = int(dshapes[dname][0]) if dname else None

    explicit_stages = stages is not None
    if stages is None:
        legal_cut = None
        if shapes and gbatch is not None:
            def legal_cut(bound):
                # the ring buffer is (rows, W): a boundary whose
                # leading dim is not the batch row dim (e.g. after a
                # batch-folding Reshape) cannot ride it — the same
                # rule the trainer's planner applies
                shp = shapes.get((id(bound[0]), bound[1]))
                return shp is not None and len(shp) >= 1 \
                    and int(shp[0]) == gbatch
        try:
            stages = plan_pipeline_stages(topo, sym._entries,
                                          batch_names, n_pp,
                                          legal_cut=legal_cut)
        except MXNetError as e:
            report.add("MXG013", "error",
                       "pipeline partition infeasible: %s" % e)
            return

    # (a) exact cover, contiguous and in topo order
    pos = {id(n): i for i, n in enumerate(op_nodes)}
    seen = {}
    cursor = 0
    for si, st in enumerate(stages):
        for n in st["nodes"]:
            if id(n) not in pos:
                report.add("MXG013", "error",
                           "stage %d contains node %r which is not an "
                           "op node of this graph" % (si, n.name),
                           node=n.name)
                return
            if id(n) in seen:
                report.add("MXG013", "error",
                           "node %r is assigned to BOTH stage %d and "
                           "stage %d; the schedule would run it twice "
                           "with divergent parameters"
                           % (n.name, seen[id(n)], si), node=n.name)
                return
            seen[id(n)] = si
            if pos[id(n)] != cursor:
                report.add("MXG013", "error",
                           "stage %d breaks topo contiguity at node %r "
                           "(topo position %d, expected %d); stage "
                           "boundaries must cut the topo order, not "
                           "interleave it"
                           % (si, n.name, pos[id(n)], cursor),
                           node=n.name)
                return
            cursor += 1
    if cursor != len(op_nodes):
        missing = [n.name for n in op_nodes if id(n) not in seen]
        report.add("MXG013", "error",
                   "pipeline plan covers %d of %d op nodes; missing: "
                   "%s — uncovered nodes silently drop out of the step"
                   % (cursor, len(op_nodes), missing[:5]),
                   node=missing[0] if missing else None)
        return

    # (b) no fused chain straddles a stage boundary.  Stage bodies
    # NEVER fuse (seeded partial topos, the PR 6 contract), so the
    # check binds exactly when the config claims otherwise or an
    # explicit plan is being audited for a fused executor.
    if config.get("fuse_blocks"):
        try:
            from .fusion import plan_block_fusion
            plan = plan_block_fusion(topo, sym._entries, record=False)
            blocks = list(getattr(plan, "blocks", {}).values())
        except Exception:  # mxlint: allow-broad-except(fusion planning is advisory here; a planner error must not mask the partition audit)
            blocks = []
        for blk in blocks:
            members, mseen = [], set()
            for n in (blk.conv, blk.bn, blk.fc, blk.terminal):
                if n is not None and id(n) not in mseen:
                    mseen.add(id(n))
                    members.append(n)
            stages_hit = sorted({seen[id(n)] for n in members
                                 if id(n) in seen})
            if len(stages_hit) > 1:
                report.add(
                    "MXG013", "error",
                    "fused block [%s] straddles pipeline stages %s; a "
                    "fused region cannot ride the (B_u, W) boundary "
                    "buffer — split the chain, move the cut, or run "
                    "the pipeline unfused (stage bodies never fuse)"
                    % (" -> ".join(n.name for n in members),
                       stages_hit),
                    node=members[0].name)

    # (c) microbatch schedule consistency
    dp = axes.get(config.get("batch_axis", "data"), 1)
    m = int(config.get("pipeline_microbatches", 2 * n_pp))
    for name, shp in dshapes.items():
        g = int(shp[0])
        if g % (dp * m):
            report.add(
                "MXG013", "error",
                "global batch %d of input %r is not divisible by "
                "dp=%d x microbatches=%d; ranks would disagree on the "
                "ring buffer's row count" % (g, name, dp, m),
                node=name)
    if shapes and explicit_stages and gbatch is not None:
        for si, st in enumerate(stages[1:], 1):
            b = st.get("boundary_in")
            if b is None:
                continue
            bshape = shapes.get((id(b[0]), b[1]))
            if bshape is not None and (len(bshape) < 1
                                       or int(bshape[0]) != gbatch):
                report.add(
                    "MXG013", "error",
                    "stage %d boundary %r has shape %s; its leading "
                    "dim must be the batch row dim (%d) to ride the "
                    "pipeline's (rows, W) buffer — a batch-folding "
                    "reshape upstream of the cut breaks the schedule"
                    % (si, b[0].name, tuple(bshape), gbatch),
                    node=b[0].name)


def check_sharding_composition(sym, mesh_axes, config, report,
                               arg_shapes=None):
    """MXG014: tp_rules x reshard rule tables x sequence-axis specs.

    Every axis named must exist in the mesh with sizes that divide the
    dims they shard (``reshard.plan_reshard`` validation at VERIFY time
    instead of load time), and the composed assignments must not
    conflict — a param tensor-sharded over the axis that carries
    sequence shards, or a pipeline mesh with a model axis, is a layout
    the runtime would refuse (or worse, silently misshard)."""
    from ..parallel import reshard as _reshard

    axes = {str(k): int(v) for k, v in (mesh_axes or {}).items()}
    cfg = dict(config or {})
    arg_shapes = dict(arg_shapes or {})
    tp_rules = dict(cfg.get("tp_rules") or {})
    tp_size = int(cfg.get("tp_size") or axes.get("model", 1))

    if tp_size > 1 and axes.get("model", 1) != tp_size:
        report.add("MXG014", "error",
                   "config claims tp_size=%d but the mesh 'model' axis "
                   "has size %d (axes: %s); the sharding layout and "
                   "the device grid disagree"
                   % (tp_size, axes.get("model", 1), dict(axes)))
    if tp_rules and axes.get("model", 1) <= 1:
        report.add("MXG014", "error",
                   "tp_rules shard %d param(s) over the 'model' axis "
                   "but the mesh has no model axis of size > 1 "
                   "(axes: %s)" % (len(tp_rules), dict(axes)),
                   node=sorted(tp_rules)[0])
    for name in sorted(tp_rules):
        ax = tp_rules[name]
        shp = arg_shapes.get(name)
        if shp is None:
            continue
        if not isinstance(ax, int) or ax < 0 or ax >= len(shp):
            report.add("MXG014", "error",
                       "tp_rules[%r] = %r is not a valid dim of shape "
                       "%s" % (name, ax, tuple(shp)), node=name)
            continue
        size = axes.get("model", 1)
        if size > 1 and int(shp[ax]) % size:
            report.add("MXG014", "error",
                       "tp_rules shard dim %d of %r (shape %s) over "
                       "the model axis of size %d, which does not "
                       "divide it" % (ax, name, tuple(shp), size),
                       node=name)

    # reshard rule table (verify-time plan_reshard)
    rules_spec = cfg.get("reshard_rules")
    rules = []
    if rules_spec:
        try:
            rules = (_reshard.parse_rules(rules_spec)
                     if isinstance(rules_spec, str) else list(rules_spec))
        except MXNetError as e:
            report.add("MXG014", "error",
                       "reshard rule table does not parse: %s" % e)
            rules = []
    if rules and arg_shapes:
        specs = {}
        for name in sorted(arg_shapes):
            spec = _reshard.first_match(rules, name)
            if spec is not None:
                specs[name] = list(spec)
        if specs:
            desc = {"axes": axes, "specs": specs}
            try:
                _reshard.plan_reshard(None, desc,
                                      {n: arg_shapes[n] for n in specs})
            except MXNetError as e:
                report.add("MXG014", "error",
                           "reshard rule table is inconsistent with "
                           "this mesh: %s" % e)

    # sequence-axis composition
    if cfg.get("sequence_parallel"):
        sp_axis = cfg.get("seq_axis", "model")
        sp = axes.get(sp_axis, 1)
        if sp <= 1:
            report.add("MXG014", "error",
                       "sequence_parallel needs mesh axis %r of size "
                       "> 1 to carry the sequence shards (axes: %s)"
                       % (sp_axis, dict(axes)))
        else:
            for name, shp in (cfg.get("data_shapes") or {}).items():
                if len(shp) >= 2 and int(shp[1]) % sp:
                    report.add(
                        "MXG014", "error",
                        "sequence dim %d of input %r is not divisible "
                        "by the %d sequence shards of axis %r"
                        % (int(shp[1]), name, sp, sp_axis), node=name)
            # tp_rules always shard over 'model': the layouts only
            # conflict when the sequence shards ride that same axis
            # (seq_axis='data' + tensor parallelism on 'model' is a
            # legitimate composition)
            if sp_axis == "model":
                for name in sorted(tp_rules):
                    report.add(
                        "MXG014", "error",
                        "param %r is tensor-sharded over %r while "
                        "sequence_parallel uses the same axis for "
                        "sequence shards; the two layouts conflict "
                        "(weights must replicate over the sequence "
                        "axis)" % (name, sp_axis), node=name)
                    break

    # pipeline x tensor-parallel composition
    if int(cfg.get("pipeline_stages", 1)) > 1 and axes.get("model", 1) > 1:
        report.add("MXG014", "error",
                   "pipeline_stages=%d with a model axis of size %d: "
                   "packed stage params cannot also be tensor-sharded "
                   "(the runtime refuses this bind)"
                   % (int(cfg["pipeline_stages"]), axes["model"]))


def check_donation(config, report):
    """MXG015: donated buffer groups referenced after donation.

    The fused step donates params/opt_state/aux (in-place HBM update);
    anything that READS one of those groups after dispatch observes
    either freed or post-update memory.  ``post_step_reads`` declares
    the after-step readers (kvstore re-push, monitor callbacks holding
    batch refs, ...); the numerics provenance replay is the documented
    special case — it replays with post-update params by design, so it
    reports as a warning carrying that caveat rather than an error."""
    donate = set(config.get("donate") or ())
    reads = list(config.get("post_step_reads") or ())
    for group in reads:
        if group in donate:
            report.add(
                "MXG015", "error",
                "buffer group %r is donated to the step "
                "(donate_argnums) but read again after dispatch; "
                "donation hands the buffer to XLA — the read observes "
                "freed or overwritten memory" % group, node=group)
    if config.get("numerics_provenance") and "batch" not in donate:
        pass      # batch not donated: the replay is exact
    elif config.get("numerics_provenance"):
        report.add(
            "MXG015", "warning",
            "numerics provenance replay re-executes the forward after "
            "the step donated its inputs; the replay uses post-update "
            "params (batch-borne NaNs replay exactly — documented "
            "telemetry.numerics caveat)", node="numerics.provenance")


def dual_event(ev):
    """The transpose of one collective, as autodiff must issue it."""
    if ev.op == "ppermute":
        inv = _inverse_perm(ev.perm or ())
        return CollectiveEvent("ppermute", ev.axis, ev.shape, ev.dtype,
                               node=ev.node, phase="bwd", perm=inv)
    if ev.op == "all_gather":
        return CollectiveEvent("reduce_scatter", ev.axis, ev.shape,
                               ev.dtype, node=ev.node, phase="bwd")
    if ev.op == "reduce_scatter":
        return CollectiveEvent("all_gather", ev.axis, ev.shape,
                               ev.dtype, node=ev.node, phase="bwd")
    # psum transposes to a broadcast (no cross-rank transfer needed on
    # a replicated cotangent); all_to_all is self-dual
    return CollectiveEvent(ev.op, ev.axis, ev.shape, ev.dtype,
                           node=ev.node, phase="bwd", perm=ev.perm)


def check_gradient_parity(fwd_events, bwd_events, report,
                          where="<step>"):
    """MXG016: the backward sequence must be the reversed dual of the
    forward one.  psum/barrier/allreduce events are excluded from the
    positional match (a psum's transpose is collective-free; reduction
    collectives may legitimately batch differently) — the structural
    duals (ppermute rings, gather/scatter pairs) must mirror exactly."""
    structural = ("ppermute", "all_gather", "reduce_scatter",
                  "all_to_all")
    fwd = [e for e in fwd_events if e.op in structural]
    bwd = [e for e in bwd_events if e.op in structural]
    want = [dual_event(e) for e in reversed(fwd)]
    if len(bwd) != len(want):
        report.add(
            "MXG016", "error",
            "%s: forward issues %d structural collective(s) but the "
            "backward issues %d; the gradient schedule must mirror "
            "the forward ring (fwd: %s / bwd: %s)"
            % (where, len(want), len(bwd),
               [e.op for e in want], [e.op for e in bwd]),
            node=(fwd[0].node if fwd else None) or where)
        return
    for i, (w, b) in enumerate(zip(want, bwd)):
        if (w.op, w.axis) != (b.op, b.axis) or \
                (w.shape and b.shape and w.shape != b.shape):
            report.add(
                "MXG016", "error",
                "%s: backward collective #%d is %s(axis=%r, shape=%s) "
                "but the dual of the forward schedule requires "
                "%s(axis=%r, shape=%s) at this position"
                % (where, i, b.op, b.axis, b.shape,
                   w.op, w.axis, w.shape),
                node=b.node or w.node)
            return
        if w.op == "ppermute" and w.perm and b.perm and \
                tuple(sorted(w.perm)) != tuple(sorted(b.perm)):
            report.add(
                "MXG016", "error",
                "%s: backward ppermute #%d rides permutation %s but "
                "the transpose of the forward ring is %s — the "
                "gradient blocks would rotate the wrong way"
                % (where, i, list(b.perm), list(w.perm)),
                node=b.node or w.node)
            return


def _inverse_perm(perm):
    return tuple(sorted((d, s) for (s, d) in perm))


def check_ring_duality(sym, mesh_axes, config, report, shapes=None):
    """MXG016/MXG012 over the REAL ring-attention lowering.

    For every ``_contrib_RingAttention`` node with an inferred q shape,
    trace ``parallel.sequence.ring_attention``'s forward and gradient
    jaxprs at those shapes on a probe mesh (the ring size when enough
    local devices exist, else 1) and require every forward ppermute's
    inverse permutation in the gradient; the gradient jaxpr is also
    scanned for rank-divergent control flow (MXG012).  A probe ring
    below 3 shards cannot discriminate direction — a 1- or 2-cycle is
    its own inverse and the residual-recompute trace carries the
    forward perms — so CI environments force >= 4 virtual devices to
    keep this check's teeth.  This is the non-vacuous half of MXG016:
    :func:`check_gradient_parity` audits caller-provided schedules,
    this audits what the code actually lowers."""
    if sym is None or not config.get("sequence_parallel"):
        return
    nodes = []
    for n in sym._topo():
        if not n.is_variable and n.op is not None \
                and n.op.name == "_contrib_RingAttention":
            src, idx = n.inputs[0]
            q_shape = (shapes or {}).get((id(src), idx))
            if q_shape is not None and len(q_shape) == 4:
                nodes.append((n, tuple(int(d) for d in q_shape)))
    if not nodes:
        return
    try:
        import jax
        import jax.numpy as jnp
        import numpy as _np
        from jax.sharding import Mesh
        from ..parallel.sequence import ring_attention
    except Exception:  # mxlint: allow-broad-except(no jax backend available; the schedule-level checks already ran and the fixture-level checker stays covered by tests)
        return
    axis = config.get("seq_axis", "model")
    ring = int((mesh_axes or {}).get(axis, 1))
    for node, q_shape in nodes:
        n_probe = ring if (ring > 1
                           and len(jax.devices()) >= ring
                           and q_shape[1] % ring == 0) else 1
        mesh = Mesh(_np.array(jax.devices()[:n_probe]), (axis,))
        causal = str(node.attrs.get("causal", "False")) in \
            ("True", "true", "1")
        qs = jax.ShapeDtypeStruct(q_shape, jnp.float32)

        def loss(q, k, v, _mesh=mesh, _causal=causal):
            out = ring_attention(q, k, v, _mesh, seq_axis=axis,
                                 causal=_causal)
            return jnp.sum(out.astype(jnp.float32) ** 2)

        try:
            fwd = collectives_in_jaxpr(jax.make_jaxpr(loss)(qs, qs, qs))
            grad_jaxpr = jax.make_jaxpr(
                jax.grad(loss, argnums=(0, 1, 2)))(qs, qs, qs)
        except Exception:  # mxlint: allow-broad-except(a probe-trace failure on this backend must degrade to the schedule-level checks, not crash verification)
            continue
        grad = collectives_in_jaxpr(grad_jaxpr)
        # normalize to sorted pair tuples: a permutation is a SET of
        # (src, dst) pairs, and trace order differs between fwd/bwd
        norm = lambda p: tuple(sorted(map(tuple, p)))
        fwd_perms = [norm(prm["perm"])
                     for name, prm in fwd if name == "ppermute"]
        grad_perms = {norm(prm["perm"])
                      for name, prm in grad if name == "ppermute"}
        for perm in fwd_perms:
            if _inverse_perm(perm) not in grad_perms:
                report.add(
                    "MXG016", "error",
                    "ring attention node %r: the gradient trace is "
                    "missing the inverse of forward ppermute %s — the "
                    "backward schedule does not mirror the forward "
                    "ring (grad perms: %s)"
                    % (node.name, list(perm),
                       sorted(map(list, grad_perms))),
                    node=node.name)
                break
        check_rank_divergence(grad_jaxpr, report, where=node.name)


def verify_step_fn(step_fn, example_args, report=None,
                   where="trainer.step"):
    """MXG012 over a REAL step function: trace it (``jax.make_jaxpr``
    — no compile) and scan the jaxpr for collectives under
    rank-conditioned control flow.  ``example_args`` may mix concrete
    arrays and ``jax.ShapeDtypeStruct``s.  Returns the Report."""
    import jax
    from .verifier import Report
    report = report if report is not None else Report()
    jaxpr = jax.make_jaxpr(step_fn)(*example_args)
    check_rank_divergence(jaxpr, report, where=where)
    return report


# ------------------------------------------------------------ the engine

def verify_spmd(sym, mesh_axes, config=None, report=None, shapes=None,
                arg_shapes=None):
    """Run the distributed-correctness pass; returns the Report.

    ``sym``: Symbol or None (config-only checks still run).
    ``mesh_axes``: {axis: size} mesh descriptor.  ``config``: a
    :func:`build_config` dict (missing keys default).  ``shapes``: the
    per-node shape map from ``infer_node_shapes`` (computed on demand
    when a Symbol is given); ``arg_shapes``: {param: shape} for the
    sharding-composition checks."""
    from .verifier import Report
    report = report if report is not None else Report()
    cfg = build_config() if config is None else dict(config)
    axes = {str(k): int(v) for k, v in (mesh_axes or {}).items()}

    node_shapes = shapes
    if sym is not None and node_shapes is None:
        data = dict(cfg.get("data_shapes") or {})
        data.update(cfg.get("label_shapes") or {})
        try:
            from .verifier import infer_node_shapes
            _topo, by_id = infer_node_shapes(sym, shapes=data)
            node_shapes = {}
            for n in _topo:
                sts = by_id.get(id(n))
                if sts is None:
                    continue
                for i, s in enumerate(sts):
                    node_shapes[(id(n), i)] = s
        except Exception:  # mxlint: allow-broad-except(shape inference is best-effort input to the schedule; structural checks still run without it)
            node_shapes = {}

    schedules = collective_schedule(sym, axes, cfg, shapes=node_shapes)
    check_schedules(schedules, axes, report)

    if int(cfg.get("pipeline_stages", 1)) > 1 and sym is not None:
        check_pipeline_partition(sym, axes, cfg, report,
                                 shapes=node_shapes)

    if arg_shapes is None and sym is not None:
        arg_shapes = {}
        for n in sym._topo():
            if n.is_variable and node_shapes and \
                    (id(n), 0) in node_shapes:
                arg_shapes[n.name] = node_shapes[(id(n), 0)]
    check_sharding_composition(sym, axes, cfg, report,
                               arg_shapes=arg_shapes)
    check_donation(cfg, report)

    # MXG016/MXG012 over the REAL lowering: trace each ring-attention
    # node's fwd + grad and require the inverse-perm ppermutes (the
    # modeled schedule's bwd is dual BY construction, so comparing it
    # to itself would be vacuous — check_gradient_parity stays the
    # audit for caller-provided schedules)
    check_ring_duality(sym, axes, cfg, report, shapes=node_shapes)
    return report


def verify_trainer_config(symbol, mesh, data_shapes, label_shapes,
                          pipeline_stages=1, pipeline_microbatches=None,
                          sequence_parallel=False, tp_rules=None,
                          dtype="float32", arg_shapes=None):
    """Bind-time entry for ShardedTrainer: assemble the config from the
    trainer's own constructor arguments and run :func:`verify_spmd`.
    Returns the Report (the trainer raises on errors under strict)."""
    axes = {str(k): int(v) for k, v in dict(mesh.shape).items()}
    cfg = build_config(
        pipeline_stages=pipeline_stages,
        pipeline_microbatches=pipeline_microbatches,
        sequence_parallel=sequence_parallel,
        tp_size=axes.get("model", 1),
        tp_rules=tp_rules,
        data_shapes=data_shapes, label_shapes=label_shapes,
        dtype=dtype)
    import os as _os
    env_rules = _os.environ.get("MXNET_TPU_RESHARD_RULES")
    if env_rules:
        cfg["reshard_rules"] = env_rules
    return verify_spmd(symbol, axes, cfg, arg_shapes=arg_shapes)
