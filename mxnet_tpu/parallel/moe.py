"""Expert parallelism: switch-routed mixture-of-experts FFN.

Beyond-reference capability (the 0.10.1 reference predates MoE), built
the TPU way: top-1 routing is expressed as dense one-hot dispatch
einsums (static shapes, no data-dependent control flow, MXU-friendly),
and expert parallelism is GSPMD — expert-major tensors carry a
``with_sharding_constraint`` over the ``expert`` mesh axis, so XLA
inserts the all-to-alls that a hand-written dispatch would need.

Routing follows the Switch Transformer recipe: per-token top-1 expert,
capacity ``ceil(T/E * capacity_factor)``, overflow tokens dropped (the
residual path carries them), gradient to the router through the gate
probability, and the standard load-balancing auxiliary loss.
"""
from __future__ import annotations

import math

import numpy as np


def switch_moe(x, router_w, w1, b1, w2, b2, capacity_factor=1.25,
               mesh=None, expert_axis="expert"):
    """Switch-MoE FFN.

    x: (tokens, d); router_w: (d, E); w1: (E, d, ff); b1: (E, ff);
    w2: (E, ff, d); b2: (E, d).
    Returns (y (tokens, d), aux_loss scalar).  With ``mesh``, expert-major
    intermediates are sharded over ``expert_axis`` (expert parallelism).
    """
    import jax
    import jax.numpy as jnp

    t, d = x.shape
    e = router_w.shape[1]
    c = int(math.ceil(t / e * capacity_factor))

    def shard(v, spec):
        if mesh is None:
            return v
        from jax.sharding import NamedSharding, PartitionSpec as P
        return jax.lax.with_sharding_constraint(
            v, NamedSharding(mesh, P(*spec)))

    # shard the expert weights too — expert parallelism's memory win is
    # each device holding only its E/n experts, not just sharded
    # activations (replicated committed params would otherwise win)
    w1 = shard(w1, (expert_axis, None, None))
    b1 = shard(b1, (expert_axis, None))
    w2 = shard(w2, (expert_axis, None, None))
    b2 = shard(b2, (expert_axis, None))

    logits = x @ router_w.astype(x.dtype)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)                 # (T,)
    gate = jnp.take_along_axis(probs, expert_idx[:, None],
                               axis=1)[:, 0]                # (T,)
    onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)   # (T,E)

    # position of each token within its expert's capacity buffer
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0         # (T,E)
    keep = (pos >= 0) & (pos < c)
    posc = jnp.clip(pos, 0, c - 1).astype(jnp.int32)
    disp = (onehot[:, :, None] *
            jax.nn.one_hot(posc, c, dtype=jnp.float32) *
            keep[:, :, None].astype(jnp.float32))           # (T,E,C)
    disp = disp.astype(x.dtype)

    xe = jnp.einsum("tec,td->ecd", disp, x)                 # (E,C,d)
    xe = shard(xe, (expert_axis, None, None))
    h = jnp.einsum("ecd,edf->ecf", xe, w1.astype(x.dtype))
    h = jax.nn.relu(h + b1[:, None, :].astype(x.dtype))
    h = shard(h, (expert_axis, None, None))
    ye = jnp.einsum("ecf,efd->ecd", h, w2.astype(x.dtype))
    ye = ye + b2[:, None, :].astype(x.dtype)
    ye = shard(ye, (expert_axis, None, None))

    y = jnp.einsum("tec,ecd->td", disp, ye)
    y = y * gate[:, None].astype(x.dtype)

    # Switch load-balance loss: E * sum_e f_e * P_e
    frac = jnp.mean(onehot, axis=0)                         # tokens/expert
    mean_p = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(frac * mean_p)
    return y, aux


def init_moe_params(rng, d, ff, num_experts, scale=0.1):
    """Convenience init for tests/examples."""
    return {
        "router_w": (rng.randn(d, num_experts) * scale).astype("float32"),
        "w1": (rng.randn(num_experts, d, ff) * scale).astype("float32"),
        "b1": np.zeros((num_experts, ff), "float32"),
        "w2": (rng.randn(num_experts, ff, d) * scale).astype("float32"),
        "b2": np.zeros((num_experts, d), "float32"),
    }


def make_expert_mesh(n_devices, devices=None):
    """1-d ('expert',) mesh for expert parallelism."""
    from .mesh import make_1d_mesh
    return make_1d_mesh("expert", n_devices, devices)
