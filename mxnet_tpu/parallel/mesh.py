"""Device-mesh construction helpers.

The reference's multi-device story is per-GPU worker threads + kvstore
reduce (SURVEY §2.4); the TPU-native story is one ``jax.sharding.Mesh``
whose axes name the parallelism kinds.  Convention here:

* ``data``  — data parallelism (batch dim sharded; grad psum rides ICI)
* ``model`` — tensor parallelism (weight dims sharded; GSPMD inserts
  all-gather/reduce-scatter)

Pipeline/sequence/expert axes are added by their owners when used.
"""
from __future__ import annotations

import numpy as np

__all__ = ["build_mesh", "build_mesh_from_axes", "data_parallel_spec",
           "largest_tp_factor"]


def largest_tp_factor(n, cap=8):
    """Largest power-of-two divisor of n, capped (heuristic tp size)."""
    tp = 1
    while n % (tp * 2) == 0 and tp * 2 <= cap:
        tp *= 2
    return tp


def build_mesh(n_devices=None, tp=1, pp=1, axis_names=None,
               devices=None):
    """Build a Mesh over the first n_devices jax devices.

    tp > 1 -> ('data', 'model') axes (tensor parallel inner);
    pp > 1 -> ('data', 'pipe') axes (pipeline stages inner; tp must be
    1 — packed pipeline stage params cannot also be tensor-sharded).
    """
    import jax
    from jax.sharding import Mesh
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    n = len(devices)
    if pp > 1:
        assert tp == 1, "tp and pp cannot both exceed 1 in build_mesh"
        assert n % pp == 0, "n_devices %d not divisible by pp %d" % (n, pp)
        arr = np.array(devices).reshape(n // pp, pp)
        return Mesh(arr, axis_names=axis_names or ("data", "pipe"))
    axis_names = axis_names or ("data", "model")
    assert n % tp == 0, "n_devices %d not divisible by tp %d" % (n, tp)
    if len(axis_names) == 1:
        assert tp == 1, "single-axis mesh cannot have tp > 1"
        arr = np.array(devices)
    else:
        arr = np.array(devices).reshape(n // tp, tp)
    return Mesh(arr, axis_names=axis_names)


def build_mesh_from_axes(axes, devices=None):
    """Mesh matching a reshard mesh-descriptor's axes dict, e.g.
    ``{"data": 4, "model": 2}`` (``parallel/reshard.py``;
    ``tools/reshard.py --mesh data=4,model=2`` parses into this form).
    Axis order follows the dict's insertion order; an empty dict gives
    a single-device ``('data',)`` mesh.  Raises ValueError when the
    product exceeds the available devices."""
    import jax
    from jax.sharding import Mesh
    axes = {str(k): int(v) for k, v in (axes or {}).items()} \
        or {"data": 1}
    n = 1
    for v in axes.values():
        n *= v
    devs = list(devices if devices is not None else jax.devices())
    if n > len(devs):
        raise ValueError(
            "mesh axes %r need %d devices, have %d" % (axes, n, len(devs)))
    arr = np.array(devs[:n]).reshape(tuple(axes.values()))
    return Mesh(arr, axis_names=tuple(axes))


def shard_map_nocheck(f, mesh, in_specs, out_specs):
    """shard_map with replication checking off, across jax versions
    (check_rep in <=0.7 / check_vma in >=0.8)."""
    import jax
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        try:
            return sm(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=False)
        except TypeError:
            return sm(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)
    from jax.experimental.shard_map import shard_map as esm
    return esm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def data_parallel_spec(mesh):
    """PartitionSpec sharding dim 0 (batch) over the data axis."""
    from jax.sharding import PartitionSpec as P
    return P(mesh.axis_names[0])


def make_1d_mesh(axis_name, n_devices, devices=None):
    """1-D mesh with ``axis_name`` over exactly ``n_devices`` devices."""
    import jax
    import numpy as _np
    devs = list(devices if devices is not None else jax.devices())[:n_devices]
    if len(devs) < n_devices:
        raise ValueError("need %d devices for the %r axis, have %d"
                         % (n_devices, axis_name, len(devs)))
    return jax.sharding.Mesh(_np.array(devs), (axis_name,))
