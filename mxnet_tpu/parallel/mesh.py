"""Device-mesh construction helpers.

The reference's multi-device story is per-GPU worker threads + kvstore
reduce (SURVEY §2.4); the TPU-native story is one ``jax.sharding.Mesh``
whose axes name the parallelism kinds.  Convention here:

* ``data``  — data parallelism (batch dim sharded; grad psum rides ICI)
* ``model`` — tensor parallelism (weight dims sharded; GSPMD inserts
  all-gather/reduce-scatter)

Pipeline/sequence/expert axes are added by their owners when used.
"""
from __future__ import annotations

import numpy as np

__all__ = ["build_mesh", "data_parallel_spec", "largest_tp_factor"]


def largest_tp_factor(n, cap=8):
    """Largest power-of-two divisor of n, capped (heuristic tp size)."""
    tp = 1
    while n % (tp * 2) == 0 and tp * 2 <= cap:
        tp *= 2
    return tp


def build_mesh(n_devices=None, tp=1, axis_names=("data", "model"),
               devices=None):
    """Build a (data, model) Mesh over the first n_devices jax devices."""
    import jax
    from jax.sharding import Mesh
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    n = len(devices)
    assert n % tp == 0, "n_devices %d not divisible by tp %d" % (n, tp)
    if len(axis_names) == 1:
        assert tp == 1, "single-axis mesh cannot have tp > 1"
        arr = np.array(devices)
    else:
        arr = np.array(devices).reshape(n // tp, tp)
    return Mesh(arr, axis_names=axis_names)


def data_parallel_spec(mesh):
    """PartitionSpec sharding dim 0 (batch) over the data axis."""
    from jax.sharding import PartitionSpec as P
    return P(mesh.axis_names[0])


def make_1d_mesh(axis_name, n_devices, devices=None):
    """1-D mesh with ``axis_name`` over exactly ``n_devices`` devices."""
    import jax
    import numpy as _np
    devs = list(devices if devices is not None else jax.devices())[:n_devices]
    if len(devs) < n_devices:
        raise ValueError("need %d devices for the %r axis, have %d"
                         % (n_devices, axis_name, len(devs)))
    return jax.sharding.Mesh(_np.array(devs), (axis_name,))
