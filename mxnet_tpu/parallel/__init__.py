"""Distributed / multi-chip execution.

Reference equivalents (SURVEY §5.8): the kvstore 'device' GPU reduce and the
ps-lite parameter server are both replaced by XLA collectives over ICI/DCN,
driven by sharding annotations on a ``jax.sharding.Mesh``.  This package
holds the TPU-native machinery:

* :mod:`mesh` — device-mesh construction (dp × tp axes).
* :mod:`trainer` — ``ShardedTrainer``: the Symbol graph fused into ONE
  pjit-compiled train step (forward + backward + optimizer + collectives),
  the performant path that Module's per-call forward/backward approximates.
* :mod:`dist_kvstore` — the ``dist_sync`` KVStore facade over collectives.
* :mod:`multihost` — process-spanning-mesh seams (runtime bootstrap,
  per-process shard staging, checkpoint gather).
* :mod:`sequence` — ring attention (sequence/context parallelism).
* :mod:`pipeline` — GPipe-style microbatch pipeline over a ``pipe`` axis.
* :mod:`reshard` — elastic training: checkpoint resharding across mesh
  shapes, rank join/leave events, ``match_partition_rules`` tables.
"""
from . import multihost
from . import reshard
from .mesh import build_mesh, build_mesh_from_axes, data_parallel_spec
from .moe import make_expert_mesh, switch_moe
from .pipeline import make_pipeline_mesh, pipeline_apply, pipeline_grad
from .trainer import ShardedTrainer
