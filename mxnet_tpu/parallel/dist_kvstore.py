"""Distributed KVStore over JAX multi-host collectives.

Reference: ``src/kvstore/kvstore_dist.h`` + ``kvstore_dist_server.h`` —
worker push/pull against parameter servers with sync aggregation over
exactly ``ps::NumWorkers()`` pushes, big keys sharded across servers
(`kvstore_dist.h:273-314`).  TPU-native design (SURVEY §5.8): no servers
exist; a ``dist_sync`` push is ONE jitted XLA program on a
process-spanning mesh that sums the whole gradient pytree across hosts
(AllReduce over DCN/ICI), replicating the result to every host — the
collective replaces the server shard fan-in/fan-out, and batching all
keys into one program replaces the reference's per-key zmq round trips.
``dist_async`` has no TPU analogue (collectives are globally
synchronous); it maps to sync semantics with a warning — SURVEY §7.7.

Bootstrap: ``jax.distributed.initialize`` replaces the ``DMLC_*`` env
bootstrap (`kvstore.h:162` InitPSEnv).  ``tools/launch.py`` sets
MXNET_TPU_{COORDINATOR,NUM_PROCESSES,PROCESS_ID}; creating a dist store
under that env joins the job automatically.
"""
from __future__ import annotations

import logging

import numpy as np

from ..base import MXNetError
from ..kvstore import KVStore, _nbytes

__all__ = ["DistKVStore"]


class DistKVStore(KVStore):
    """Multi-host synchronous kvstore."""

    def __init__(self, kv_type="dist_sync"):
        super().__init__(kv_type)
        import jax
        from . import multihost
        if "async" in kv_type:
            logging.warning(
                "dist_async has no TPU analogue (collectives are globally "
                "synchronous); using dist_sync semantics.")
        # join the launch.py job if one is described in the env (no-op
        # otherwise); shared with the fused-path bootstrap
        multihost.ensure_initialized()
        self._num_workers = jax.process_count()
        self._rank = jax.process_index()
        self._mesh = None
        self._reduce_fn = None

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._num_workers

    # ------------------------------------------------------------ collective
    def _host_mesh(self):
        """1-D mesh with one device per process.  The kvstore reduce rides
        the inter-host fabric; intra-host model/data parallelism belongs
        to ``parallel.ShardedTrainer``'s own mesh."""
        if self._mesh is None:
            import jax
            devs = []
            for p in range(self._num_workers):
                devs.append(next(d for d in jax.devices()
                                 if d.process_index == p))
            self._mesh = jax.sharding.Mesh(np.array(devs), ("hosts",))
        return self._mesh

    def allreduce(self, tree):
        """Sum a pytree of per-host numpy/jax arrays across all hosts in
        ONE jitted program; every leaf comes back replicated on every
        host.  The TPU-native replacement for the reference's per-key
        server push/pull (kvstore_dist.h:99-161)."""
        if self._num_workers == 1:
            return tree
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        mesh = self._host_mesh()
        ins = NamedSharding(mesh, PartitionSpec("hosts"))
        outs = NamedSharding(mesh, PartitionSpec())

        def lift(x):
            # jax arrays stay on device; host arrays upload once
            local = x[None] if isinstance(x, jax.Array) \
                else np.asarray(x)[None]
            return jax.make_array_from_process_local_data(ins, local)

        global_tree = jax.tree.map(lift, tree)
        if self._reduce_fn is None:
            self._reduce_fn = jax.jit(
                lambda t: jax.tree.map(lambda g: g.sum(axis=0), t),
                out_shardings=outs)
        return self._reduce_fn(global_tree)

    def _global_sum(self, arr):
        """Sum one array over all processes (kept for callers of the
        round-1 API; new code should batch keys via :meth:`allreduce`)."""
        out = self.allreduce([arr.asnumpy() if hasattr(arr, "asnumpy")
                              else np.asarray(arr)])
        return out[0]

    # ------------------------------------------------------------------ api
    def push(self, key, value, priority=0):
        """Aggregate local replicas, AllReduce every key across hosts in
        one program, then apply the updater — the reference's
        sync-aggregation contract (kvstore_dist_server.h:164-199: update
        runs once after exactly num_workers pushes)."""
        from ..kvstore import _ctype_key_value, _group_kv_pairs
        from ..ndarray import NDArray
        keys, vals = _ctype_key_value(key, value)
        uniq, grouped = _group_kv_pairs(keys, vals)
        merged = {}
        push_bytes = 0
        for k, group in zip(uniq, grouped):
            m = group[0].copy()
            for other in group[1:]:
                m += other
            merged[k] = m
            push_bytes += _nbytes(m)
        self._push_bytes.inc(push_bytes)
        if self._num_workers > 1:
            # cross-host collective: worth a flight-ring entry (a hang
            # or peer death surfaces here), unlike the per-param local
            # aggregation above.  The distview timestamp barrier just
            # before it measures — not infers — how long this rank
            # waited on its slowest peer (straggler attribution:
            # collective wait lands on the FAST ranks).
            from ..telemetry import distview as _dv
            from ..telemetry import flight as _flight
            skew = _dv.pre_collective_barrier("kvstore.push")
            ev = {"op": "allreduce", "store": "dist_sync",
                  "keys": len(merged), "bytes": push_bytes}
            if skew is not None:
                ev["wait_s"] = round(skew["wait_s"], 6)
                ev["skew_s"] = round(skew["skew_s"], 6)
                ev["slowest_rank"] = skew["slowest_rank"]
            _flight.record("kvstore", **ev)
            summed = self.allreduce({k: m.data for k, m in merged.items()})
            # addressable_data(0) is this host's replica of the reduced
            # value — no host round trip
            merged = {k: NDArray(v.addressable_data(0))
                      for k, v in summed.items()}
        for k, m in merged.items():
            if self._updater is not None:
                if k not in self._store:
                    raise MXNetError("key %s has not been inited" % str(k))
                self._updater(k, m, self._store[k])
            else:
                self._store[k] = m

    def barrier(self):
        if self._num_workers > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("mxnet_tpu_kvstore_barrier")

    @staticmethod
    def init_env(**kwargs):
        """Initialize the multi-host runtime (replaces InitPSEnv)."""
        import jax
        jax.distributed.initialize(**kwargs)
