"""Distributed KVStore facade over JAX multi-host collectives.

Reference: ``src/kvstore/kvstore_dist.h`` + ``kvstore_dist_server.h`` —
worker push/pull against parameter servers with sync aggregation over
exactly ``ps::NumWorkers()`` pushes.  TPU-native design (SURVEY §5.8): no
servers exist; ``dist_sync`` push = a global psum over all hosts' gradients
via a jitted sum on a process-spanning mesh (DCN/ICI collectives), followed
by the local updater.  ``dist_async`` has no TPU analogue (collectives are
globally synchronous); we map it to sync semantics and warn — see
SURVEY §7.7 for the descoping rationale.

Bootstrap: ``jax.distributed.initialize`` replaces the ``DMLC_*`` env
bootstrap (`kvstore.h:162` InitPSEnv); ``tools/launch.py`` sets the
coordinator env vars.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from ..kvstore import KVStore

__all__ = ["DistKVStore"]


class DistKVStore(KVStore):
    """Multi-host synchronous kvstore."""

    def __init__(self, kv_type="dist_sync"):
        super().__init__(kv_type)
        import jax
        if "async" in kv_type:
            logging.warning(
                "dist_async has no TPU analogue (collectives are globally "
                "synchronous); using dist_sync semantics.")
        self._num_workers = jax.process_count()
        self._rank = jax.process_index()
        self._psum_fn = None

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._num_workers

    def _global_sum(self, arr):
        """Sum an array over all processes (DCN collective)."""
        import jax
        if self._num_workers == 1:
            return arr
        import jax.numpy as jnp
        from jax.experimental.multihost_utils import (
            process_allgather)
        # all-gather over hosts then sum: one DCN collective per push.
        gathered = process_allgather(arr.data if hasattr(arr, "data")
                                     else arr)
        return jnp.sum(gathered, axis=0)

    def push(self, key, value, priority=0):
        from ..kvstore import _ctype_key_value, _group_kv_pairs
        from ..ndarray import NDArray
        keys, vals = _ctype_key_value(key, value)
        uniq, grouped = _group_kv_pairs(keys, vals)
        for k, group in zip(uniq, grouped):
            merged = group[0].copy()
            for other in group[1:]:
                merged += other
            if self._num_workers > 1:
                merged = NDArray(self._global_sum(merged))
            if self._updater is not None:
                if k not in self._store:
                    raise MXNetError("key %s has not been inited" % str(k))
                self._updater(k, merged, self._store[k])
            else:
                self._store[k] = merged

    def barrier(self):
        if self._num_workers > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("mxnet_tpu_kvstore_barrier")

    @staticmethod
    def init_env(**kwargs):
        """Initialize the multi-host runtime (replaces InitPSEnv)."""
        import jax
        jax.distributed.initialize(**kwargs)
