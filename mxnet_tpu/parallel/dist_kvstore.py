"""Distributed KVStore over JAX multi-host collectives.

Reference: ``src/kvstore/kvstore_dist.h`` + ``kvstore_dist_server.h`` —
worker push/pull against parameter servers with sync aggregation over
exactly ``ps::NumWorkers()`` pushes, big keys sharded across servers
(`kvstore_dist.h:273-314`).  TPU-native design (SURVEY §5.8): no servers
exist; a ``dist_sync`` push is ONE jitted XLA program on a
process-spanning mesh that sums the whole gradient pytree across hosts
(AllReduce over DCN/ICI), replicating the result to every host — the
collective replaces the server shard fan-in/fan-out, and batching all
keys into one program replaces the reference's per-key zmq round trips.
``dist_async`` has no TPU analogue (collectives are globally
synchronous); it maps to sync semantics with a warning — SURVEY §7.7.

Bootstrap: ``jax.distributed.initialize`` replaces the ``DMLC_*`` env
bootstrap (`kvstore.h:162` InitPSEnv).  ``tools/launch.py`` sets
MXNET_TPU_{COORDINATOR,NUM_PROCESSES,PROCESS_ID}; creating a dist store
under that env joins the job automatically.
"""
from __future__ import annotations

import logging

import numpy as np

from ..base import MXNetError
from ..kvstore import KVStore, _nbytes

__all__ = ["DistKVStore"]


class DistKVStore(KVStore):
    """Multi-host synchronous kvstore."""

    def __init__(self, kv_type="dist_sync"):
        super().__init__(kv_type)
        import jax
        from . import multihost
        if "async" in kv_type:
            logging.warning(
                "dist_async has no TPU analogue (collectives are globally "
                "synchronous); using dist_sync semantics.")
        # join the launch.py job if one is described in the env (no-op
        # otherwise); shared with the fused-path bootstrap
        multihost.ensure_initialized()
        self._num_workers = jax.process_count()
        self._rank = jax.process_index()
        self._mesh = None
        self._reduce_fn = None
        self._merge_fn = None
        self._bucket_queue = None

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._num_workers

    # ------------------------------------------------------------ collective
    def _host_mesh(self):
        """1-D mesh with one device per process.  The kvstore reduce rides
        the inter-host fabric; intra-host model/data parallelism belongs
        to ``parallel.ShardedTrainer``'s own mesh."""
        if self._mesh is None:
            import jax
            devs = []
            for p in range(self._num_workers):
                devs.append(next(d for d in jax.devices()
                                 if d.process_index == p))
            self._mesh = jax.sharding.Mesh(np.array(devs), ("hosts",))
        return self._mesh

    def allreduce(self, tree):
        """Sum a pytree of per-host numpy/jax arrays across all hosts in
        ONE jitted program; every leaf comes back replicated on every
        host.  The TPU-native replacement for the reference's per-key
        server push/pull (kvstore_dist.h:99-161)."""
        if self._num_workers == 1:
            return tree
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        mesh = self._host_mesh()
        ins = NamedSharding(mesh, PartitionSpec("hosts"))
        outs = NamedSharding(mesh, PartitionSpec())

        def lift(x):
            # jax arrays stay on device; host arrays upload once
            local = x[None] if isinstance(x, jax.Array) \
                else np.asarray(x)[None]
            return jax.make_array_from_process_local_data(ins, local)

        global_tree = jax.tree.map(lift, tree)
        if self._reduce_fn is None:
            self._reduce_fn = jax.jit(
                lambda t: jax.tree.map(lambda g: g.sum(axis=0), t),
                out_shardings=outs)
        return self._reduce_fn(global_tree)

    def _global_sum(self, arr):
        """Sum one array over all processes (kept for callers of the
        round-1 API; new code should batch keys via :meth:`allreduce`)."""
        out = self.allreduce([arr.asnumpy() if hasattr(arr, "asnumpy")
                              else np.asarray(arr)])
        return out[0]

    # ----------------------------------------------------- local merge
    def _merge_local(self, key, value):
        """Merge local replicas per key into ``{key: NDArray}`` and the
        total payload bytes.  Single-member groups pass through WITHOUT
        the old defensive ``copy()`` — the allreduce consumes without
        mutating, and the single-process apply paths re-protect via
        ``copy_on_store`` (:meth:`_apply` copies before a store
        assignment or a user updater, either of which could otherwise
        alias/mutate the caller's live gradient); multi-member groups
        are summed in ONE dispatched program over the whole key set —
        the old serial per-key ``m += other`` host loop paid a dispatch
        per replica per key (ISSUE 15 satellite)."""
        from ..kvstore import _ctype_key_value, _group_kv_pairs
        from ..ndarray import NDArray
        keys, vals = _ctype_key_value(key, value)
        uniq, grouped = _group_kv_pairs(keys, vals)
        merged = {}
        multi = {}
        push_bytes = 0
        for k, group in zip(uniq, grouped):
            if len(group) == 1:
                merged[k] = group[0]
            else:
                multi[k] = [g.data for g in group]
            push_bytes += _nbytes(group[0])
        if multi:
            if self._merge_fn is None:
                import functools
                import jax
                # sum each key's replica list left-to-right inside one
                # jitted program (bit-identical to the old serial
                # NDArray += loop, which also folded left-to-right);
                # jit caches per pytree structure, so a different key
                # set retraces and a repeated one dispatches directly
                self._merge_fn = jax.jit(lambda tree: {
                    kk: functools.reduce(lambda a, b: a + b, vs)
                    for kk, vs in tree.items()})
            summed = self._merge_fn(multi)
            for k, v in summed.items():
                merged[k] = NDArray(v)
        return {k: merged[k] for k in uniq}, push_bytes

    # ------------------------------------------------------------------ api
    def push(self, key, value, priority=0):
        """Aggregate local replicas, AllReduce every key across hosts in
        one program, then apply the updater — the reference's
        sync-aggregation contract (kvstore_dist_server.h:164-199: update
        runs once after exactly num_workers pushes).

        This is the SYNCHRONOUS path (one fleet-wide collective per
        call); trainer gradient sync should prefer the bucketed
        :meth:`push_bucketed`/:meth:`drain` pair, which overlaps the
        allreduce with backward (``model._update_params_on_kvstore``
        routes there when ``MXNET_TPU_OVERLAP`` is on — see
        docs/api/overlap.md)."""
        from ..ndarray import NDArray
        merged, push_bytes = self._merge_local(key, value)
        self._push_bytes.inc(push_bytes)
        if self._num_workers > 1:
            # cross-host collective: worth a flight-ring entry (a hang
            # or peer death surfaces here), unlike the per-param local
            # aggregation above.  The distview timestamp barrier just
            # before it measures — not infers — how long this rank
            # waited on its slowest peer (straggler attribution:
            # collective wait lands on the FAST ranks).
            from ..telemetry import distview as _dv
            from ..telemetry import flight as _flight
            skew = _dv.pre_collective_barrier("kvstore.push")
            ev = {"op": "allreduce", "store": "dist_sync",
                  "keys": len(merged), "bytes": push_bytes}
            if skew is not None:
                ev["wait_s"] = round(skew["wait_s"], 6)
                ev["skew_s"] = round(skew["skew_s"], 6)
                ev["slowest_rank"] = skew["slowest_rank"]
            _flight.record("kvstore", **ev)
            summed = self.allreduce({k: m.data for k, m in merged.items()})
            # addressable_data(0) is this host's replica of the reduced
            # value — no host round trip
            merged = {k: NDArray(v.addressable_data(0))
                      for k, v in summed.items()}
        self._apply(merged, copy_on_store=self._num_workers == 1)

    def _apply(self, merged, copy_on_store=False):
        """Apply reduced values: through the updater when installed,
        else into the store.  ``copy_on_store``: single-process merges
        skip the defensive copy in :meth:`_merge_local`, so BOTH
        branches re-protect here — a store assignment (which keeps the
        array) copies instead of aliasing the caller's gradient, and an
        installed updater receives a private recv buffer (the reference
        contract lets a user updater mutate its gradient argument in
        place; without the copy that would corrupt the executor's live
        gradient).  Multi-worker values are fresh allreduce outputs and
        never alias."""
        if self._updater is not None:
            # validate the whole batch BEFORE any update so a missing
            # key cannot leave a partially-applied drain
            for k in merged:
                if k not in self._store:
                    raise MXNetError("key %s has not been inited"
                                     % str(k))
        for k, m in merged.items():
            if self._updater is not None:
                self._updater(k, m.copy() if copy_on_store else m,
                              self._store[k])
            else:
                self._store[k] = m.copy() if copy_on_store else m

    # ------------------------------------------- bucketed overlap path
    @property
    def overlap_active(self):
        """Whether trainer gradient sync should route through the
        bucketed :meth:`push_bucketed`/:meth:`drain` pair
        (``MXNET_TPU_OVERLAP``, multi-worker only — a single process
        has no collective to hide)."""
        from . import overlap as _overlap
        return self._num_workers > 1 and _overlap.overlap_enabled()

    def _launch_bucket(self, bucket):
        """BucketQueue reduce_fn: dispatch ONE bucket's pytree
        allreduce.  JAX dispatch is asynchronous — the call returns as
        soon as the program is enqueued, so the collective runs behind
        whatever device work (the backward) is still in flight; the
        returned handle only converts the already-dispatched arrays."""
        from ..ndarray import NDArray
        summed = self.allreduce({k: m.data for k, m in bucket.items()})

        def handle():
            return {k: NDArray(v.addressable_data(0))
                    for k, v in summed.items()}
        return handle

    def push_bucketed(self, key, value, priority=0):
        """Bucketed asynchronous push: merge local replicas (one
        dispatched program), append to the current size-targeted
        bucket (``MXNET_TPU_BUCKET_BYTES``), and launch a full
        bucket's allreduce immediately — overlapping the rest of
        gradient production.  Nothing is applied until :meth:`drain`;
        per-key :meth:`pull` ordering holds after the drain exactly as
        after a synchronous push."""
        from . import overlap as _overlap
        if self._num_workers <= 1:
            # no collective to bucket: keep the synchronous semantics
            return self.push(key, value, priority=priority)
        if self._bucket_queue is None:
            self._bucket_queue = _overlap.BucketQueue(
                self._launch_bucket, site="kvstore.push")
        merged, push_bytes = self._merge_local(key, value)
        self._push_bytes.inc(push_bytes)
        for k, m in merged.items():
            self._bucket_queue.push(k, m, _nbytes(m))

    def drain(self):
        """Optimizer boundary: launch the remaining buckets
        (slowest-to-produce first — parallel/overlap.py scheduler),
        wait out every in-flight allreduce, then apply the updater for
        ALL keys.  All-or-nothing: a collective fault mid-drain (the
        ``kvstore.collective`` seam) raises before any update is
        applied, leaving optimizer state untouched.  No-op when
        nothing was pushed."""
        if self._bucket_queue is None or not self._bucket_queue.pending:
            return
        mesh = {"hosts": self._num_workers}
        reduced = self._bucket_queue.drain(mesh=mesh)
        self._apply(reduced)

    def pull(self, key, out=None, priority=0):
        """Join any in-flight buckets first: per-worker push-then-pull
        ordering must hold for the bucketed path exactly as it does for
        the synchronous :meth:`push` (``AsyncKVStore.pull`` has the
        same guard) — without it a ``push_bucketed`` → ``pull`` pair
        would silently read the stale pre-drain weights."""
        self.drain()
        return super().pull(key, out=out, priority=priority)

    def barrier(self):
        if self._num_workers > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("mxnet_tpu_kvstore_barrier")

    # --------------------------------------------------- elastic migration
    def save_state(self, prefix, epoch):
        """Checkpoint every initialized key (rank 0 writes, ordered by a
        barrier) in the standard manifest-verified checkpoint format
        (``prefix-%04d.params`` + CRC manifest, schema v2 meta carrying
        the saving world size).  KVStore values are replicated across
        workers, so the file is world-size independent — the elastic
        migration path: a fleet restarted at a different size reloads
        it via :meth:`load_state` (docs/api/reshard.md).  Returns the
        params path."""
        import numpy as np_
        from .. import ndarray as _nd
        from .. import resilience

        path = "%s-%04d.params" % (prefix, int(epoch))
        # the gather seam is evaluated SYMMETRICALLY on every rank: an
        # armed chaos fault fails the whole fleet's save together
        # instead of rank 0 alone raising while its peers sit in the
        # barrier below
        for k in sorted(self._store, key=str):
            resilience.fault_point("reshard.gather")
        if self._rank == 0:
            # only the writer gathers: values are replicated, so the
            # other ranks would pay a full device-to-host copy of the
            # store just to discard it at the barrier
            arrays = {}
            for k in sorted(self._store, key=str):
                v = self._store[k]
                # keys keep their type across the file: "kv:i:3" for
                # int 3, "kv:s:7" for the STRING "7" (a bare "kv:7"
                # could not tell them apart on load)
                tag = "i" if isinstance(k, int) else "s"
                arrays["kv:%s:%s" % (tag, k)] = np_.asarray(
                    v.asnumpy() if hasattr(v, "asnumpy") else v)
            resilience.atomic_write(
                path,
                lambda tmp: _nd.save(
                    tmp, {k: _nd.array(v) for k, v in arrays.items()}),
                fault_site="checkpoint.save")
            resilience.write_manifest(
                prefix, int(epoch), [path], arrays=arrays,
                meta={"mesh": {"format": 2, "axes": {},
                               "world": self._num_workers},
                      "kvstore": self.type})
        # the timeout-bounded barrier (MXNET_TPU_BARRIER_TIMEOUT): a
        # rank-0 write failure must surface on the peers as the
        # dead-rank barrier error, not an unbounded hang
        from . import multihost
        multihost.process_barrier("dist_kvstore_state_save")
        return path

    def load_state(self, prefix, epoch):
        """Restore the key/value store saved by :meth:`save_state` on
        ANY world size (every rank reads the shared file).  A world-size
        change fires the ``elastic.rejoin`` seam and records
        ``rank_join``/``rank_leave`` + ``mxtpu_reshard_total``
        {kind="kvstore"} — the kvstore analogue of the trainer's
        checkpoint reshard.  Returns the world size the state was saved
        at."""
        from .. import ndarray as _nd
        from .. import resilience
        from . import reshard as _reshard

        resilience.fault_point("checkpoint.load")
        manifest = resilience.verify_manifest(prefix, int(epoch))
        saved_desc = _reshard.manifest_mesh(manifest)
        saved_world = int((saved_desc or {}).get("world") or 1)
        if saved_world != self._num_workers:
            resilience.fault_point("elastic.rejoin")
        path = "%s-%04d.params" % (prefix, int(epoch))
        try:
            loaded = _nd.load(path)
        except FileNotFoundError as e:
            raise MXNetError("kvstore state file %r is missing for "
                             "epoch %d" % (path, int(epoch))) from e
        store = {}
        nbytes = 0
        for k, v in loaded.items():
            parts = k.split(":", 2)
            if len(parts) != 3 or parts[0] != "kv" or \
                    parts[1] not in ("i", "s"):
                raise MXNetError(
                    "%r is not a kvstore state file: unexpected key %r "
                    "(expected kv:i:<int>/kv:s:<name> entries)"
                    % (path, k))
            key = int(parts[2]) if parts[1] == "i" else parts[2]
            # nd.load already yields jax-backed NDArrays; keep them
            # (an asnumpy round trip would both copy and break the
            # '_data is a jax.Array' invariant)
            store[key] = v
            nbytes += _nbytes(v)
        self._store = store
        if saved_world != self._num_workers:
            _reshard.note_reshape(
                "kvstore",
                {"n_params": len(store), "n_resharded": 0,
                 "bytes": nbytes, "src": "world=%d" % saved_world,
                 "dst": "world=%d" % self._num_workers},
                epoch=int(epoch))
            _reshard.note_world_change(saved_world, self._num_workers,
                                       kind="kvstore")
        return saved_world

    @staticmethod
    def init_env(**kwargs):
        """Initialize the multi-host runtime (replaces InitPSEnv)."""
        import jax
        jax.distributed.initialize(**kwargs)
