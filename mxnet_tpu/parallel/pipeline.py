"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh.

The reference's model parallelism assigns whole layers to devices and
runs them sequentially per batch (`example/model-parallel-lstm/
lstm.py:142-205` — each LSTM layer on its own GPU, overlap only from
async engine dispatch).  This module is the compiled TPU-native
successor: the layer stack is sharded over a ``pipe`` mesh axis, the
batch is split into microbatches, and ONE jitted SPMD program streams
activations stage-to-stage over the ICI ring (`lax.ppermute` inside
`shard_map`), so all stages compute concurrently after the fill phase.
Gradients come from `jax.grad` straight through the schedule — the
backward pass replays it in reverse (GPipe semantics; per-microbatch
`jax.checkpoint` keeps activation memory at O(microbatch)).

Scope: uniform stages — every stage maps (microbatch, ...) -> the same
shape (layer stacks: RNN/transformer layers, repeated blocks).  The
stage parameters are stacked on a leading axis sharded over ``pipe``.
"""
from __future__ import annotations

import functools

__all__ = ["pipeline_apply", "pipeline_grad", "make_pipeline_mesh"]


def make_pipeline_mesh(n_stages, devices=None):
    """1-D mesh with a ``pipe`` axis of n_stages devices."""
    from .mesh import make_1d_mesh
    return make_1d_mesh("pipe", n_stages, devices)


def _stage_loop(stage_fn, params_stack, x_stack, axis_name, remat,
                n_stages):
    """Per-device body under shard_map.

    params_stack: (1, ...) this device's stage params (leading stage axis
    sharded to size 1).  x_stack: (M, B_u, ...) all microbatches,
    replicated.  Returns (M, B_u, ...) outputs of the LAST stage
    (garbage on other devices; caller slices stage S-1's shard).
    ``n_stages`` is threaded in statically (the scan length and the
    ppermute ring need python ints; jax 0.4.x has no lax.axis_size).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    n = n_stages
    sid = lax.axis_index(axis_name)
    m = x_stack.shape[0]
    params = jax.tree.map(lambda p: p[0], params_stack)
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    shift = [(i, (i + 1) % n) for i in range(n)]  # stage s -> s+1

    def tick(carry, t):
        # carry: (inbuf, outputs)
        #   inbuf: (B_u, ...) the activation this stage consumes this tick
        #   outputs: (M, B_u, ...) last-stage results by microbatch
        inbuf, outputs = carry
        # stage 0 reads microbatch t from the input stream; others read
        # what the previous stage sent last tick
        x_t = jnp.where(sid == 0,
                        x_stack[jnp.clip(t, 0, m - 1)], inbuf)
        # active when microbatch (t - sid) is in range
        mb = t - sid
        active = (mb >= 0) & (mb < m)
        y = fn(params, x_t)
        y = jnp.where(active, y, jnp.zeros_like(y))
        # the last stage stores its result; everyone else forwards it
        outputs = jnp.where(
            (sid == n - 1) & active,
            outputs.at[jnp.clip(mb, 0, m - 1)].set(y), outputs)
        nxt = lax.ppermute(y, axis_name, shift)
        return (nxt, outputs), None

    inbuf0 = jnp.zeros_like(x_stack[0])
    outputs0 = jnp.zeros_like(x_stack)
    (_, outputs), _ = lax.scan(tick, (inbuf0, outputs0),
                               jnp.arange(m + n - 1))
    return outputs


def pipeline_apply(stage_fn, params_stack, x, mesh, microbatches,
                   remat=True):
    """Run ``x`` through ``n_stages`` pipelined applications of
    ``stage_fn`` (one stage per device on the mesh's ``pipe`` axis).

    stage_fn(params, x_micro) -> y_micro with y.shape == x.shape (uniform
    stages).  params_stack: pytree whose leaves have a leading stage axis
    of size n_stages.  x: (batch, ...), split into ``microbatches`` equal
    chunks.  Returns (batch, ...) outputs of the final stage, replicated.
    """
    import jax
    from .. import telemetry
    if not jax.core.trace_state_clean():
        # caller is tracing (jit(pipeline_apply) is a supported
        # pattern): a span here would record one trace-time interval
        # and then nothing per execution — worse than no data
        return _pipeline_apply(stage_fn, params_stack, x, mesh,
                               microbatches, remat)
    with telemetry.span("pipeline.apply", category="trainer"):
        return _pipeline_apply(stage_fn, params_stack, x, mesh,
                               microbatches, remat)


def _pipeline_apply(stage_fn, params_stack, x, mesh, microbatches, remat):
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.devices.size
    b = x.shape[0]
    if b % microbatches:
        raise ValueError("batch %d not divisible into %d microbatches"
                         % (b, microbatches))
    x_stack = x.reshape((microbatches, b // microbatches) + x.shape[1:])

    body = functools.partial(_stage_loop, stage_fn, axis_name="pipe",
                             remat=remat, n_stages=int(n))
    out = shard_map(
        lambda p, xs: jax.lax.psum(body(p, xs), "pipe"),
        mesh=mesh,
        in_specs=(P("pipe"), P()),
        out_specs=P(),
        check_rep=False,
    )(params_stack, x_stack)
    # only the last stage contributed nonzeros; psum replicates its result
    return out.reshape((b,) + out.shape[2:])


def pipeline_grad(loss_fn, stage_fn, params_stack, x, labels, mesh,
                  microbatches, remat=True):
    """(loss, grads) of ``loss_fn(pipeline(x), labels)`` w.r.t. the
    stacked stage params — jax.grad runs the schedule in reverse
    (ppermute transposes to the opposite ring direction)."""
    import jax
    from .. import telemetry

    def full(p):
        y = _pipeline_apply(stage_fn, p, x, mesh, microbatches,
                            remat=remat)
        return loss_fn(y, labels)

    if not jax.core.trace_state_clean():
        # under an outer trace a span records nothing per execution
        return jax.value_and_grad(full)(params_stack)
    with telemetry.span("pipeline.grad", category="trainer"):
        return jax.value_and_grad(full)(params_stack)


# ===================================================================
# Heterogeneous stages: arbitrary per-stage functions/params/shapes.
#
# The uniform path above stacks identical stage params; real models
# (ResNet stages, embed->blocks->head transformers) have per-stage
# pytrees of different shapes and different boundary activations.  The
# SPMD-compatible encoding:
#
# * each stage's (compute-dtype) params are flattened and concatenated
#   into one vector, padded to the max stage length, stacked (N, L) and
#   sharded over ``pipe`` — every device holds ONLY its stage's packed
#   params (no replication);
# * boundary activations are flattened per sample and padded to the max
#   boundary width W, so the ring carries one (B_u, W) buffer;
# * the per-device stage body is ``lax.switch(stage_id, branches)`` —
#   each branch statically unpacks ITS stage's params/input shape, runs
#   the stage, and re-packs.  Only the resident branch executes on each
#   device, so compute and memory stay per-stage.
#
# The GPipe schedule (fill, steady state, drain over M + N - 1 ticks)
# and its reverse-mode transpose are the same as the uniform path.
# ===================================================================

def plan_pipeline_stages(topo, entries, batch_names, n_stages,
                         cost_of=None, legal_cut=None):
    """Partition a Symbol graph into ``n_stages`` contiguous segments.

    Cuts are only legal where exactly ONE tensor crosses the boundary
    (single-live-tensor positions — between residual blocks, transformer
    layers, stacked stages); ``legal_cut((node, out_idx)) -> bool`` can
    veto candidates further (the trainer rejects boundaries whose
    leading dim is not the microbatch row count).  Segments are balanced
    by ``cost_of`` (node -> float; default: 1 per node — callers with
    shape information pass a params+activations proxy).

    Returns a list of per-stage dicts:
      nodes         — the segment's non-variable nodes, topo order
      boundary_in   — (node, out_idx) produced by the previous segment
                      (None for stage 0)
      param_names   — names of weight variables consumed by the segment
      batch_names   — batch variables consumed by the segment (stage 0
                      gets the data; later stages e.g. the loss labels)
    Raises MXNetError when the graph has no n_stages-1 legal cuts or
    when a segment node carries auxiliary state (BatchNorm moving stats
    — GPipe microbatching would change their semantics).
    """
    from ..base import MXNetError

    nodes = [n for n in topo if not n.is_variable]
    if len(nodes) < n_stages:
        raise MXNetError("graph has %d op nodes < %d pipeline stages"
                         % (len(nodes), n_stages))
    pos = {id(n): i for i, n in enumerate(nodes)}
    end = len(nodes)

    # last consumer position of every (producer, out_idx)
    last_use = {}
    for i, n in enumerate(nodes):
        for (src, idx) in n.inputs:
            if not src.is_variable:
                last_use[(id(src), idx)] = i
    for (n, idx) in entries:
        last_use[(id(n), idx)] = end

    # legal cut positions: after node i, exactly one value crosses
    id2node = {id(n): n for n in nodes}
    crossings = {}
    for i in range(len(nodes) - 1):
        live = [(pid, idx) for (pid, idx), lu in last_use.items()
                if pos[pid] <= i < lu]
        if len(live) == 1:
            pid, idx = live[0]
            if legal_cut is None or legal_cut((id2node[pid], idx)):
                crossings[i] = live[0]

    if cost_of is None:
        def cost_of(node):
            return 1.0
    prefix = []
    acc = 0.0
    for n in nodes:
        acc += float(cost_of(n))
        prefix.append(acc)
    total = acc

    cuts = []
    prev = -1
    cands = sorted(crossings)
    for s in range(1, n_stages):
        target = total * s / n_stages
        best = None
        for c in cands:
            if c <= prev or (cuts and c <= cuts[-1]):
                continue
            # keep enough remaining cut positions for later stages
            remaining = sum(1 for cc in cands if cc > c)
            if remaining < n_stages - 1 - s:
                continue
            if best is None or abs(prefix[c] - target) < \
                    abs(prefix[best] - target):
                best = c
        if best is None:
            raise MXNetError(
                "cannot cut the graph into %d pipeline stages: only %d "
                "single-live-tensor positions available" %
                (n_stages, len(cands)))
        cuts.append(best)
        prev = best

    stages = []
    bounds = [-1] + cuts + [len(nodes) - 1]
    for s in range(n_stages):
        seg = nodes[bounds[s] + 1: bounds[s + 1] + 1]
        pnames, bnames = [], []
        for n in seg:
            if len(n.inputs) > n.num_args:
                raise MXNetError(
                    "pipeline stage %d contains %r which carries "
                    "auxiliary state; GPipe microbatching would change "
                    "its semantics (BatchNorm moving stats are per-"
                    "microbatch) — use LayerNorm-style models or fewer "
                    "stages" % (s, n.name))
            stoch = n.op.stochastic
            if callable(stoch):
                stoch = stoch(n.attrs)
            if stoch:
                raise MXNetError(
                    "pipeline stage %d contains stochastic op %r; the "
                    "pipelined trace does not thread PRNG keys — set "
                    "dropout to 0 for pipeline training" % (s, n.name))
            for (src, _i) in n.inputs:
                if src.is_variable:
                    if src.name in batch_names:
                        if src.name not in bnames:
                            bnames.append(src.name)
                    elif src.name not in pnames:
                        pnames.append(src.name)
        boundary_in = None
        if s > 0:
            pid, idx = crossings[cuts[s - 1]]
            boundary_in = (id2node[pid], idx)
        stages.append({"nodes": seg, "boundary_in": boundary_in,
                       "param_names": pnames, "batch_names": bnames})
    return stages


def hetero_pipeline_loss(branches, x_stack, params_stack, microbatches,
                         axis_name="pipe", remat=True):
    """GPipe schedule over heterogeneous stage branches (per-device body
    — call under shard_map).

    branches: list of N fns ``(packed_params_row, x_flat, mb) ->
    (y_flat, loss)`` — branch s unpacks its own stage statically; all
    return the common padded buffer width and a shape-(1,) loss (nonzero
    only from the last stage).  x_stack: (M, B_u, W) microbatched input
    (consumed by stage 0).  params_stack: either (1, L) — this device's
    packed stage params, pre-sharded over ``axis_name`` — or (N, L)
    REPLICATED, in which case each device dynamically selects its
    stage's row.  Callers composing pipe with a data axis must pass the
    replicated form: GSPMD (jax 0.4.x) mispartitions the reshard of an
    in-jit concatenate onto a minor mesh axis — the partial
    dynamic-update-slices it combines with an add double-count the data
    replicas, silently scaling the packed params by the data-axis size.
    Returns the shape-(1,) summed loss over microbatches (nonzero on
    the last stage; psum over ``axis_name`` to broadcast).

    The loss stays rank-1 end to end INSIDE the shard_map body: jax
    0.4.x's shard_map partial-eval promotes rank-0 residuals
    inconsistently across the remat/transpose path, and a scalar
    residual with dim-0 axis names fails its out-spec check under
    jax.grad — callers index ``[0]`` outside the shard_map instead.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    # one branch per pipeline stage, one stage per device on the axis:
    # the branch count IS the axis size, and it is static (the scan
    # length below needs a python int; jax 0.4.x has no lax.axis_size)
    n = len(branches)
    sid = lax.axis_index(axis_name)
    m = x_stack.shape[0]
    if params_stack.shape[0] == 1:
        row = params_stack[0]            # pre-sharded: this stage's row
    else:
        row = lax.dynamic_index_in_dim(  # replicated: select by stage id
            params_stack, sid, 0, keepdims=False)
    shift = [(i, (i + 1) % n) for i in range(n)]

    def run_stage(x_t, mb):
        fns = [jax.checkpoint(f) if remat else f for f in branches]
        return lax.switch(sid, fns, row, x_t, mb)

    def tick(carry, t):
        inbuf, loss_acc = carry
        mb = t - sid
        active = (mb >= 0) & (mb < m)
        x_t = jnp.where(sid == 0, x_stack[jnp.clip(t, 0, m - 1)], inbuf)
        y, loss_c = run_stage(x_t, jnp.clip(mb, 0, m - 1))
        y = jnp.where(active, y, jnp.zeros_like(y))
        loss_acc = loss_acc + jnp.where(active, loss_c,
                                        jnp.zeros_like(loss_c))
        nxt = lax.ppermute(y, axis_name, shift)
        return (nxt, loss_acc), None

    inbuf0 = jnp.zeros_like(x_stack[0])
    (_, loss), _ = lax.scan(tick,
                            (inbuf0, jnp.zeros((1,), jnp.float32)),
                            jnp.arange(m + n - 1))
    return loss
