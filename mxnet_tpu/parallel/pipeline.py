"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh.

The reference's model parallelism assigns whole layers to devices and
runs them sequentially per batch (`example/model-parallel-lstm/
lstm.py:142-205` — each LSTM layer on its own GPU, overlap only from
async engine dispatch).  This module is the compiled TPU-native
successor: the layer stack is sharded over a ``pipe`` mesh axis, the
batch is split into microbatches, and ONE jitted SPMD program streams
activations stage-to-stage over the ICI ring (`lax.ppermute` inside
`shard_map`), so all stages compute concurrently after the fill phase.
Gradients come from `jax.grad` straight through the schedule — the
backward pass replays it in reverse (GPipe semantics; per-microbatch
`jax.checkpoint` keeps activation memory at O(microbatch)).

Scope: uniform stages — every stage maps (microbatch, ...) -> the same
shape (layer stacks: RNN/transformer layers, repeated blocks).  The
stage parameters are stacked on a leading axis sharded over ``pipe``.
"""
from __future__ import annotations

import functools

__all__ = ["pipeline_apply", "pipeline_grad", "make_pipeline_mesh"]


def make_pipeline_mesh(n_stages, devices=None):
    """1-D mesh with a ``pipe`` axis of n_stages devices."""
    from .mesh import make_1d_mesh
    return make_1d_mesh("pipe", n_stages, devices)


def _stage_loop(stage_fn, params_stack, x_stack, axis_name, remat):
    """Per-device body under shard_map.

    params_stack: (1, ...) this device's stage params (leading stage axis
    sharded to size 1).  x_stack: (M, B_u, ...) all microbatches,
    replicated.  Returns (M, B_u, ...) outputs of the LAST stage
    (garbage on other devices; caller slices stage S-1's shard).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    n = lax.axis_size(axis_name)
    sid = lax.axis_index(axis_name)
    m = x_stack.shape[0]
    params = jax.tree.map(lambda p: p[0], params_stack)
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    shift = [(i, (i + 1) % n) for i in range(n)]  # stage s -> s+1

    def tick(carry, t):
        # carry: (inbuf, outputs)
        #   inbuf: (B_u, ...) the activation this stage consumes this tick
        #   outputs: (M, B_u, ...) last-stage results by microbatch
        inbuf, outputs = carry
        # stage 0 reads microbatch t from the input stream; others read
        # what the previous stage sent last tick
        x_t = jnp.where(sid == 0,
                        x_stack[jnp.clip(t, 0, m - 1)], inbuf)
        # active when microbatch (t - sid) is in range
        mb = t - sid
        active = (mb >= 0) & (mb < m)
        y = fn(params, x_t)
        y = jnp.where(active, y, jnp.zeros_like(y))
        # the last stage stores its result; everyone else forwards it
        outputs = jnp.where(
            (sid == n - 1) & active,
            outputs.at[jnp.clip(mb, 0, m - 1)].set(y), outputs)
        nxt = lax.ppermute(y, axis_name, shift)
        return (nxt, outputs), None

    inbuf0 = jnp.zeros_like(x_stack[0])
    outputs0 = jnp.zeros_like(x_stack)
    (_, outputs), _ = lax.scan(tick, (inbuf0, outputs0),
                               jnp.arange(m + n - 1))
    return outputs


def pipeline_apply(stage_fn, params_stack, x, mesh, microbatches,
                   remat=True):
    """Run ``x`` through ``n_stages`` pipelined applications of
    ``stage_fn`` (one stage per device on the mesh's ``pipe`` axis).

    stage_fn(params, x_micro) -> y_micro with y.shape == x.shape (uniform
    stages).  params_stack: pytree whose leaves have a leading stage axis
    of size n_stages.  x: (batch, ...), split into ``microbatches`` equal
    chunks.  Returns (batch, ...) outputs of the final stage, replicated.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.devices.size
    b = x.shape[0]
    if b % microbatches:
        raise ValueError("batch %d not divisible into %d microbatches"
                         % (b, microbatches))
    x_stack = x.reshape((microbatches, b // microbatches) + x.shape[1:])

    body = functools.partial(_stage_loop, stage_fn, axis_name="pipe",
                             remat=remat)
    out = shard_map(
        lambda p, xs: jax.lax.psum(body(p, xs), "pipe"),
        mesh=mesh,
        in_specs=(P("pipe"), P()),
        out_specs=P(),
        check_rep=False,
    )(params_stack, x_stack)
    # only the last stage contributed nonzeros; psum replicates its result
    return out.reshape((b,) + out.shape[2:])


def pipeline_grad(loss_fn, stage_fn, params_stack, x, labels, mesh,
                  microbatches, remat=True):
    """(loss, grads) of ``loss_fn(pipeline(x), labels)`` w.r.t. the
    stacked stage params — jax.grad runs the schedule in reverse
    (ppermute transposes to the opposite ring direction)."""
    import jax

    def full(p):
        y = pipeline_apply(stage_fn, p, x, mesh, microbatches, remat=remat)
        return loss_fn(y, labels)

    return jax.value_and_grad(full)(params_stack)
