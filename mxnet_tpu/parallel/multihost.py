"""Multi-host (process-spanning mesh) support for the fused path.

Reference: the multi-machine training loop rides kvstore ``dist_sync`` —
each worker pushes per-key gradients to parameter servers, which
aggregate exactly ``num_workers`` pushes before workers pull
(``src/kvstore/kvstore_dist.h:192-238``,
``kvstore_dist_server.h:164-199``).  TPU-native design (SURVEY §5.8):
there are no servers and no per-key pushes — ``ShardedTrainer``'s single
jitted step runs as the SAME XLA program on every process over a
process-spanning ``jax.sharding.Mesh``, and GSPMD places the gradient
psum on the cross-process fabric (ICI within a slice, DCN across
slices) wherever the ``data`` axis spans hosts.  The multi-controller
model keeps the hot loop identical to single-host; these helpers cover
the seams jit does not:

* joining the runtime (``ensure_initialized`` — the reference's
  ``InitPSEnv`` from DMLC_* env, ``include/mxnet/kvstore.h:162``);
* staging per-process host shards into global arrays
  (``stage_local`` — the role of the worker-side send slicing,
  ``kvstore_dist.h:273-314``);
* gathering process-sharded state back to every host for rank-0
  checkpoint writes (``gather_to_host``).
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError

__all__ = ["ensure_initialized", "spans_processes", "stage_local",
           "scale_local_shape", "gather_to_host", "process_barrier"]


def ensure_initialized():
    """Join the ``jax.distributed`` job described by the MXNET_TPU_*
    env (set by ``tools/launch.py``); no-op for single-process jobs or
    when the runtime is already up.  Must run before the XLA backend is
    touched — the first eagerly-executed primitive binds it, after
    which joining is impossible."""
    import jax
    from .. import config

    nproc = config.get_int("MXNET_TPU_NUM_PROCESSES")
    if not nproc or nproc <= 1 or jax.distributed.is_initialized():
        return
    coordinator = config.get("MXNET_TPU_COORDINATOR")
    if not coordinator:
        # a silent localhost default would make every rank wait on its
        # own unbound port — fail fast instead
        raise MXNetError(
            "MXNET_TPU_NUM_PROCESSES=%d but MXNET_TPU_COORDINATOR is "
            "unset; launch via tools/launch.py or export the "
            "coordinator address" % nproc)
    kwargs = {}
    hb = config.get_int("MXNET_TPU_HEARTBEAT_TIMEOUT")
    if hb:
        # failure detection: a dead peer is declared failed after this
        # many seconds without heartbeats (the reference's ps-lite
        # heartbeat role, kvstore_dist.h:159-169); default 100 s
        kwargs["heartbeat_timeout_seconds"] = hb
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=nproc,
        process_id=config.get_int("MXNET_TPU_PROCESS_ID", 0),
        **kwargs)


def spans_processes(mesh):
    """True when the mesh's devices live in more than one process."""
    return len({d.process_index for d in mesh.devices.flat}) > 1


def stage_local(sharding, local, global_shape=None):
    """Build a global array on a process-spanning mesh from this
    process's host data.

    ``local`` is either the full global value (identical on every
    process — parameters, optimizer slots) or this process's contiguous
    shard of a process-sharded dimension (batches).  ``global_shape``
    defaults to ``local.shape`` (the full-value case)."""
    import jax
    local = np.asarray(local)
    return jax.make_array_from_process_local_data(
        sharding, local, tuple(global_shape or local.shape))


def scale_local_shape(sharding, local_shape):
    """Global shape implied by a per-process local shard under a
    NamedSharding: every dimension sharded over process-spanning mesh
    axes scales by the number of distinct processes along those axes
    (so partial tail batches keep working — the global batch dim follows
    the local one instead of the configured full size)."""
    mesh, spec = sharding.mesh, sharding.spec
    gshape = list(local_shape)
    for d, axes in enumerate(spec):
        if d >= len(gshape) or axes is None:
            continue
        axes = (axes,) if isinstance(axes, str) else tuple(axes)
        sub = mesh.devices[tuple(
            slice(None) if name in axes else 0
            for name in mesh.axis_names)]
        gshape[d] *= len({dev.process_index for dev in np.ravel(sub)})
    return tuple(gshape)


def gather_to_host(arr):
    """Numpy copy of a global array, identical on every process.

    Fully-addressable and fully-replicated arrays read out locally;
    process-sharded state (e.g. tensor-parallel weights on a
    process-spanning 'model' axis) is all-gathered — every process must
    call this (it is a collective in that case)."""
    if getattr(arr, "is_fully_addressable", True):
        return np.asarray(arr)
    if arr.is_fully_replicated:
        return np.asarray(arr.addressable_data(0))
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(arr, tiled=True))


def process_barrier(name="mxnet_tpu_multihost"):
    """Block until every process reaches this point (checkpoint
    write/read ordering across ranks)."""
    import jax
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(name)
