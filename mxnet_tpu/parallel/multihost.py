"""Multi-host (process-spanning mesh) support for the fused path.

Reference: the multi-machine training loop rides kvstore ``dist_sync`` —
each worker pushes per-key gradients to parameter servers, which
aggregate exactly ``num_workers`` pushes before workers pull
(``src/kvstore/kvstore_dist.h:192-238``,
``kvstore_dist_server.h:164-199``).  TPU-native design (SURVEY §5.8):
there are no servers and no per-key pushes — ``ShardedTrainer``'s single
jitted step runs as the SAME XLA program on every process over a
process-spanning ``jax.sharding.Mesh``, and GSPMD places the gradient
psum on the cross-process fabric (ICI within a slice, DCN across
slices) wherever the ``data`` axis spans hosts.  The multi-controller
model keeps the hot loop identical to single-host; these helpers cover
the seams jit does not:

* joining the runtime (``ensure_initialized`` — the reference's
  ``InitPSEnv`` from DMLC_* env, ``include/mxnet/kvstore.h:162``);
* staging per-process host shards into global arrays
  (``stage_local`` — the role of the worker-side send slicing,
  ``kvstore_dist.h:273-314``);
* gathering process-sharded state back to every host for rank-0
  checkpoint writes (``gather_to_host``).
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError

__all__ = ["ensure_initialized", "spans_processes", "stage_local",
           "scale_local_shape", "gather_to_host", "process_barrier",
           "world_size"]


def world_size():
    """Process count of the running job (1 single-process).

    Elastic contract (docs/api/reshard.md): this is the CURRENT world —
    after a rank leave/join restart, ``tools/launch.py --elastic``
    relaunches every worker with the new ``MXNET_TPU_NUM_PROCESSES``,
    :func:`ensure_initialized` joins the resized ``jax.distributed``
    job under the same ``MXNET_TPU_INIT_TIMEOUT``/``_RETRIES`` bounds,
    and checkpoint loaders compare this value against the manifest's
    saved world to emit ``rank_join``/``rank_leave`` events."""
    import jax
    try:
        return int(jax.process_count())
    except (RuntimeError, ValueError):
        return 1


def _distributed_initialized():
    """True when this process already joined a jax.distributed job.
    ``jax.distributed.is_initialized`` only exists on newer jax; fall
    back to the runtime state object older versions expose."""
    import jax
    fn = getattr(jax.distributed, "is_initialized", None)
    if fn is not None:
        return bool(fn())
    try:
        from jax._src import distributed as _dist
        return getattr(_dist.global_state, "client", None) is not None
    except ImportError:  # pragma: no cover - very old jax
        return False


def ensure_initialized():
    """Join the ``jax.distributed`` job described by the MXNET_TPU_*
    env (set by ``tools/launch.py``); no-op for single-process jobs or
    when the runtime is already up.  Must run before the XLA backend is
    touched — the first eagerly-executed primitive binds it, after
    which joining is impossible.

    Resilience: the join is bounded by ``MXNET_TPU_INIT_TIMEOUT``
    seconds (0/unset = the runtime's own timeout); transient connect
    failures are retried with exponential backoff up to
    ``MXNET_TPU_INIT_RETRIES`` times (default 2) — a coordinator that
    is still binding its port when a fast rank arrives no longer kills
    the whole job.  A TIMED-OUT join is terminal (see the retry_call
    below).  The ``multihost.init`` fault seam (resilience.py) fires
    inside the retried attempt."""
    import jax
    from .. import config
    from .. import resilience

    nproc = config.get_int("MXNET_TPU_NUM_PROCESSES")
    need_init = bool(nproc and nproc > 1
                     and not _distributed_initialized())
    coordinator = config.get("MXNET_TPU_COORDINATOR")
    if need_init and not coordinator:
        # a config error never heals — fail fast OUTSIDE the retry (a
        # silent localhost default would make every rank wait on its
        # own unbound port)
        raise MXNetError(
            "MXNET_TPU_NUM_PROCESSES=%d but MXNET_TPU_COORDINATOR is "
            "unset; launch via tools/launch.py or export the "
            "coordinator address" % nproc)
    import inspect
    kwargs = {}
    accepted = inspect.signature(jax.distributed.initialize).parameters
    hb = config.get_int("MXNET_TPU_HEARTBEAT_TIMEOUT")
    if hb and "heartbeat_timeout_seconds" in accepted:
        # failure detection: a dead peer is declared failed after this
        # many seconds without heartbeats (the reference's ps-lite
        # heartbeat role, kvstore_dist.h:159-169); default 100 s.
        # Older jax has no such kwarg — the env is then only consumed
        # by the launch.py watchdog.
        kwargs["heartbeat_timeout_seconds"] = hb
    timeout = config.get_int("MXNET_TPU_INIT_TIMEOUT")
    if timeout and "initialization_timeout" in accepted:
        kwargs["initialization_timeout"] = timeout

    def attempt():
        resilience.fault_point("multihost.init")
        if not need_init or _distributed_initialized():
            return
        resilience.with_timeout(
            lambda: jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=nproc,
                process_id=config.get_int("MXNET_TPU_PROCESS_ID", 0),
                **kwargs),
            timeout or None,
            name="jax.distributed.initialize(%s)" % coordinator)

    # a TIMED-OUT initialize is terminal, not retried: its daemon
    # thread is still inside the coordinator handshake, and a second
    # concurrent initialize from this process could double-register the
    # rank.  Transient pre-connect failures (coordinator still binding
    # its port) are the retryable class.
    resilience.retry_call(
        attempt,
        retries=config.get_int("MXNET_TPU_INIT_RETRIES", "2"),
        exceptions=(resilience.FaultInjected, RuntimeError,
                    ConnectionError, OSError),
        no_retry=(resilience.TimeoutError,),
        base_delay=0.2, max_delay=5.0,
        name="multihost.ensure_initialized")


def spans_processes(mesh):
    """True when the mesh's devices live in more than one process."""
    return len({d.process_index for d in mesh.devices.flat}) > 1


def stage_local(sharding, local, global_shape=None):
    """Build a global array on a process-spanning mesh from this
    process's host data.

    ``local`` is either the full global value (identical on every
    process — parameters, optimizer slots) or this process's contiguous
    shard of a process-sharded dimension (batches).  ``global_shape``
    defaults to ``local.shape`` (the full-value case)."""
    import jax
    local = np.asarray(local)
    return jax.make_array_from_process_local_data(
        sharding, local, tuple(global_shape or local.shape))


def scale_local_shape(sharding, local_shape):
    """Global shape implied by a per-process local shard under a
    NamedSharding: every dimension sharded over process-spanning mesh
    axes scales by the number of distinct processes along those axes
    (so partial tail batches keep working — the global batch dim follows
    the local one instead of the configured full size)."""
    mesh, spec = sharding.mesh, sharding.spec
    gshape = list(local_shape)
    for d, axes in enumerate(spec):
        if d >= len(gshape) or axes is None:
            continue
        axes = (axes,) if isinstance(axes, str) else tuple(axes)
        sub = mesh.devices[tuple(
            slice(None) if name in axes else 0
            for name in mesh.axis_names)]
        gshape[d] *= len({dev.process_index for dev in np.ravel(sub)})
    return tuple(gshape)


def gather_to_host(arr):
    """Numpy copy of a global array, identical on every process.

    Fully-addressable and fully-replicated arrays read out locally;
    process-sharded state (e.g. tensor-parallel weights on a
    process-spanning 'model' axis) is all-gathered — every process must
    call this (it is a collective in that case)."""
    if getattr(arr, "is_fully_addressable", True):
        return np.asarray(arr)
    if arr.is_fully_replicated:
        return np.asarray(arr.addressable_data(0))
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(arr, tiled=True))


# first sync_global_devices compiles its collective program; that call's
# wall time must not land in the wait histogram (see attempt() below)
_barrier_state = {"warm": False}


def process_barrier(name="mxnet_tpu_multihost"):
    """Block until every process reaches this point (checkpoint
    write/read ordering across ranks).

    Resilience: with ``MXNET_TPU_BARRIER_TIMEOUT`` set (seconds), the
    sync is bounded: a TIMEOUT is terminal and raises
    :class:`~mxnet_tpu.base.MXNetError` naming the barrier — the
    dead-rank detector for rendezvous points, instead of an unbounded
    hang against a preempted peer.  (A timed-out collective is NOT
    retried: the hung attempt's thread is still parked inside it, and
    re-entering the same barrier from a second thread of this process
    would corrupt the rendezvous.)  Transient pre-collective failures —
    including the ``multihost.barrier`` fault seam — are retried up to
    ``MXNET_TPU_BARRIER_RETRIES`` times (default 1) with backoff.
    0/unset keeps the previous wait-forever behavior."""
    import jax
    from .. import config
    from .. import resilience

    timeout = config.get_int("MXNET_TPU_BARRIER_TIMEOUT") or None

    def attempt():
        resilience.fault_point("multihost.barrier")
        if jax.process_count() > 1:
            import time as _time
            from jax.experimental import multihost_utils
            t0 = _time.perf_counter()
            resilience.with_timeout(
                lambda: multihost_utils.sync_global_devices(name),
                timeout, name="process_barrier(%r)" % name)
            # the barrier IS a collective wait: how long this rank
            # stalled for its slowest peer (straggler attribution,
            # telemetry.distview) — except the process's FIRST barrier,
            # whose duration is dominated by the sync program's XLA
            # compile, not peer wait (same warm-up rule as distview's
            # timestamp barrier)
            if _barrier_state["warm"]:
                from ..telemetry.registry import histogram
                histogram("mxtpu_collective_wait_seconds").observe(
                    _time.perf_counter() - t0)
            else:
                _barrier_state["warm"] = True

    resilience.retry_call(
        attempt,
        retries=config.get_int("MXNET_TPU_BARRIER_RETRIES", "1"),
        exceptions=(resilience.FaultInjected,),
        base_delay=0.1, max_delay=2.0,
        name="process_barrier(%r)" % name)
