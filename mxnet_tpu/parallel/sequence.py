"""Sequence/context parallelism: ring attention over a device mesh.

The reference (2017-era) has no attention ops; its long-sequence story is
bucketing (SURVEY §5.7).  This module is the TPU-native long-context
capability the new framework treats as first-class: sequence-sharded
attention where K/V blocks rotate around the ICI ring (``lax.ppermute``)
while each device holds its Q shard — HBM use per device is O(T/n), and
compute overlaps the neighbor transfer (Ring Attention; flash-style online
softmax keeps the accumulation numerically stable).

Layouts: q/k/v are (batch, seq, heads, head_dim), sharded along ``seq``.
"""
from __future__ import annotations

import functools
import math

import numpy as np

__all__ = ["ring_attention", "attention_reference", "sequence_parallel",
           "active_context"]

# trace-time routing for the _contrib_RingAttention operator: when a
# (mesh, axis) context is active, the op runs the sequence-parallel ring
# schedule; otherwise it falls back to single-device attention — one
# Symbol serves both deployments (ops/pallas_kernels.py ring_attention_op)
_ACTIVE = None


class sequence_parallel:
    """Context manager activating sequence-parallel attention for ops
    traced within; ``mesh=None`` deactivates (single-device fallback).
    ``batch_axis`` names the mesh axis the batch dim is sharded over
    (None = replicated), so dp x sp composition shards both dims."""

    def __init__(self, mesh, axis="model", batch_axis="data"):
        self.ctx = (mesh, axis, batch_axis) if mesh is not None else None

    def __enter__(self):
        global _ACTIVE
        self._prev = _ACTIVE
        _ACTIVE = self.ctx
        return self

    def __exit__(self, *exc):
        global _ACTIVE
        _ACTIVE = self._prev


def active_context():
    return _ACTIVE


def attention_reference(q, k, v, causal=False):
    """Single-device softmax attention (the correctness oracle)."""
    import jax.numpy as jnp
    scale = 1.0 / math.sqrt(q.shape[-1])
    # (B, H, Tq, Tk)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool))
        s = jnp.where(mask, s, -jnp.inf)
    p = jnp.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _ring_attention_local(q, k, v, axis_name, causal, n=1):
    """Per-shard body under shard_map: rotate K/V around the ring.
    ``n`` is the ring size, threaded in statically (the scan length and
    the ppermute ring need python ints; jax 0.4.x has no lax.axis_size).
    """
    import jax.numpy as jnp
    from jax import lax

    my_idx = lax.axis_index(axis_name)
    t_local = q.shape[1]
    scale = 1.0 / math.sqrt(q.shape[-1])
    perm = [(i, (i + 1) % n) for i in range(n)]  # ring: pass to neighbor

    b, _, h, d = q.shape
    o = jnp.zeros((b, t_local, h, d), jnp.float32)
    l = jnp.zeros((b, h, t_local), jnp.float32)       # softmax denominator
    m = jnp.full((b, h, t_local), -jnp.inf, jnp.float32)  # running max

    q_pos = my_idx * t_local + jnp.arange(t_local)

    def step(carry, step_idx):
        o, l, m, k_blk, v_blk = carry
        src_idx = (my_idx - step_idx) % n          # whose block we hold
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            k_pos = src_idx * t_local + jnp.arange(t_local)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, -jnp.inf)
        m_new = jnp.maximum(m, s.max(-1))
        # guard fully-masked rows (exp(-inf - -inf))
        safe_m = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(s - safe_m[..., None])
        if causal:
            p = jnp.where(mask[None, None], p, 0.0)
        corr = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - safe_m))
        l_new = l * corr + p.sum(-1)
        o_new = o * corr.transpose(0, 2, 1)[..., None] + jnp.einsum(
            "bhqk,bkhd->bqhd", p, v_blk.astype(jnp.float32))
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        return (o_new, l_new, m_new, k_next, v_next), None

    (o, l, m, _, _), _ = lax.scan(step, (o, l, m, k, v), jnp.arange(n))
    denom = jnp.where(l == 0.0, 1.0, l)
    out = o / denom.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh, seq_axis="data", causal=False,
                   batch_axis=None):
    """Sequence-parallel attention.

    q/k/v: (batch, seq, heads, head_dim) with ``seq`` sharded over
    ``seq_axis`` of ``mesh`` (and optionally batch over ``batch_axis``
    for dp x sp composition — otherwise a dp mesh would all-gather the
    batch at the shard_map boundary and duplicate attention work per
    data shard).  Returns the attention output with the same sharding.
    K/V blocks ride the ICI ring; each of the n steps computes a
    (T/n × T/n) block and the online softmax merges it.
    """
    from jax.sharding import PartitionSpec as P
    from .mesh import shard_map_nocheck

    spec = P(batch_axis, seq_axis, None, None)
    body = functools.partial(_ring_attention_local, axis_name=seq_axis,
                             causal=causal,
                             n=int(mesh.shape[seq_axis]))
    # replication checking is off (shard_map_nocheck): the online-softmax
    # accumulators are device-varying from step 0 and jax 0.4.x has no
    # pcast/pbroadcast surface to declare it
    f = shard_map_nocheck(body, mesh, (spec, spec, spec), spec)
    return f(q, k, v)
