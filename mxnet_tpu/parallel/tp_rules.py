"""Automatic tensor-parallel sharding rules for Symbol graphs.

The reference has no tensor parallelism (SURVEY §2.4); the TPU-native
design is GSPMD sharding annotations: ``tp_rules`` maps parameter names
to the weight axis sharded over the mesh 'model' axis, and XLA inserts
the all-gathers/reduce-scatters.  ANY rule set is numerically correct —
GSPMD reshards as needed — so the job of this module is to derive the
COMMUNICATION-EFFICIENT rules a user would hand-write:

* Megatron-style pairing (arXiv:1909.08053): a FullyConnected whose
  output feeds (through elementwise/attention-shaped ops) another
  FullyConnected is column-parallel (weight axis 0, the output dim) and
  its partner row-parallel (weight axis 1, the input dim) — one psum
  per block instead of per-layer all-gathers.  Covers transformer
  QKV -> attention -> out-proj and ff1 -> act -> ff2 chains.
* Convolutions shard output channels (OIHW axis 0) when divisible —
  activations stay channel-sharded through elementwise/BN chains.
* Classifier-style standalone FC weights stay column-parallel (the
  round-2 default rule).

Bias/beta-style vectors follow their column-parallel owner (axis 0);
row-parallel owners keep replicated biases (they add after the psum).
"""
from __future__ import annotations

__all__ = ["derive_tp_rules"]

# ops a sharded activation flows through without changing which FC pair
# should be row-parallel: elementwise-ish, attention-shaped, dropout
_PASS_OPS = frozenset({
    "Activation", "LeakyReLU", "Dropout", "identity", "_copy",
    "softmax", "log_softmax", "SoftmaxActivation", "slice_axis",
    "batch_dot", "elemwise_mul", "_mul", "_mul_scalar", "_div_scalar",
    "_plus_scalar", "_minus_scalar", "broadcast_mul", "negative",
    "clip", "expand_dims", "squeeze", "SwapAxis", "transpose",
})


def _weight_of(node):
    """(weight_name, bias_name | None) for FullyConnected/Convolution."""
    names = [src.name for (src, _i) in node.inputs if src.is_variable]
    w = next((n for n in names if n.endswith("_weight")), None)
    b = next((n for n in names if n.endswith("_bias")), None)
    return w, b


def derive_tp_rules(topo, arg_shapes, tp_size, min_dim=8):
    """{param_name: shard_axis} over the 'model' axis for a graph.

    topo: Symbol topo order; arg_shapes: {name: shape}; tp_size: the
    mesh 'model' axis size.  Only dims divisible by tp_size and at
    least ``min_dim * tp_size`` wide are sharded.
    """
    if tp_size <= 1:
        return {}
    rules = {}
    ok = lambda d: d % tp_size == 0 and d >= min_dim * tp_size

    fc_nodes = []
    col_ids = set()    # FC nodes currently column-parallel
    for node in topo:
        if node.is_variable or node.op is None:
            continue
        opname = node.op.name
        if opname in ("FullyConnected", "Convolution"):
            w, b = _weight_of(node)
            if w is None or w not in arg_shapes:
                continue
            shp = arg_shapes[w]
            if opname == "Convolution":
                if len(shp) >= 3 and ok(shp[0]) and \
                        int(node.attrs.get("num_group", 1)) == 1:
                    rules[w] = 0
                    if b is not None and b in arg_shapes:
                        rules[b] = 0
                continue
            # FullyConnected: column-parallel by default
            if ok(shp[0]):
                rules[w] = 0
                col_ids.add(id(node))
                if b is not None and b in arg_shapes:
                    rules[b] = 0
            fc_nodes.append(node)

    # second pass: an FC whose data flows (through pass-ops) out of a
    # column-parallel FC becomes row-parallel — sharding its INPUT dim
    # consumes the column-sharded activation directly and emits one
    # psum, whether or not its own output dim was shardable
    memo = {}

    def reaches_col(node):
        """Does data flowing into ``node`` come from a column-parallel
        FC through pass-ops only?  Memoized: pass-op diamonds (gating)
        would otherwise branch exponentially."""
        r = memo.get(id(node))
        if r is not None:
            return r
        memo[id(node)] = False       # cycle/diamond guard
        out = False
        for (src, _i) in node.inputs:
            if src.is_variable or src.op is None:
                continue
            if id(src) in col_ids:
                out = True
                break
            if src.op.name in _PASS_OPS and reaches_col(src):
                out = True
                break
        memo[id(node)] = out
        return out

    for node in fc_nodes:
        w, b = _weight_of(node)
        shp = arg_shapes[w]
        if len(shp) != 2 or not ok(shp[1]) or rules.get(w) == 1:
            continue
        if reaches_col(node):
            rules[w] = 1              # row-parallel: shard input dim
            if b is not None:
                rules.pop(b, None)    # bias adds after the psum
            col_ids.discard(id(node))
            memo.clear()              # col_ids changed; recompute
    return rules
