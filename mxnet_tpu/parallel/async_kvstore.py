"""True ``dist_async``: a host-driven asynchronous parameter server.

Reference: ``src/kvstore/kvstore_dist_server.h:200-208`` — in async
mode the server applies EVERY push to the weights immediately (no
aggregation gate), and workers pull whatever the weights are at that
moment; staleness is the accepted price for never blocking on peers.
The TPU-native sync path (one jitted psum) replaces dist_sync, but
async has no collective analogue BY CONSTRUCTION — collectives are
globally synchronous — so this module keeps the reference's host-side
architecture: a parameter-server thread in the rank-0 process, workers
pushing/pulling numpy tensors over TCP, the optimizer running
server-side per push (``set_optimizer`` ships a pickled optimizer,
exactly the reference's pickled-command protocol,
``python/mxnet/kvstore.py:226-270``).  Gradients never touch the
accelerator on this path — it is a host protocol, as in the reference.

Wire format: 8-byte big-endian length + pickle.  One persistent
connection per worker; the server runs one thread per connection and
serializes updates with a lock (the reference server is also a single
consumer per key, kvstore_dist_server.h ``exec_``).
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import time

import numpy as np

from ..base import MXNetError
from .. import telemetry
from ..kvstore import KVStore, _ctype_key_value, _group_kv_pairs

__all__ = ["AsyncKVStore", "ParameterServer"]

# push/pull byte children come bound from KVStore.__init__
# (store="dist_async"); only the in-flight gauge is module-level
_PENDING = telemetry.gauge("mxtpu_kvstore_pending_async")


def _send_msg(sock, obj):
    payload = pickle.dumps(obj, protocol=4)
    sock.sendall(struct.pack(">Q", len(payload)) + payload)


def _recv_msg(sock):
    hdr = b""
    while len(hdr) < 8:
        chunk = sock.recv(8 - len(hdr))
        if not chunk:
            raise ConnectionError("peer closed")
        hdr += chunk
    (n,) = struct.unpack(">Q", hdr)
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed mid-message")
        buf += chunk
    return pickle.loads(bytes(buf))


class ParameterServer:
    """The server role (runs as a thread inside the rank-0 process)."""

    def __init__(self, num_workers, port, host="0.0.0.0"):
        self.num_workers = num_workers
        self._store = {}
        self._updater = None
        self._updater_obj = None
        self._lock = threading.Lock()
        self.update_count = 0
        self._barrier_gen = 0
        self._barrier_count = 0
        self._barrier_cv = threading.Condition()
        self._byes = 0
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            self._listener.bind((host, port))
        except OSError as e:
            raise MXNetError(
                "dist_async parameter server cannot bind %s:%d (%s) — "
                "set MXNET_TPU_ASYNC_PORT to a free port"
                % (host, port, e)) from e
        self._listener.listen(num_workers + 1)
        self._threads = []
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    def _accept_loop(self):
        for _ in range(self.num_workers):
            conn, _addr = self._listener.accept()
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)
        self._listener.close()

    def _serve(self, conn):
        msg = ("<recv>",)  # so the fault-report path below can never NameError
        try:
            while True:
                msg = _recv_msg(conn)
                op = msg[0]
                if op == "init":
                    from .. import ndarray as _nd
                    _key, val = msg[1], msg[2]
                    with self._lock:
                        # first writer wins (every worker inits); the
                        # store holds NDArrays — updaters/optimizers
                        # expect the NDArray surface (context, state
                        # creation), exactly as on the reference server
                        self._store.setdefault(_key, _nd.array(val))
                    _send_msg(conn, ("ok",))
                elif op == "push":
                    from .. import ndarray as _nd
                    _key, grad = msg[1], msg[2]
                    with self._lock:
                        if _key not in self._store:
                            _send_msg(conn, ("err",
                                             "key %r not inited" % _key))
                            continue
                        # ASYNC CONTRACT: applied immediately, per push
                        if self._updater is not None:
                            self._updater(_key, _nd.array(grad),
                                          self._store[_key])
                        else:
                            # no updater installed: assign, matching the
                            # facade's assign-vs-updater contract
                            self._store[_key] = _nd.array(grad)
                        self.update_count += 1
                    _send_msg(conn, ("ok",))
                elif op == "pull":
                    with self._lock:
                        if msg[1] not in self._store:
                            _send_msg(conn, ("err",
                                             "key %r not inited" % (msg[1],)))
                            continue
                        val = self._store[msg[1]].asnumpy()
                    _send_msg(conn, ("val", val))
                elif op == "set_optimizer":
                    from .. import optimizer as opt_mod
                    optimizer = pickle.loads(msg[1])
                    with self._lock:
                        # idempotent across workers: one shared updater
                        if self._updater is None:
                            self._updater_obj = opt_mod.get_updater(
                                optimizer)
                            self._updater = self._updater_obj
                    _send_msg(conn, ("ok",))
                elif op == "barrier":
                    with self._barrier_cv:
                        gen = self._barrier_gen
                        self._barrier_count += 1
                        if self._barrier_count == self.num_workers:
                            self._barrier_count = 0
                            self._barrier_gen += 1
                            self._barrier_cv.notify_all()
                        else:
                            while self._barrier_gen == gen:
                                self._barrier_cv.wait()
                    _send_msg(conn, ("ok",))
                elif op == "stats":
                    with self._lock:
                        _send_msg(conn, ("val",
                                         {"updates": self.update_count,
                                          "keys": len(self._store)}))
                elif op == "opt_states":
                    with self._lock:
                        st = (self._updater_obj.get_states()
                              if self._updater_obj is not None else b"")
                    _send_msg(conn, ("val", st))
                elif op == "set_opt_states":
                    with self._lock:
                        if self._updater_obj is None:
                            _send_msg(conn, ("err", "set_optimizer must "
                                             "run before state restore"))
                            continue
                        self._updater_obj.set_states(msg[1])
                    _send_msg(conn, ("ok",))
                elif op == "bye":
                    _send_msg(conn, ("ok",))
                    with self._lock:
                        self._byes += 1
                    return
                else:
                    _send_msg(conn, ("err", "unknown op %r" % (op,)))
        except (ConnectionError, OSError):
            return
        except Exception as e:  # mxlint: allow-broad-except(server loop must survive any handler fault; the error is sent to the worker)
            try:
                _send_msg(conn, ("err", "server error on %r: %r"
                                 % (msg[:1], e)))
            except (ConnectionError, OSError):
                pass
            return
        finally:
            conn.close()


class AsyncKVStore(KVStore):
    """Worker-side ``dist_async`` client (reference kvstore_dist.h
    worker role under ``--launcher`` env, without the sync gate).

    Multi-server sharding (reference ``kvstore_dist.h:273-314``
    ``EncodeKey``): ``MXNET_TPU_NUM_SERVERS`` (default 1) parameter
    servers run inside the first N worker processes.  Small keys hash
    to one server; arrays above ``MXNET_KVSTORE_BIGARRAY_BOUND``
    elements (reference env var, default 1e6) are sliced into
    near-equal contiguous flat ranges, one per server, so no single
    server carries a whole big tensor or its push traffic.
    """

    def __init__(self, kv_type="dist_async"):
        super().__init__(kv_type)
        from .. import config

        self._rank = config.get_int("MXNET_TPU_PROCESS_ID", 0)
        self._num_workers = config.get_int("MXNET_TPU_NUM_PROCESSES", 1)
        coordinator = config.get("MXNET_TPU_COORDINATOR") or \
            "127.0.0.1:8431"
        host, cport = coordinator.rsplit(":", 1)
        port = config.get_int("MXNET_TPU_ASYNC_PORT") or int(cport) + 1
        nserv = config.get_int("MXNET_TPU_NUM_SERVERS", 1)
        if nserv < 1 or nserv > self._num_workers:
            raise MXNetError(
                "MXNET_TPU_NUM_SERVERS=%d must be in [1, num_workers=%d]"
                " (servers run inside the first N worker processes)"
                % (nserv, self._num_workers))
        self._num_servers = nserv
        self._big_bound = config.get_int(
            "MXNET_KVSTORE_BIGARRAY_BOUND", 1000 * 1000)
        hosts_env = config.get("MXNET_TPU_SERVER_HOSTS")
        server_hosts = (hosts_env.split(",") if hosts_env
                        else [host] * nserv)
        if len(server_hosts) != nserv:
            raise MXNetError("MXNET_TPU_SERVER_HOSTS lists %d hosts for "
                             "%d servers" % (len(server_hosts), nserv))
        self._server = None
        if self._rank < nserv:
            self._server = ParameterServer(self._num_workers,
                                           port + self._rank,
                                           host="0.0.0.0")
        self._socks = [self._connect(h, port + i)
                       for i, h in enumerate(server_hosts)]
        self._sock = self._socks[0]  # back-compat alias
        self._plans = {}             # key -> None (small) | [(lo, hi)]*S
        self._push_pool = None       # lazy single sender thread
        self._bucket_queue = None    # lazy overlap.BucketQueue

    @staticmethod
    def _connect(host, port, timeout=60.0):
        deadline = time.time() + timeout
        while True:
            try:
                s = socket.create_connection((host, port), timeout=5)
                # blocking RPCs (barrier waits on the slowest worker —
                # the point of async mode) must not inherit the connect
                # timeout
                s.settimeout(None)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                return s
            except OSError:
                if time.time() > deadline:
                    raise MXNetError(
                        "dist_async: cannot reach the parameter server "
                        "at %s:%d (rank 0 hosts it; launch via "
                        "tools/launch.py)" % (host, port))
                time.sleep(0.2)

    def _rpc_to(self, sidx, *msg):
        sock = self._socks[sidx]
        # in-flight depth: the async contract means a slow server shows
        # up as this gauge sticking above 0, not as a training stall
        _PENDING.inc()
        try:
            _send_msg(sock, msg)
            resp = _recv_msg(sock)
        finally:
            _PENDING.dec()
        if resp[0] == "err":
            telemetry.flight.record("kvstore", op="rpc_error",
                                    store="dist_async", server=int(sidx),
                                    message=str(resp[1])[:500])
            raise MXNetError("dist_async server %d: %s" % (sidx, resp[1]))
        return resp[1] if len(resp) > 1 else None

    def _rpc(self, *msg):
        return self._rpc_to(0, *msg)

    def _rpc_all(self, *msg):
        return [self._rpc_to(i, *msg) for i in range(self._num_servers)]

    # --------------------------------------------------- key sharding
    def _server_of(self, key):
        import zlib
        return zlib.crc32(str(key).encode()) % self._num_servers

    def _plan_of(self, key, size):
        """None for hash-routed small keys; a list of S contiguous flat
        ranges [lo, hi) for arrays above the bigarray bound (reference
        EncodeKey slicing, kvstore_dist.h:273-314)."""
        plan = self._plans.get(key, "?")
        if plan != "?":
            return plan
        if self._num_servers == 1 or size <= self._big_bound:
            plan = None
        else:
            S = self._num_servers
            edges = [size * i // S for i in range(S + 1)]
            plan = [(edges[i], edges[i + 1]) for i in range(S)]
        self._plans[key] = plan
        return plan

    # ------------------------------------------------------------------ api
    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._num_workers

    def init(self, key, value):
        keys, vals = _ctype_key_value(key, value)
        for k, v in zip(keys, vals):
            arr = v.asnumpy()
            plan = self._plan_of(k, arr.size)
            if plan is None:
                self._rpc_to(self._server_of(k), "init", k, arr)
            else:
                flat = arr.reshape(-1)
                for i, (lo, hi) in enumerate(plan):
                    self._rpc_to(i, "init", "%s#%d" % (k, i), flat[lo:hi])

    def _send_push(self, k, merged):
        """Wire one merged gradient to its server(s) — the per-key
        protocol shared by the synchronous push and the bucketed
        sender thread (sockets are serialized either way: a single
        caller, or the single worker of the push pool)."""
        plan = self._plan_of(k, merged.size)
        if plan is None:
            self._rpc_to(self._server_of(k), "push", k, merged)
        else:
            flat = merged.reshape(-1)
            for i, (lo, hi) in enumerate(plan):
                self._rpc_to(i, "push", "%s#%d" % (k, i), flat[lo:hi])

    def push(self, key, value, priority=0):
        keys, vals = _ctype_key_value(key, value)
        uniq, grouped = _group_kv_pairs(keys, vals)
        for k, group in zip(uniq, grouped):
            merged = group[0].asnumpy()
            for other in group[1:]:
                merged = merged + other.asnumpy()
            self._push_bytes.inc(merged.nbytes)
            self._send_push(k, merged)

    # ------------------------------------------- bucketed overlap path
    @property
    def overlap_active(self):
        """Bucketed pushes (parallel/overlap.py, MXNET_TPU_OVERLAP):
        the RPC round trips — the async path's per-key latency — move
        onto a background sender thread, overlapping the rest of
        gradient production; :meth:`drain` is the ack point before the
        weight pulls."""
        from . import overlap as _overlap
        return _overlap.overlap_enabled()

    def _launch_push_bucket(self, bucket):
        """BucketQueue reduce_fn: ship one bucket's pushes on the
        single sender thread (one worker — the per-server sockets are
        not concurrency-safe and the server applies updates per push
        in arrival order anyway).  The handle joins the send; async
        semantics mean there is no reduced value to hand back."""
        import concurrent.futures

        if self._push_pool is None:
            self._push_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="mxtpu-async-push")

        def send(items=tuple(bucket.items())):
            for k, merged in items:
                self._send_push(k, merged)

        fut = self._push_pool.submit(send)

        def handle():
            fut.result()
            return {}
        return handle

    def push_bucketed(self, key, value, priority=0):
        """Merge local replicas and buffer into size-targeted buckets;
        full buckets ship on the sender thread immediately.  Updates
        still apply server-side per push (the dist_async contract) —
        nothing is applied locally at :meth:`drain`."""
        from . import overlap as _overlap
        if self._bucket_queue is None:
            self._bucket_queue = _overlap.BucketQueue(
                self._launch_push_bucket, site="kvstore.async_push",
                skew_probe=lambda: None)
        keys, vals = _ctype_key_value(key, value)
        uniq, grouped = _group_kv_pairs(keys, vals)
        for k, group in zip(uniq, grouped):
            merged = group[0].asnumpy()
            for other in group[1:]:
                merged = merged + other.asnumpy()
            self._push_bytes.inc(merged.nbytes)
            self._bucket_queue.push(k, merged, merged.nbytes)

    def drain(self):
        """Ship the remaining buckets and join every in-flight send —
        the ordering point that keeps push-before-pull semantics for
        the Module update path.  No-op when nothing was pushed."""
        if self._bucket_queue is None or not self._bucket_queue.pending:
            return
        self._bucket_queue.drain()

    def pull(self, key, out=None, priority=0):
        assert out is not None
        # join any in-flight bucketed sends first: per-worker
        # push-then-pull ordering, and the sender thread must not
        # share a socket with this pull mid-message
        self.drain()
        keys, outs = _ctype_key_value(key, out)
        cache = {}
        for k, o in zip(keys, outs):
            if k not in cache:
                plan = self._plan_of(k, int(np.prod(o.shape)))
                if plan is None:
                    cache[k] = self._rpc_to(self._server_of(k), "pull", k)
                else:
                    parts = [self._rpc_to(i, "pull", "%s#%d" % (k, i))
                             for i in range(self._num_servers)]
                    cache[k] = np.concatenate(
                        [np.asarray(p).reshape(-1) for p in parts]
                    ).reshape(o.shape)
                self._pull_bytes.inc(np.asarray(cache[k]).nbytes)
            o[:] = cache[k]

    def set_optimizer(self, optimizer):
        # ship the optimizer to the server (reference pickled-command
        # protocol); updates happen server-side per push.  The attached
        # Symbol (attribute hints only) holds op closures — the server
        # needs the update rule, not the graph, so drop it
        import copy
        optimizer = copy.copy(optimizer)
        optimizer.sym = None
        blob = pickle.dumps(optimizer, protocol=4)
        self._rpc_all("set_optimizer", blob)

    def set_updater(self, updater):
        raise MXNetError("dist_async applies updates on the server; "
                         "use set_optimizer")

    def barrier(self):
        # every server gates on all workers, so the slowest server
        # bounds the barrier exactly once per generation
        self.drain()
        self._rpc_all("barrier")

    def server_stats(self):
        """{'updates': per-push update count, 'keys': n} — observability
        for the async contract (updates grow per push, not per round)."""
        per = self._rpc_all("stats")
        return {"updates": sum(p["updates"] for p in per),
                "keys": sum(p["keys"] for p in per),
                "per_server": per}

    def save_optimizer_states(self, fname):
        """Write SERVER-side updater states to ``fname`` (rank 0 only).

        SHARED-STORAGE CONTRACT (same as the fused path's checkpoint
        helpers): rank 0 writes the file; every rank later reads it in
        :meth:`load_optimizer_states`, so ``fname`` must live on storage
        all ranks can see (NFS, GCS fuse, single-host launch).
        """
        if self._rank != 0:
            return           # rank 0 writes; no N-way state transfer
        blobs = self._rpc_all("opt_states")
        with open(fname, "wb") as f:
            f.write(pickle.dumps({"per_server": blobs}, protocol=4))

    def load_optimizer_states(self, fname):
        # restore SERVER-side updater states (call after set_optimizer,
        # as Module.init_optimizer's preload path does).  Shared-storage
        # contract: see save_optimizer_states.
        if not os.path.exists(fname):
            from ..base import MXNetError
            raise MXNetError(
                "optimizer-states file %r not found on rank %d: "
                "save_optimizer_states writes on rank 0 only, so the "
                "path must be on storage shared by all ranks"
                % (fname, self._rank))
        with open(fname, "rb") as f:
            raw = f.read()
        try:
            blobs = pickle.loads(raw)["per_server"]
        except Exception:  # mxlint: allow-broad-except(any unpickle failure means a pre-sharding single-server file; fall back to raw)
            blobs = [raw]    # pre-sharding single-server file
        if len(blobs) != self._num_servers:
            raise MXNetError(
                "optimizer-states file holds %d server shards, job runs "
                "%d servers" % (len(blobs), self._num_servers))
        for i, b in enumerate(blobs):
            self._rpc_to(i, "set_opt_states", b)

    def close(self):
        try:
            self.drain()
        except MXNetError:
            pass          # best-effort teardown: sends may be half-dead
        for i, sock in enumerate(list(self._socks)):
            try:
                self._rpc_to(i, "bye")
                sock.close()
            except (ConnectionError, OSError, EOFError, MXNetError,
                    pickle.UnpicklingError):
                # best-effort handshake: a server dying mid-send can
                # also deliver a corrupt (unpicklable) response
                pass
        self._socks = []

    def __del__(self):
        try:
            self.close()
        except Exception:  # mxlint: allow-broad-except(__del__ at interpreter teardown must never raise)
            pass
