"""ShardedTrainer: a Symbol fused into one pjit train step.

This is the TPU-native performant path.  The reference runs forward,
backward, and optimizer as separate engine pushes with kvstore reduce in
between (SURVEY §3.1); here the whole training step — forward, vjp,
gradient collectives, optimizer update, aux-state update — is ONE
jit-compiled XLA program over a device mesh:

* batch sharded over the ``data`` axis → XLA inserts the gradient psum over
  ICI (the role of kvstore 'device', `src/kvstore/comm.h:220-385`);
* nominated weights sharded over the ``model`` axis → GSPMD tensor
  parallelism (absent in the reference, SURVEY §2.4);
* parameters are donated, so updates are in-place in HBM.

Module/Executor remain the API-parity path; bench.py and the pod-scale
training scripts use this.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..symbol import eval_graph, _classify_vars
from ..initializer import Xavier, InitDesc

__all__ = ["ShardedTrainer"]


class ShardedTrainer:
    def __init__(self, symbol, mesh, data_shapes, label_shapes=(),
                 optimizer="sgd", learning_rate=0.05, momentum=0.9,
                 weight_decay=0.0, initializer=None, dtype="float32",
                 tp_rules=None, seed=0):
        """
        symbol: loss-headed Symbol (e.g. SoftmaxOutput net).
        mesh: jax.sharding.Mesh with ('data', 'model') axes.
        data_shapes/label_shapes: dict name -> GLOBAL shape (batch dim 0).
        tp_rules: {param_name: axis_index} — weight dims to shard over the
            'model' axis.  Default: classifier-style FullyConnected weights
            whose output dim divides the tp size.
        """
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.symbol = symbol
        self.mesh = mesh
        self.lr = learning_rate
        self.momentum = momentum
        self.wd = weight_decay
        self.dtype = dtype

        self._topo = symbol._topo()
        arg_nodes, aux_nodes = _classify_vars(self._topo)
        self._arg_nodes, self._aux_nodes = arg_nodes, aux_nodes
        arg_names = [n.name for n in arg_nodes]
        self._input_names = list(data_shapes) + list(label_shapes or ())
        self._param_names = [n for n in arg_names
                             if n not in self._input_names]
        self._aux_names = [n.name for n in aux_nodes]

        shapes = dict(data_shapes)
        shapes.update(label_shapes or {})
        arg_shapes, _, aux_shapes = symbol.infer_shape(**shapes)
        self._arg_shapes = dict(zip(arg_names, arg_shapes))
        self._aux_shapes = dict(zip(self._aux_names, aux_shapes))
        batch_axis_size = next(iter(data_shapes.values()))[0]
        self._rescale = 1.0 / batch_axis_size

        # ---- init params on host, then device_put with shardings
        init = initializer or Xavier(rnd_type="gaussian", factor_type="in",
                                     magnitude=2)
        rng = np.random.RandomState(seed)
        host_params = {}
        for name in self._param_names:
            arr = _HostArray(np.zeros(self._arg_shapes[name],
                                      np.dtype(dtype)))
            try:
                init(InitDesc(name), arr)
            except Exception:
                arr.data[...] = rng.normal(
                    0, 0.01, self._arg_shapes[name]).astype(dtype)
            host_params[name] = arr.data
        host_aux = {}
        for name in self._aux_names:
            v = np.zeros(self._aux_shapes[name], np.dtype(dtype))
            if name.endswith("moving_var"):
                v[...] = 1.0
            host_aux[name] = v

        tp_size = mesh.shape.get("model", 1)
        if tp_rules is None:
            tp_rules = {}
            for name in self._param_names:
                shp = self._arg_shapes[name]
                # output-parallel sharding for large FC weights
                if (name.endswith("_weight") and len(shp) == 2 and
                        shp[0] % tp_size == 0 and shp[0] >= tp_size and
                        tp_size > 1):
                    tp_rules[name] = 0
        self.tp_rules = tp_rules

        def param_spec(name):
            shp = self._arg_shapes.get(name, self._aux_shapes.get(name))
            spec = [None] * len(shp)
            if name in tp_rules:
                spec[tp_rules[name]] = "model"
            return P(*spec)

        self._param_sharding = {
            n: NamedSharding(mesh, param_spec(n)) for n in self._param_names}
        self._aux_sharding = {
            n: NamedSharding(mesh, P(*([None] * len(self._aux_shapes[n]))))
            for n in self._aux_names}
        self._batch_sharding = {
            n: NamedSharding(
                mesh, P(*(["data"] + [None] * (len(shapes[n]) - 1))))
            for n in self._input_names}

        with mesh:
            self.params = {n: jax.device_put(host_params[n],
                                             self._param_sharding[n])
                           for n in self._param_names}
            self.aux = {n: jax.device_put(host_aux[n],
                                          self._aux_sharding[n])
                        for n in self._aux_names}
            self.momentum_state = {
                n: jax.device_put(np.zeros_like(host_params[n]),
                                  self._param_sharding[n])
                for n in self._param_names}

        self._step_fn = self._build_step()
        self._fwd_fn = None
        self._step_count = 0
        self._key = jax.random.PRNGKey(seed)

    # ------------------------------------------------------------ builders
    def _node_value_map(self, params, batch, aux):
        vals = {}
        for node in self._arg_nodes:
            if node.name in params:
                vals[id(node)] = params[node.name]
            else:
                vals[id(node)] = batch[node.name]
        for node in self._aux_nodes:
            vals[id(node)] = aux[node.name]
        return vals

    def _build_step(self):
        import jax
        import jax.numpy as jnp

        topo, entries = self._topo, self.symbol._entries
        head_is_loss = [bool(n.op is not None and n.op.is_loss)
                        for (n, _i) in entries]
        lr, mom, wd, rescale = self.lr, self.momentum, self.wd, self._rescale

        def step(params, mom_state, aux, batch, key):
            bsz = next(iter(batch.values())).shape[0]

            def fwd(p):
                var_values = self._node_value_map(p, batch, aux)
                heads, aux_upd = eval_graph(topo, entries, var_values,
                                            is_train=True, key=key,
                                            batch_size=bsz)
                return heads, aux_upd

            heads, vjp, aux_upd = jax.vjp(fwd, params, has_aux=True)
            cot = [jnp.ones_like(h) if il else jnp.zeros_like(h)
                   for h, il in zip(heads, head_is_loss)]
            (grads,) = vjp(list(cot))

            new_params, new_mom = {}, {}
            for k, w in params.items():
                g = grads[k].astype(jnp.float32) * rescale + \
                    wd * w.astype(jnp.float32)
                m = mom * mom_state[k].astype(jnp.float32) - lr * g
                new_mom[k] = m.astype(w.dtype)
                new_params[k] = (w.astype(jnp.float32) + m).astype(w.dtype)

            new_aux = {}
            aux_by_id = {id(n): n.name for n in self._aux_nodes}
            for n in self._aux_nodes:
                new_aux[n.name] = aux_upd.get(id(n), aux[n.name])

            # monitoring loss: mean -log p(label) from the softmax head
            loss = jnp.float32(0)
            label = None
            for nm in self._input_names:
                if "label" in nm:
                    label = batch[nm]
            if label is not None and head_is_loss[0]:
                probs = heads[0]
                if probs.ndim == 2 and label.ndim == 1:
                    idx = label.astype(jnp.int32)
                    p = probs[jnp.arange(probs.shape[0]), idx]
                    loss = -jnp.mean(jnp.log(jnp.maximum(p, 1e-10)))
            return new_params, new_mom, new_aux, loss

        from jax.sharding import NamedSharding, PartitionSpec as P
        in_shardings = (self._param_sharding, self._param_sharding,
                        self._aux_sharding, self._batch_sharding, None)
        out_shardings = (self._param_sharding, self._param_sharding,
                         self._aux_sharding, None)
        return jax.jit(step, in_shardings=in_shardings,
                       out_shardings=out_shardings,
                       donate_argnums=(0, 1))

    # ------------------------------------------------------------------ api
    def _cast_batch(self, batch):
        """Data inputs follow the compute dtype (bf16 training); labels
        keep their own dtype."""
        out = {}
        for k, v in batch.items():
            v = np.asarray(v)
            if "label" not in k and v.dtype.kind == "f":
                v = v.astype(self.dtype)
            out[k] = v
        return out

    def put_batch(self, batch):
        """Stage a host batch onto the mesh (sharded device arrays).
        Use with :meth:`step` to overlap host IO with compute, or to
        reuse a batch without re-transfer."""
        import jax
        return {k: jax.device_put(v, self._batch_sharding[k])
                for k, v in self._cast_batch(batch).items()}

    def step(self, batch):
        """One fused training step.  ``batch``: dict name -> host array
        with GLOBAL batch dim (or a dict from :meth:`put_batch`).
        Returns the (device) loss scalar."""
        import jax
        self._key, sub = jax.random.split(self._key)
        first = next(iter(batch.values()))
        if isinstance(first, jax.Array):
            dev_batch = batch
        else:
            dev_batch = self.put_batch(batch)
        self.params, self.momentum_state, self.aux, loss = self._step_fn(
            self.params, self.momentum_state, self.aux, dev_batch, sub)
        self._step_count += 1
        return loss

    def forward(self, batch, is_train=False):
        """Jitted inference forward returning head arrays."""
        import jax
        if self._fwd_fn is None:
            topo, entries = self._topo, self.symbol._entries

            def fwd(params, aux, batch):
                var_values = self._node_value_map(params, batch, aux)
                heads, _ = eval_graph(topo, entries, var_values,
                                      is_train=False, key=None,
                                      batch_size=next(
                                          iter(batch.values())).shape[0])
                return heads
            self._fwd_fn = jax.jit(fwd, in_shardings=(
                self._param_sharding, self._aux_sharding,
                self._batch_sharding))
        dev_batch = {k: jax.device_put(v, self._batch_sharding[k])
                     for k, v in self._cast_batch(batch).items()}
        return self._fwd_fn(self.params, self.aux, dev_batch)


class _HostArray:
    """Minimal NDArray-like shim so Initializers can write numpy in-place."""

    def __init__(self, data):
        self.data = data

    @property
    def shape(self):
        return self.data.shape

    @property
    def dtype(self):
        return self.data.dtype

    def __setitem__(self, key, value):
        self.data[key] = np.asarray(value)

    def __getitem__(self, key):
        return self.data[key]
